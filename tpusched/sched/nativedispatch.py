"""Native batched dispatch inner loop (ISSUE 16).

The sharded core (ROADMAP item 1, ISSUEs 12-14) made every per-cycle cost
O(Δ) — except the dispatch inner loop itself: the per-node Filter sweep and
the Score pass are pure Python, so N shard lanes time-slice one interpreter
and lane concurrency tops out at ~1.5-2x (doc/performance.md).  This module
packs a cycle's candidate set into flat int64 matrices and evaluates the
whole Filter→Score sweep in ONE ctypes call into the native torus engine
(tpusched_dispatch_eval) — ctypes releases the GIL for the call, so lanes
finally overlap inside the hot loop.  Python is re-entered only for the
final argmax name tie-break and the guarded commit
(Cache.assume_pod_guarded stays the authoritative compare-and-reserve).

Exactness contract — the kernel must be BIT-IDENTICAL to the pure-Python
path, which stays on as the oracle:

- Coverage is opt-out, not best-effort: ``attempt`` declines (returns None,
  counted per reason in tpusched_native_dispatch_fallbacks_total) whenever
  the cycle's semantics are not provably replicated — unknown/unskipped
  plugins, nominated pods, non-canonical pod shapes (node name/selector,
  tolerations, fractional TPU memory, exotic resources), live freed-window
  claims, non-integer resource values, non-inline lanes (the thread-pool
  sweep's feasible set is nondeterministic by contract), and zero-feasible
  outcomes (the Python path re-runs to produce byte-identical diagnosis).
- The visit order replicates Parallelizer.until's inline contract: rotate
  from ctx.next_start_node_index, stop checked BEFORE each visit once
  ``want`` feasible nodes are found, and the rotation advance is
  (start + max(visited, 1)) % n — exactly _find_feasible's bookkeeping.
- Scoring replicates run_score_plugins for the covered plugin set:
  TpuSlice raw = free chips, default-normalized over the feasible set
  (reverse ⇔ binpack), TopologyMatch's weighted constraint/strategy blend
  (computed in C with -ffp-contract=off so the float math matches CPython),
  each times its profile weight; argmax ties break on the
  lexicographically-last node name, in Python, like _select_host.
- A sampled in-cycle differential (native_dispatch_differential_period /
  TPUSCHED_NATIVE_DIFFERENTIAL) re-runs the pure-Python sweep with the same
  rotation start and asserts the identical placement; a mismatch counts
  tpusched_native_dispatch_differential_mismatches_total, logs, and uses
  the ORACLE's answer for that cycle.

Candidate packing amortizes like the pooled snapshots it reads (ISSUE 13):
per-(pool, cursor) blocks are packed once and reused by reference until the
pool's cursor moves, so a quiet pool costs nothing and a bind re-packs one
pool, not the partition.  Gang cycles (restricted node sets from
TopologyMatch's window stash) pack ad hoc per cycle — the stash already
collapsed the candidate set to window survivors.
"""
from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Tuple

from .. import native
from ..api.core import Pod, node_health_error
from ..api.resources import CPU, MEMORY, PODS, TPU
from ..fwk import CycleState, Status
from ..util import klog, tracectx
from ..util.metrics import (native_dispatch_cycles_total,
                            native_dispatch_differential_mismatches,
                            native_dispatch_fallbacks,
                            native_dispatch_pods_total)

# One row per candidate node, int64 each — mirrored by kDispatchFields in
# native/torus_engine.cc (keep in lockstep).
DISPATCH_FIELDS = 13
_FLAG_HEALTHY = 1
_FLAG_HARD_TAINT = 2

# Filter plugins whose semantics the kernel replicates; any OTHER filter in
# the profile must be per-cycle skipped (PreFilter Skip) or the cycle falls
# back.  NodeResourcesFit is covered through its batch semantics (the
# kernel's fit pass IS filter_batch's fused loop).
_COVERED_FILTERS = frozenset({
    "TopologyMatch", "NodeUnschedulable", "NodeName", "NodeSelector",
    "TaintToleration", "NodeResourcesFit", "TpuSlice",
})
_COVERED_SCORERS = frozenset({"TpuSlice", "TopologyMatch"})
_STRATEGY_CODES = {"LeastAllocated": 0, "MostAllocated": 1,
                   "BalancedAllocation": 2}

# CycleState keys shared with the plugins (framework-level contract: the
# scheduler core reads them by name, like QUOTA_GUARD_STATE_KEY).
_TOPO_STATE_KEY = "TopologyMatch/state"
_TOPO_CLAIMS_KEY = "TopologyMatch/claimed-hosts"
_FIT_REQ_KEY = "NodeResourcesFit/pod-request"

_CANONICAL = (CPU, MEMORY, PODS, TPU)


class _ProfileSupport:
    """Once-per-scheduler verdict: can this profile's Filter/Score plugin
    wiring be replicated by the kernel at all, and with which parameters."""

    __slots__ = ("ok", "skip_needed", "score_skip_needed", "w_tpu", "w_topo",
                 "reverse_tpu", "strategy", "packing_weight")

    def __init__(self, fw) -> None:
        self.ok = False
        self.skip_needed: frozenset = frozenset()
        self.score_skip_needed: frozenset = frozenset()
        self.w_tpu = 0
        self.w_topo = 0
        self.reverse_tpu = False
        self.strategy = 0
        self.packing_weight = 0.7
        filter_names = {p.name() for p in fw.filter_plugins}
        batch_names = {p.name() for p in fw.batch_filter_plugins}
        if not batch_names <= {"NodeResourcesFit"}:
            return
        self.skip_needed = frozenset(filter_names - _COVERED_FILTERS)
        score_extra = set()
        for plugin, weight in fw.score_plugins:
            name = plugin.name()
            if name == "TpuSlice":
                self.w_tpu = weight
                self.reverse_tpu = plugin.args.score_mode == "binpack"
            elif name == "TopologyMatch":
                self.w_topo = weight
                strategy = _STRATEGY_CODES.get(plugin.args.scoring_strategy)
                if strategy is None:
                    return
                self.strategy = strategy
                self.packing_weight = plugin.args.packing_weight
            else:
                score_extra.add(name)
        self.score_skip_needed = frozenset(score_extra)
        # pre-score plugins run for real in _select (same as _select_host),
        # so they need no coverage here — only their SCORE methods must end
        # up skipped, which _select re-checks per cycle after PreScore.
        self.ok = True


class _Block:
    """One pool's packed candidate matrix, valid while the pool's cursor
    (and the identity of its shared per-pool NodeInfo list) is unchanged."""

    __slots__ = ("cursor", "list_id", "n", "buf", "infos")

    def __init__(self, cursor: int, list_id: int, n: int, buf, infos) -> None:
        self.cursor = cursor
        self.list_id = list_id
        self.n = n
        self.buf = buf
        self.infos = infos      # the shared (read-only) per-pool list


class _Arena:
    """Per-lane scratch: pool blocks + reusable kernel in/out buffers.
    Lane-confined (lives on _LaneContext), so no locking."""

    __slots__ = ("blocks", "req_buf", "out_cap", "out_feasible", "out_raw",
                 "out_topo", "out_visited", "differential_tick")

    def __init__(self) -> None:
        self.blocks: Dict[str, _Block] = {}
        self.req_buf = (ctypes.c_int64 * 4)()
        self.out_cap = 0
        self.out_feasible = None
        self.out_raw = None
        self.out_topo = None
        self.out_visited = (ctypes.c_int64 * 1)()
        self.differential_tick = 0

    def ensure_out(self, want: int) -> None:
        if want > self.out_cap:
            cap = max(want, 128)
            self.out_cap = cap
            self.out_feasible = (ctypes.c_int64 * cap)()
            self.out_raw = (ctypes.c_int64 * cap)()
            self.out_topo = (ctypes.c_int64 * cap)()


def pack_rows(infos) -> List[int]:
    """The pod-independent per-node dispatch facts, row-major — the single
    definition both the arena packer and the parity tests use.  Raises
    (TypeError/OverflowError via the ctypes copy downstream, ValueError
    here) on rows the kernel cannot represent exactly."""
    from ..plugins.tpuslice.chip_node import ChipNode
    vals: List[int] = []
    for info in infos:
        node = info.node
        alloc = info.allocatable
        req = info.requested
        flags = _FLAG_HEALTHY if node_health_error(node) is None else 0
        for t in node.spec.taints:
            if t.effect in ("NoSchedule", "NoExecute"):
                flags |= _FLAG_HARD_TAINT
                break
        cn = ChipNode.cached(info)
        if cn is None:
            ucl = uml = hbm = free = 0
        else:
            ucl = cn.used_chips_limit
            uml = cn.used_mem_limit
            hbm = cn.hbm_total_mb
            free = len(cn.free_chip_indexes())
        row = (alloc.get(CPU, 0), alloc.get(MEMORY, 0), alloc.get(PODS, 0),
               alloc.get(TPU, 0), req.get(CPU, 0), req.get(MEMORY, 0),
               req.get(PODS, 0), req.get(TPU, 0), ucl, uml, hbm, free, flags)
        for v in row:
            # bool is an int; exact floats (test fixtures) are NOT packable
            if type(v) is not int and not isinstance(v, bool):
                raise ValueError(f"non-integer dispatch fact {v!r} "
                                 f"on node {node.name}")
        vals.extend(row)
    return vals


def py_dispatch_eval(rows: List[int], req, chips_set: bool, chips_req: int,
                     start: int, want: int, membership=None, pool_util=None,
                     max_membership: int = 1, strategy: int = 0,
                     packing_weight: float = 0.7):
    """Pure-Python mirror of tpusched_dispatch_eval over one packed row
    matrix — the parity-suite oracle for the kernel itself (the scheduler's
    oracle is the real plugin path).  Returns (feasible, raws, topos,
    visited)."""
    n = len(rows) // DISPATCH_FIELDS
    feasible: List[int] = []
    raws: List[int] = []
    topos: List[int] = []
    visited = 0
    for idx in range(n):
        if len(feasible) >= want:
            break
        oi = (start + idx) % n
        r = rows[oi * DISPATCH_FIELDS:(oi + 1) * DISPATCH_FIELDS]
        visited += 1
        flags = r[12]
        if not flags & _FLAG_HEALTHY:
            continue
        if flags & _FLAG_HARD_TAINT:
            continue
        if any(req[k] > 0 and r[4 + k] + req[k] > r[k] for k in range(4)):
            continue
        if chips_set:
            if r[3] <= 0 or r[8] + chips_req > r[3] or r[9] > r[10] \
                    or r[11] < chips_req:
                continue
        if membership is not None and membership[oi] <= 0:
            continue
        feasible.append(oi)
        raws.append(r[11] if (chips_set and r[3] > 0) else 0)
        if membership is not None:
            maxm = max(1, max_membership)
            constraint = 100 * (max_membership - membership[oi]) // maxm
            util = pool_util[oi]
            if strategy == 1:
                strat = int(util * 100.0)
            elif strategy == 2:
                strat = int((1.0 - abs(util - 0.5) * 2.0) * 100.0)
            else:
                strat = int((1.0 - util) * 100.0)
            topos.append(int(constraint * packing_weight
                             + strat * (1.0 - packing_weight)))
        else:
            topos.append(0)
    return feasible, raws, topos, visited


def combine_scores(raws: List[int], topos: List[int], w_tpu: int,
                   w_topo: int, reverse_tpu: bool) -> List[int]:
    """run_score_plugins' totals for the covered plugin pair: TpuSlice
    default-normalize over the feasible set (reverse ⇔ binpack), then the
    weighted sum.  Shared by the dispatch path and the parity tests."""
    max_raw = max(raws, default=0)
    totals = []
    for raw, topo in zip(raws, topos):
        s = raw * 100 // max_raw if max_raw > 0 else raw
        if reverse_tpu:
            s = 100 - s
        totals.append(s * w_tpu + topo * w_topo)
    return totals


class NativeDispatch:
    """The scheduler-side driver.  One instance per Scheduler; all mutable
    per-lane state lives on the lane context's arena."""

    def __init__(self, scheduler) -> None:
        self._sched = scheduler
        self._support: Optional[_ProfileSupport] = None
        self._lib_checked = False
        self._lib = None
        period = os.environ.get("TPUSCHED_NATIVE_DIFFERENTIAL")
        if period is not None:
            self.differential_period = int(period)
        else:
            self.differential_period = getattr(
                scheduler.profile, "native_dispatch_differential_period", 0)

    # -- plumbing -------------------------------------------------------------

    def _lib_or_none(self):
        if not self._lib_checked:
            self._lib = native.load()
            self._lib_checked = True
        return self._lib

    def _profile_support(self) -> _ProfileSupport:
        if self._support is None:
            self._support = _ProfileSupport(self._sched._fw)
        return self._support

    @staticmethod
    def _decline(reason: str) -> None:
        native_dispatch_fallbacks.with_labels(reason).inc()
        return None

    # -- the per-cycle entry point -------------------------------------------

    def attempt(self, state: CycleState, pod: Pod, snapshot, infos,
                want: int, ctx, restricted: bool, view=None
                ) -> Optional[Tuple[str, Status]]:
        """Evaluate this cycle natively if every semantic is covered.
        Returns (node_name, status) to use as the cycle's Filter+Score
        outcome — with ctx's rotation advanced exactly as _find_feasible
        would — or None to run the pure-Python path (ctx untouched)."""
        if not ctx.pools_scoped:
            # the thread-pool sweep's feasible set is nondeterministic by
            # contract; only the inline (lane-is-the-parallelism) sweep is
            # replicable bit-for-bit
            return self._decline("lane")
        lib = self._lib_or_none()
        if lib is None:
            return self._decline("no-native")
        sup = self._profile_support()
        if not sup.ok:
            return self._decline("profile")
        if not self._sched.handle.pod_nominator.empty():
            return self._decline("nominated")
        if not sup.skip_needed <= state.skip_filter_plugins:
            return self._decline("plugin-active")
        stash = state.try_read(_TOPO_STATE_KEY)
        if stash is None and state.try_read(_TOPO_CLAIMS_KEY):
            return self._decline("claims")
        spec = pod.spec
        if spec.node_name or spec.node_selector or spec.tolerations:
            return self._decline("pod-shape")
        from ..plugins.tpuslice.chip_node import pod_tpu_limits
        chips_req, chips_set, _, mem_set = pod_tpu_limits(pod)
        if mem_set:
            return self._decline("pod-shape")

        def build_request():
            from ..util.podutil import pod_effective_request
            req = pod_effective_request(pod)
            req["pods"] = 1
            return tuple((k, v) for k, v in req.items() if v > 0)

        request = state.read_or_init(_FIT_REQ_KEY, build_request)
        req_map = dict(request)
        if any(k not in _CANONICAL for k in req_map):
            return self._decline("pod-shape")

        arena = ctx.native_arena
        if arena is None:
            arena = ctx.native_arena = _Arena()
        n = len(infos)
        start = ctx.next_start_node_index % n

        try:
            if restricted or stash is not None \
                    or getattr(snapshot, "pool_segments", None) is None:
                # gang/restricted cycles: the candidate set is already the
                # PreFilter-narrowed survivor list (small), packed ad hoc
                packed = self._pack_adhoc(arena, infos, stash)
            else:
                packed = self._pack_pooled(arena, snapshot, n)
        except (ValueError, TypeError, OverflowError):
            return self._decline("pack-error")
        if packed is None:
            return self._decline("pack-error")
        block_ptrs, block_lens, nblocks, keepalive, memb_arr, util_arr, \
            maxm = packed

        arena.ensure_out(want)
        req_buf = arena.req_buf
        for k, res in enumerate(_CANONICAL):
            v = req_map.get(res, 0)
            if type(v) is not int:
                return self._decline("pod-shape")
            req_buf[k] = v

        prev = tracectx.set_plugin("native:dispatch")
        try:
            nf = lib.tpusched_dispatch_eval(
                block_ptrs, block_lens, nblocks, req_buf,
                1 if chips_set else 0, chips_req, start, want,
                memb_arr, util_arr, maxm, sup.strategy,
                sup.packing_weight, 0,
                arena.out_feasible, arena.out_raw, arena.out_topo,
                arena.out_visited)
        finally:
            tracectx.set_plugin(prev)
        native_dispatch_cycles_total.inc()
        visited = arena.out_visited[0]
        if nf <= 0:
            # zero feasible: the Python path re-runs for byte-identical
            # diagnosis aggregation (failures are off the throughput path)
            return self._decline("no-feasible")

        del keepalive  # buffers only needed alive through the kernel call
        advance = (start + max(visited, 1)) % n
        feasible_nodes = [infos[i].node for i in arena.out_feasible[:nf]]
        raws = list(arena.out_raw[:nf])
        topos = list(arena.out_topo[:nf])

        # snapshot the data map BEFORE Score-phase writes, exactly like
        # _schedule_full: the entry the offer below arms may hold
        # PreFilter/Filter state only
        prefilter_export = None
        if ctx.equiv_cache is not None:
            from .scheduler import _EQUIV_EXCLUDE_KEYS
            prefilter_export = state.export(exclude=_EQUIV_EXCLUDE_KEYS)

        result = self._select(state, pod, feasible_nodes, raws, topos, sup)
        if result is None:
            return self._decline("prescore")
        node_name, status = result

        mismatch = False
        if self.differential_period > 0:
            arena.differential_tick += 1
            if arena.differential_tick >= self.differential_period:
                arena.differential_tick = 0
                oracle = self._differential(state, pod, infos, want, start,
                                            node_name, status)
                if oracle is not None:
                    mismatch = True
                    node_name, status, advance = oracle
        ctx.next_start_node_index = advance
        state.write("tpusched/diagnosis", {})
        if status.is_success():
            native_dispatch_pods_total.inc()
            if not mismatch:
                # arm the equivalence cache exactly as the Python full path
                # would — gang siblings depend on this fast path (a
                # complete sweep is required; the sampled big-partition
                # sweep keeps swept_all False, same as _schedule_full)
                self._sched._equiv_offer(pod, state, feasible_nodes,
                                         swept_all=want >= n,
                                         prefilter_data=prefilter_export,
                                         ctx=ctx, view=view)
        return node_name, status

    # -- packing --------------------------------------------------------------

    def _pack_pooled(self, arena: _Arena, snapshot, n: int):
        """Per-(pool, cursor) cached blocks over the pooled snapshot's
        shared per-pool lists, concatenated in candidate-sequence order
        (PoolChain order == pool_segments order, so the kernel's global
        index maps straight back through ``infos[gi]``)."""
        segments = snapshot.pool_segments()
        if segments is None:
            return None
        cursors = snapshot.pool_cursors
        blocks: List[_Block] = []
        total = 0
        for pool, lst in segments:
            cursor = cursors.get(pool, -1)
            blk = arena.blocks.get(pool)
            if blk is None or blk.cursor != cursor \
                    or blk.list_id != id(lst) or blk.n != len(lst):
                vals = pack_rows(lst)
                buf = (ctypes.c_int64 * max(1, len(vals)))(*vals)
                blk = _Block(cursor, id(lst), len(lst), buf, lst)
                arena.blocks[pool] = blk
            blocks.append(blk)
            total += blk.n
        if total != n:
            return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        nblocks = len(blocks)
        block_ptrs = (i64p * max(1, nblocks))(
            *[ctypes.cast(b.buf, i64p) for b in blocks])
        block_lens = (ctypes.c_int64 * max(1, nblocks))(
            *[b.n for b in blocks])
        return block_ptrs, block_lens, nblocks, blocks, None, None, 1

    def _pack_adhoc(self, arena: _Arena, infos, stash):
        """Per-cycle single-block pack for restricted (gang) candidate sets
        and non-pooled snapshots; carries the gang stash columns."""
        infos = list(infos)
        vals = pack_rows(infos)
        buf = (ctypes.c_int64 * max(1, len(vals)))(*vals)
        i64p = ctypes.POINTER(ctypes.c_int64)
        block_ptrs = (i64p * 1)(ctypes.cast(buf, i64p))
        block_lens = (ctypes.c_int64 * 1)(len(infos))
        memb_arr = util_arr = None
        maxm = 1
        if stash is not None:
            n = len(infos)
            memb_arr = (ctypes.c_int64 * max(1, n))()
            util_arr = (ctypes.c_double * max(1, n))()
            for i, info in enumerate(infos):
                ent = stash.allowed.get(info.node.name)
                if ent is None:
                    memb_arr[i] = -1
                else:
                    memb_arr[i] = ent[1]
                    util_arr[i] = ent[2]
            maxm = stash.max_membership
        return block_ptrs, block_lens, 1, buf, memb_arr, util_arr, maxm

    # -- selection ------------------------------------------------------------

    def _select(self, state: CycleState, pod: Pod, feasible_nodes, raws,
                topos, sup: _ProfileSupport):
        """_select_host's semantics over the kernel outputs.  Returns
        (node, status), or None to fall back (pre-score anomaly)."""
        if len(feasible_nodes) == 1:
            return feasible_nodes[0].name, Status.success()
        s = self._sched._timed_point("PreScore",
                                     self._sched._fw.run_pre_score_plugins,
                                     state, pod, feasible_nodes)
        if not s.is_success():
            return "", s
        if sup.score_skip_needed - state.skip_score_plugins:
            # a scorer the kernel cannot replicate would actually run
            return None
        totals = combine_scores(raws, topos, sup.w_tpu, sup.w_topo,
                                sup.reverse_tpu)
        best = max(zip(totals, (n.name for n in feasible_nodes)))
        return best[1], Status.success()

    # -- sampled in-cycle oracle ----------------------------------------------

    def _differential(self, state: CycleState, pod: Pod, infos, want: int,
                      start: int, node_name: str, status: Status):
        """Re-run the pure-Python sweep with the same rotation start and
        compare placements.  On mismatch: count, log, and return the
        oracle's (node, status, advance) — correctness wins over speed."""
        sched = self._sched

        class _ShimCtx:
            next_start_node_index = start

        shim = _ShimCtx()
        feasible, _diag, error = sched._find_feasible(
            state, pod, infos, want, shim)
        if error is not None:
            o_node, o_status = "", error
        elif not feasible:
            o_node, o_status = "", Status.unschedulable("0 nodes (oracle)")
        else:
            o_node, o_status = sched._select_host(state, pod, feasible)
        if o_node == node_name and o_status.is_success() \
                == status.is_success():
            return None
        native_dispatch_differential_mismatches.inc()
        klog.error_s(None, "native dispatch differential mismatch",
                     pod=pod.key, native=node_name or "<none>",
                     oracle=o_node or "<none>")
        return o_node, o_status, shim.next_start_node_index
