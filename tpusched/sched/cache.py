"""Scheduler cache: authoritative in-process view of nodes + pods, with
assume/confirm/expire semantics so concurrent cycles see in-flight decisions.

Rebuild of upstream internal/cache as the reference's hot loop depends on it
(snapshot at cycle start, SURVEY §3.2 "assume pod in cache"). NodeInfos are
maintained incrementally on every event (upstream's design) so snapshot() is
a cheap per-node clone, not a rebuild. Assumed pods expire if the bind is
never confirmed by the API server (watch event), which keeps the scheduler
restart-safe with annotations-as-truth (SURVEY §5 checkpoint/resume).

Sharded dispatch additions (ROADMAP item 1): every structural mutation is
attributed to the POOL it touched (``tpu.dev/pool`` of the node involved)
and bumps a per-pool cursor alongside the global one.  A shard's dispatch
cycle captures its partition's pool-cursor tuple atomically with the
snapshot it filters against (``snapshot_view``), and commits its placement
through the optimistic ``assume_pod_guarded`` compare-and-assume: the
assume lands only if the chosen pool's cursor is still the one the cycle's
filters read — a foreign mutation in that pool (an informer event, a
global-lane bind) fails the compare and the shard retries on fresh state
instead of binding a stale placement.  Mutations in OTHER pools do not
conflict: that independence is the whole point of partitioning dispatch by
pool.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.core import Node, Pod
from ..api.scheduling import POD_GROUP_LABEL
from ..api.topology import LABEL_POOL
from ..fwk.nodeinfo import NodeInfo, Snapshot
from ..util import klog
from ..util.locking import GuardedLock, guarded_by

ASSUME_EXPIRATION_S = 30.0


def pool_of_node(node: Node) -> str:
    """The pool a node's mutations are attributed to.  Unpooled nodes
    (no ``tpu.dev/pool`` label) share the '' pool — they conflict with
    each other and with every cycle that places onto unpooled hardware,
    which is exactly the conservative behavior they need."""
    return node.meta.labels.get(LABEL_POOL, "")


class CacheView:
    """One cycle's atomically-captured view: the snapshot its filters read,
    the global cursor that snapshot was built at, and the per-pool cursors
    at the same instant (restricted to the cycle's partition when one was
    given — the equivalence-cache validity witness for shard lanes)."""

    __slots__ = ("snapshot", "cursor", "pool_cursors")

    def __init__(self, snapshot: Snapshot, cursor: int,
                 pool_cursors: Dict[str, int]):
        self.snapshot = snapshot
        self.cursor = cursor
        self.pool_cursors = pool_cursors

    def cursor_tuple(self) -> Tuple[Tuple[str, int], ...]:
        """Canonical (sorted) form for equivalence-entry validity."""
        return tuple(sorted(self.pool_cursors.items()))


@guarded_by("_lock", "_infos", "_pods", "_assumed", "_node_clones",
            "_pg_assigned", "_mutation", "_snap_mutation", "_last_snapshot",
            "_pool_mutation", "_pool_nodes", "_pool_members", "_part_snaps",
            "_windex")
class Cache:
    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = GuardedLock("sched.Cache")
        self._infos: Dict[str, NodeInfo] = {}       # node name → live NodeInfo
        self._pods: Dict[str, Pod] = {}             # all known scheduled pods
        self._assumed: Dict[str, float] = {}        # pod key → bind deadline
        # earliest finite assume deadline (inf = none armed): the expiry
        # sweep is O(1) until something can actually expire — every
        # snapshot/view used to scan the whole assume table, which under
        # N concurrent dispatch lanes turned the cache lock into the
        # process hot spot and stalled informer ingestion behind it
        self._next_expiry = float("inf")
        # per-node snapshot clones keyed by generation — upstream's
        # UpdateSnapshot design: only nodes that changed re-clone.  Shared
        # by the full snapshot AND every partition snapshot (a node's
        # read-only clone is the same object in both), pruned on node
        # removal.
        self._node_clones: Dict[str, Tuple[int, NodeInfo]] = {}
        # gang full-name → members attached to a cached node (the Permit
        # quorum input), maintained incrementally at attach/detach so
        # assigned_count never walks the fleet (O(1) per cycle at any scale)
        self._pg_assigned: Dict[str, int] = {}
        # global change cursor: bumped by every structural mutation so an
        # unchanged cache returns the PREVIOUS Snapshot object outright —
        # back-to-back cycles over a quiet fleet otherwise rebuild two
        # O(nodes) dicts each (in-place pod mutations after assume stay
        # visible without a bump: snapshots share the pod objects)
        self._mutation = 0
        self._snap_mutation = -1
        self._last_snapshot: "Snapshot | None" = None
        # per-pool change cursors (sharded dispatch): every structural
        # mutation bumps the cursor of the pool it touched in the same
        # critical section as the global bump, so a partition's cursor
        # tuple is an exact witness of "nothing in MY pools changed"
        self._pool_mutation: Dict[str, int] = {}
        # pool → live node count (pools() without an O(nodes) walk)
        self._pool_nodes: Dict[str, int] = {}
        # bumped only when the pool SET changes (first node of a pool
        # arrives / last one leaves).  Read LOCK-FREE by dispatch lanes
        # (GIL-atomic int) to decide whether their partition needs a
        # recompute: a per-cycle pools() call under the cache lock from N
        # lanes was, measurably, the process's hottest contention point.
        self.pools_version = 0
        # pool → live node-name set: the partition snapshot builder's
        # iteration domain (a shard rebuilds its view from ITS pools'
        # nodes only, never walking the fleet)
        self._pool_members: Dict[str, Dict[str, None]] = {}
        # partition-snapshot cache: partition (pool tuple) → (the pool-
        # cursor tuple it was built at, Snapshot).  A shard's epoch view
        # is rebuilt only when ITS pools mutated — cross-shard traffic
        # leaves it untouched, which is what keeps N concurrent lanes from
        # re-cloning the fleet on every foreign assume (the copy-on-write
        # epoch design of ROADMAP item 1).
        self._part_snaps: Dict[Tuple[str, ...], Tuple[Tuple, Snapshot]] = {}
        # incremental torus window index (topology/windowindex.py, ISSUE
        # 13): every structural mutation below feeds the index its
        # occupancy delta IN THE SAME critical section as the cursor bump,
        # so a plane whose version equals a snapshot's pool cursor is an
        # exact witness of identical occupancy.  None = no index attached
        # (TPUSCHED_NO_WINDOW_INDEX, or the index self-detached on error).
        self._windex = None

    def _bump_locked(self, pool: str) -> int:
        self._mutation += 1
        cursor = self._pool_mutation.get(pool, 0) + 1
        self._pool_mutation[pool] = cursor
        return cursor

    def _pool_member_locked(self, pool: str, name: str, delta: int) -> None:
        if delta > 0:
            n = self._pool_nodes.get(pool, 0)
            if n == 0:
                self.pools_version += 1      # a pool was born
            self._pool_nodes[pool] = n + 1
            self._pool_members.setdefault(pool, {})[name] = None
            return
        n = self._pool_nodes.get(pool, 0) - 1
        if n <= 0:
            self._pool_nodes.pop(pool, None)
            self.pools_version += 1          # a pool emptied out
        else:
            self._pool_nodes[pool] = n
        members = self._pool_members.get(pool)
        if members is not None:
            members.pop(name, None)
            if not members:
                self._pool_members.pop(pool, None)

    # -- window index plumbing ------------------------------------------------

    def attach_window_index(self, idx) -> None:
        """Attach (or replace) the torus window index and seed it from the
        CURRENT cache state + per-pool cursors in one critical section."""
        with self._lock:
            self._windex = idx
            if idx is None:
                return
            try:
                idx.cache_reset()
                for info in self._infos.values():
                    idx.cache_seed_node(info.node, info.pods)
                idx.rebuild_stale(
                    lambda p: self._pool_mutation.get(p, 0))
            except Exception as e:  # noqa: BLE001 — the index is an
                # accelerator: on ANY maintenance failure detach it and let
                # every consumer fall back to the Python recompute path
                klog.error_s(e, "window index attach failed; detaching")
                self._windex = None

    def window_index(self):
        with self._lock:
            return self._windex

    def sync_window_index(self) -> None:
        """Rebuild any stale index pools (topology CR change, differential
        self-heal) atomically with their pool cursors."""
        with self._lock:
            idx = self._windex
            if idx is None or not idx.stale_pools():
                return
            try:
                idx.rebuild_stale(lambda p: self._pool_mutation.get(p, 0))
            except Exception as e:  # noqa: BLE001 — see attach_window_index
                klog.error_s(e, "window index rebuild failed; detaching")
                self._windex = None

    def _windex_call_locked(self, method: str, *args) -> None:
        idx = self._windex
        if idx is None:
            return
        try:
            getattr(idx, method)(*args)
        except Exception as e:  # noqa: BLE001 — see attach_window_index
            klog.error_s(e, "window index update failed; detaching",
                         hook=method)
            self._windex = None

    def _pg_adjust_locked(self, pod: Pod, delta: int) -> None:
        name = pod.meta.labels.get(POD_GROUP_LABEL)
        if not name or not pod.spec.node_name:
            return
        key = f"{pod.meta.namespace}/{name}"
        n = self._pg_assigned.get(key, 0) + delta
        if n <= 0:
            self._pg_assigned.pop(key, None)
        else:
            self._pg_assigned[key] = n

    # -- nodes ----------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            pool = pool_of_node(node)
            stamps = [(pool, self._bump_locked(pool))]
            old = self._infos.get(node.name)
            if old is not None:
                old_pool = pool_of_node(old.node)
                if old_pool != pool:
                    # a replacement that MOVED pools dirties both: shards
                    # on either side of the move must see the change
                    stamps.append((old_pool, self._bump_locked(old_pool)))
                    self._pool_member_locked(old_pool, node.name, -1)
                    self._pool_member_locked(pool, node.name, +1)
                for p in old.pods:
                    self._pg_adjust_locked(p, -1)
            else:
                self._pool_member_locked(pool, node.name, +1)
            info = NodeInfo(node)
            self._infos[node.name] = info
            # attach pods already known to live on this node
            attached = []
            for p in self._pods.values():
                if p.spec.node_name == node.name:
                    info.add_pod(p)
                    self._pg_adjust_locked(p, +1)
                    attached.append(p)
            self._windex_call_locked("cache_node_upsert", node, attached,
                                     stamps)

    def update_node(self, node: Node) -> None:
        with self._lock:
            info = self._infos.get(node.name)
            if info is None:
                self.add_node(node)
            else:
                pool = pool_of_node(node)
                old_pool = pool_of_node(info.node)
                stamps = [(pool, self._bump_locked(pool))]
                if old_pool != pool:
                    stamps.append((old_pool, self._bump_locked(old_pool)))
                    self._pool_member_locked(old_pool, node.name, -1)
                    self._pool_member_locked(pool, node.name, +1)
                info.set_node(node)
                self._windex_call_locked("cache_node_upsert", node, None,
                                         stamps)

    def remove_node(self, node: Node) -> list:
        """Drop a node AND reconcile the pod state attached to it — node
        removal with bound/assumed pods is a first-class event, not a blind
        pop.

        - pods stay in ``_pods`` (upstream RemoveNode semantics: the API
          server still holds bound pods, and a node-object replacement —
          remove+add of the same name — must re-attach them); quorum
          accounting is decremented with the NodeInfo;
        - assumed pods with a still-∞ deadline get their expiry TTL armed
          NOW: their bind targets hardware that no longer exists, and
          without this a bind whose confirmation can never arrive would
          leak the assume-table entry (and its quorum count on re-add)
          forever. The scheduler's ``_on_node_delete`` additionally rejects
          barrier-parked members on the vanished node, whose failure path
          forgets them promptly — the TTL is the backstop.

        Returns the pods that were attached so the caller can reject
        barrier-parked members and requeue the affected gangs."""
        with self._lock:
            info = self._infos.pop(node.name, None)
            if info is None:
                # cursor semantics unchanged: a no-op removal still reads
                # as a mutation of the named node's pool (callers observed
                # an event; shards re-validate cheaply)
                pool = pool_of_node(node)
                self._windex_call_locked("cache_note", pool,
                                         self._bump_locked(pool))
                return []
            pool = pool_of_node(info.node)
            cursor = self._bump_locked(pool)
            self._pool_member_locked(pool, node.name, -1)
            self._node_clones.pop(node.name, None)
            self._windex_call_locked("cache_node_removed", node.name,
                                     [(pool, cursor)])
            affected = list(info.pods)
            deadline = self._clock() + ASSUME_EXPIRATION_S
            for p in affected:
                self._pg_adjust_locked(p, -1)
                if self._assumed.get(p.key) == float("inf"):
                    self._assumed[p.key] = deadline
                    self._next_expiry = min(self._next_expiry, deadline)
            return affected


    # -- pods -----------------------------------------------------------------

    def _attach_locked(self, pod: Pod) -> None:
        info = self._infos.get(pod.spec.node_name)
        if info is not None:
            pool = pool_of_node(info.node)
            cursor = self._bump_locked(pool)
            info.add_pod(pod)
            self._pg_adjust_locked(pod, +1)
            self._windex_call_locked("cache_pod_delta", pod.spec.node_name,
                                     pod, 1, [(pool, cursor)])

    def _detach_locked(self, pod: Pod) -> None:
        info = self._infos.get(pod.spec.node_name)
        if info is not None and info.remove_pod(pod):
            pool = pool_of_node(info.node)
            cursor = self._bump_locked(pool)
            self._pg_adjust_locked(pod, -1)
            self._windex_call_locked("cache_pod_delta", pod.spec.node_name,
                                     pod, -1, [(pool, cursor)])

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        """Stores the caller's object by reference (upstream shares the pod
        pointer too): Reserve plugins mutate the assumed pod's annotations
        *after* assume, and snapshots must see those writes — the chip model
        is rebuilt from annotations (tpuslice/chip_node.py)."""
        with self._lock:
            self._assume_locked(pod, node_name)

    def _assume_locked(self, pod: Pod, node_name: str) -> None:
        # replace-don't-stack: an entry already cached under this key (a
        # watch confirm that raced in, or a re-assume) is detached first —
        # stacking a second attached copy would double-count the gang's
        # permit-quorum index (found by the cross-shard-gang-quorum
        # interleaving scenario)
        old = self._pods.get(pod.key)
        if old is not None:
            self._detach_locked(old)
        pod.spec.node_name = node_name
        self._pods[pod.key] = pod
        self._attach_locked(pod)
        self._assumed[pod.key] = float("inf")  # until finish_binding arms TTL

    def assume_pod_guarded(self, pod: Pod, node_name: str,
                           expected_pool_cursor: int,
                           pools: Optional[Sequence[str]] = None):
        """Optimistic compare-and-assume (sharded dispatch commit point):
        assume ``pod`` onto ``node_name`` iff the chosen node's POOL cursor
        still equals ``expected_pool_cursor`` — the value the calling
        cycle's snapshot_view captured when its filters read the state.

        Returns None (nothing assumed) when the pool saw a foreign
        mutation since, or when the node itself vanished: the caller must
        re-derive its placement on fresh state instead of committing a
        decision computed against a superseded epoch.  Per-node filter
        outcomes are monotone under foreign ASSUMES in other pools (they
        only consume resources elsewhere), so the compare is deliberately
        scoped to the one pool the placement touches — cross-pool traffic
        never serializes here.

        On success returns the post-assume cursor tuple of ``pools`` (the
        shard-scoped equivalence arming guard's input, read in the SAME
        critical section — a separate lock hop per cycle was measurable
        contention), or an empty tuple when ``pools`` is None."""
        with self._lock:
            info = self._infos.get(node_name)
            if info is None:
                return None
            pool = pool_of_node(info.node)
            if self._pool_mutation.get(pool, 0) != expected_pool_cursor:
                return None
            self._assume_locked(pod, node_name)
            if pools is None:
                return ()
            return tuple(sorted(
                (p, self._pool_mutation.get(p, 0)) for p in pools))

    def finish_binding(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._assumed:
                deadline = self._clock() + ASSUME_EXPIRATION_S
                self._assumed[pod.key] = deadline
                self._next_expiry = min(self._next_expiry, deadline)

    def forget_pod(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._assumed:
                self._assumed.pop(pod.key, None)
                old = self._pods.pop(pod.key, None)
                if old is not None:
                    self._detach_locked(old)

    def add_pod(self, pod: Pod) -> None:
        """Confirmed (bound) pod from the watch stream."""
        with self._lock:
            self._assumed.pop(pod.key, None)
            old = self._pods.get(pod.key)
            if old is not None:
                self._detach_locked(old)
            self._pods[pod.key] = pod
            self._attach_locked(pod)

    def update_pod(self, pod: Pod) -> None:
        self.add_pod(pod)

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop(pod.key, None)
            old = self._pods.pop(pod.key, None)
            if old is not None:
                self._detach_locked(old)

    def is_assumed(self, pod_key: str) -> bool:
        with self._lock:
            return pod_key in self._assumed

    def _cleanup_expired_locked(self) -> None:
        if self._next_expiry == float("inf") \
                or self._clock() < self._next_expiry:
            return                      # O(1) on the hot path
        now = self._clock()
        nxt = float("inf")
        for key, deadline in list(self._assumed.items()):
            if deadline < now:
                klog.warning_s("assumed pod expired without bind confirmation",
                               pod=key)
                self._assumed.pop(key, None)
                old = self._pods.pop(key, None)
                if old is not None:
                    self._detach_locked(old)
            else:
                nxt = min(nxt, deadline)
        self._next_expiry = nxt

    # -- snapshot -------------------------------------------------------------

    def _clone_of_locked(self, name: str, info: NodeInfo) -> NodeInfo:
        ent = self._node_clones.get(name)
        if ent is None or ent[0] != info.generation:
            ent = (info.generation, info.clone())
            self._node_clones[name] = ent
        return ent[1]

    def _snapshot_locked(self) -> Snapshot:
        """Incremental (upstream cache.UpdateSnapshot): a node's clone from
        the previous snapshot is reused while its generation is unchanged.
        Safe because snapshot NodeInfos are read-only by contract — every
        mutation path (preemption dry-runs, nominated-pod evaluation) clones
        first (sched/preemption.py:129-130, fwk/runtime.py:309-312)."""
        self._cleanup_expired_locked()
        if (self._mutation == self._snap_mutation
                and self._last_snapshot is not None):
            return self._last_snapshot
        infos = {name: self._clone_of_locked(name, info)
                 for name, info in self._infos.items()}
        snap = Snapshot.from_infos(infos, dict(self._pg_assigned))
        snap.pool_cursors = dict(self._pool_mutation)
        self._snap_mutation = self._mutation
        self._last_snapshot = snap
        return snap

    def snapshot(self) -> Snapshot:
        with self._lock:
            return self._snapshot_locked()

    def snapshot_view(self,
                      pools: Optional[Sequence[str]] = None) -> CacheView:
        """Epoch view for one dispatch cycle: a snapshot plus the per-pool
        cursors it was built at, read in ONE critical section so the
        cursors are an exact witness of the state the cycle's filters see.

        ``pools`` = a shard's partition: the returned snapshot holds ONLY
        those pools' nodes — plugins sweeping the shared lister
        (TopologyMatch's window search, Coscheduling's capacity dry-run)
        are structurally restricted to the shard's world, which is where
        the per-cycle cost reduction sharding exists for actually lands.
        Gang quorum accounting stays fleet-global (the pg-assigned index
        rides in whole).  The partition snapshot is cached against its
        pool-cursor tuple and REBUILT ONLY when the partition's own pools
        mutated; per-node clones are shared with the full snapshot, so a
        rebuild clones only nodes that changed since any view saw them.

        ``pools=None`` is the global lane's view: the full fleet snapshot
        plus every pool cursor."""
        with self._lock:
            if pools is None:
                snap = self._snapshot_locked()
                return CacheView(snap, self._snap_mutation,
                                 dict(self._pool_mutation))
            self._cleanup_expired_locked()
            cursors = {p: self._pool_mutation.get(p, 0) for p in pools}
            key = tuple(pools)
            sig = tuple(sorted(cursors.items()))
            ent = self._part_snaps.get(key)
            if ent is not None and ent[0] == sig:
                return CacheView(ent[1], self._mutation, cursors)
            infos: Dict[str, NodeInfo] = {}
            for p in pools:
                for name in self._pool_members.get(p, ()):
                    infos[name] = self._clone_of_locked(
                        name, self._infos[name])
            # the gang-quorum index rides in LIVE (by reference, not a
            # frozen copy): gang assignments land in pools OUTSIDE this
            # partition (escalated siblings, pool-pinned members) without
            # bumping the partition's cursors, and a frozen copy would
            # serve Coscheduling's permit barrier stale quorum counts for
            # as long as the cached view is reused.  Reads are single-key
            # dict gets (GIL-atomic against the locked writers), and
            # live-is-fresher is exactly what admission wants — the
            # quorum clock is shard-agnostic process state by design.
            snap = Snapshot.from_infos(infos, self._pg_assigned)
            snap.pool_cursors = dict(cursors)
            if len(self._part_snaps) > 64:   # partition churn backstop
                self._part_snaps.clear()
            self._part_snaps[key] = (sig, snap)
            return CacheView(snap, self._mutation, cursors)

    def peek_snapshot(self) -> "Snapshot | None":
        """Read-only view of the LAST snapshot the scheduling loop built —
        never rebuilds.  Foreign threads (the /metrics capacity collector)
        must use this instead of snapshot(): a rebuild from outside the
        loop advances ``_snap_mutation`` mid-cycle, which would launder a
        concurrent foreign mutation past the equivalence cache's
        "cursor advanced by exactly my own assume" arming guard
        (scheduler._equiv_offer / _equiv_after_assume) and arm an entry
        whose feasible set was computed against older state.  Telemetry
        readers tolerate the staleness (at most one scheduling cycle)."""
        with self._lock:
            return self._last_snapshot

    def node_names(self):
        with self._lock:
            return list(self._infos)

    def pools(self) -> List[str]:
        """Sorted names of pools with at least one live node — the shard
        topology's partitioning input."""
        with self._lock:
            return sorted(self._pool_nodes)

    # -- mutation cursor (equivalence-cache validity witness) -----------------

    def mutation_cursor(self) -> int:
        """Current value of the global change cursor. Every structural
        mutation (node add/update/remove, pod attach/detach, assume/forget)
        advances it; the equivalence cache keys entry validity on it."""
        with self._lock:
            return self._mutation

    def snapshot_cursor(self) -> int:
        """Cursor value the LAST snapshot() was built at — i.e. the state
        this cycle's filters actually read. Differs from mutation_cursor()
        only when an informer event raced in after snapshot()."""
        with self._lock:
            return self._snap_mutation

    def pool_cursor(self, pool: str) -> int:
        """Current cursor of one pool (the sharded commit protocol's
        compare key; captured atomically via snapshot_view)."""
        with self._lock:
            return self._pool_mutation.get(pool, 0)

    def pool_cursors(self,
                     pools: Sequence[str]) -> Tuple[Tuple[str, int], ...]:
        """Canonical cursor tuple for a partition — the shard-scoped
        equivalence-cache arming guard reads this right after its own
        guarded assume to verify the chain "my partition advanced by
        EXACTLY my own attach"."""
        with self._lock:
            return tuple(sorted(
                (p, self._pool_mutation.get(p, 0)) for p in pools))
