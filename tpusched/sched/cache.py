"""Scheduler cache: authoritative in-process view of nodes + pods, with
assume/confirm/expire semantics so concurrent cycles see in-flight decisions.

Rebuild of upstream internal/cache as the reference's hot loop depends on it
(snapshot at cycle start, SURVEY §3.2 "assume pod in cache"). NodeInfos are
maintained incrementally on every event (upstream's design) so snapshot() is
a cheap per-node clone, not a rebuild. Assumed pods expire if the bind is
never confirmed by the API server (watch event), which keeps the scheduler
restart-safe with annotations-as-truth (SURVEY §5 checkpoint/resume).
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from ..api.core import Node, Pod
from ..api.scheduling import POD_GROUP_LABEL
from ..fwk.nodeinfo import NodeInfo, Snapshot
from ..util import klog
from ..util.locking import GuardedLock, guarded_by

ASSUME_EXPIRATION_S = 30.0


@guarded_by("_lock", "_infos", "_pods", "_assumed", "_snap_clones",
            "_pg_assigned", "_mutation", "_snap_mutation", "_last_snapshot")
class Cache:
    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = GuardedLock("sched.Cache")
        self._infos: Dict[str, NodeInfo] = {}       # node name → live NodeInfo
        self._pods: Dict[str, Pod] = {}             # all known scheduled pods
        self._assumed: Dict[str, float] = {}        # pod key → bind deadline
        # last snapshot's clones, keyed by (generation) — upstream's
        # UpdateSnapshot design: only nodes that changed re-clone
        self._snap_clones: Dict[str, Tuple[int, NodeInfo]] = {}
        # gang full-name → members attached to a cached node (the Permit
        # quorum input), maintained incrementally at attach/detach so
        # assigned_count never walks the fleet (O(1) per cycle at any scale)
        self._pg_assigned: Dict[str, int] = {}
        # global change cursor: bumped by every structural mutation so an
        # unchanged cache returns the PREVIOUS Snapshot object outright —
        # back-to-back cycles over a quiet fleet otherwise rebuild two
        # O(nodes) dicts each (in-place pod mutations after assume stay
        # visible without a bump: snapshots share the pod objects)
        self._mutation = 0
        self._snap_mutation = -1
        self._last_snapshot: "Snapshot | None" = None

    def _pg_adjust_locked(self, pod: Pod, delta: int) -> None:
        name = pod.meta.labels.get(POD_GROUP_LABEL)
        if not name or not pod.spec.node_name:
            return
        key = f"{pod.meta.namespace}/{name}"
        n = self._pg_assigned.get(key, 0) + delta
        if n <= 0:
            self._pg_assigned.pop(key, None)
        else:
            self._pg_assigned[key] = n

    # -- nodes ----------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            self._mutation += 1
            old = self._infos.get(node.name)
            if old is not None:
                for p in old.pods:
                    self._pg_adjust_locked(p, -1)
            info = NodeInfo(node)
            self._infos[node.name] = info
            # attach pods already known to live on this node
            for p in self._pods.values():
                if p.spec.node_name == node.name:
                    info.add_pod(p)
                    self._pg_adjust_locked(p, +1)

    def update_node(self, node: Node) -> None:
        with self._lock:
            info = self._infos.get(node.name)
            if info is None:
                self.add_node(node)
            else:
                self._mutation += 1
                info.set_node(node)

    def remove_node(self, node: Node) -> list:
        """Drop a node AND reconcile the pod state attached to it — node
        removal with bound/assumed pods is a first-class event, not a blind
        pop.

        - pods stay in ``_pods`` (upstream RemoveNode semantics: the API
          server still holds bound pods, and a node-object replacement —
          remove+add of the same name — must re-attach them); quorum
          accounting is decremented with the NodeInfo;
        - assumed pods with a still-∞ deadline get their expiry TTL armed
          NOW: their bind targets hardware that no longer exists, and
          without this a bind whose confirmation can never arrive would
          leak the assume-table entry (and its quorum count on re-add)
          forever. The scheduler's ``_on_node_delete`` additionally rejects
          barrier-parked members on the vanished node, whose failure path
          forgets them promptly — the TTL is the backstop.

        Returns the pods that were attached so the caller can reject
        barrier-parked members and requeue the affected gangs."""
        with self._lock:
            self._mutation += 1
            info = self._infos.pop(node.name, None)
            if info is None:
                return []
            affected = list(info.pods)
            deadline = self._clock() + ASSUME_EXPIRATION_S
            for p in affected:
                self._pg_adjust_locked(p, -1)
                if self._assumed.get(p.key) == float("inf"):
                    self._assumed[p.key] = deadline
            return affected


    # -- pods -----------------------------------------------------------------

    def _attach_locked(self, pod: Pod) -> None:
        info = self._infos.get(pod.spec.node_name)
        if info is not None:
            self._mutation += 1
            info.add_pod(pod)
            self._pg_adjust_locked(pod, +1)

    def _detach_locked(self, pod: Pod) -> None:
        info = self._infos.get(pod.spec.node_name)
        if info is not None and info.remove_pod(pod):
            self._mutation += 1
            self._pg_adjust_locked(pod, -1)

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        """Stores the caller's object by reference (upstream shares the pod
        pointer too): Reserve plugins mutate the assumed pod's annotations
        *after* assume, and snapshots must see those writes — the chip model
        is rebuilt from annotations (tpuslice/chip_node.py)."""
        with self._lock:
            pod.spec.node_name = node_name
            self._pods[pod.key] = pod
            self._attach_locked(pod)
            self._assumed[pod.key] = float("inf")  # until finish_binding arms TTL

    def finish_binding(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._assumed:
                self._assumed[pod.key] = self._clock() + ASSUME_EXPIRATION_S

    def forget_pod(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._assumed:
                self._assumed.pop(pod.key, None)
                old = self._pods.pop(pod.key, None)
                if old is not None:
                    self._detach_locked(old)

    def add_pod(self, pod: Pod) -> None:
        """Confirmed (bound) pod from the watch stream."""
        with self._lock:
            self._assumed.pop(pod.key, None)
            old = self._pods.get(pod.key)
            if old is not None:
                self._detach_locked(old)
            self._pods[pod.key] = pod
            self._attach_locked(pod)

    def update_pod(self, pod: Pod) -> None:
        self.add_pod(pod)

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop(pod.key, None)
            old = self._pods.pop(pod.key, None)
            if old is not None:
                self._detach_locked(old)

    def is_assumed(self, pod_key: str) -> bool:
        with self._lock:
            return pod_key in self._assumed

    def _cleanup_expired_locked(self) -> None:
        now = self._clock()
        for key, deadline in list(self._assumed.items()):
            if deadline < now:
                klog.warning_s("assumed pod expired without bind confirmation",
                               pod=key)
                self._assumed.pop(key, None)
                old = self._pods.pop(key, None)
                if old is not None:
                    self._detach_locked(old)

    # -- snapshot -------------------------------------------------------------

    def snapshot(self) -> Snapshot:
        """Incremental (upstream cache.UpdateSnapshot): a node's clone from
        the previous snapshot is reused while its generation is unchanged.
        Safe because snapshot NodeInfos are read-only by contract — every
        mutation path (preemption dry-runs, nominated-pod evaluation) clones
        first (sched/preemption.py:129-130, fwk/runtime.py:309-312)."""
        with self._lock:
            self._cleanup_expired_locked()
            if (self._mutation == self._snap_mutation
                    and self._last_snapshot is not None):
                return self._last_snapshot
            prev = self._snap_clones
            clones: Dict[str, Tuple[int, NodeInfo]] = {}
            infos: Dict[str, NodeInfo] = {}
            for name, info in self._infos.items():
                ent = prev.get(name)
                if ent is None or ent[0] != info.generation:
                    ent = (info.generation, info.clone())
                clones[name] = ent
                infos[name] = ent[1]
            self._snap_clones = clones
            snap = Snapshot.from_infos(infos, dict(self._pg_assigned))
            self._snap_mutation = self._mutation
            self._last_snapshot = snap
            return snap

    def peek_snapshot(self) -> "Snapshot | None":
        """Read-only view of the LAST snapshot the scheduling loop built —
        never rebuilds.  Foreign threads (the /metrics capacity collector)
        must use this instead of snapshot(): a rebuild from outside the
        loop advances ``_snap_mutation`` mid-cycle, which would launder a
        concurrent foreign mutation past the equivalence cache's
        "cursor advanced by exactly my own assume" arming guard
        (scheduler._equiv_offer / _equiv_after_assume) and arm an entry
        whose feasible set was computed against older state.  Telemetry
        readers tolerate the staleness (at most one scheduling cycle)."""
        with self._lock:
            return self._last_snapshot

    def node_names(self):
        with self._lock:
            return list(self._infos)

    # -- mutation cursor (equivalence-cache validity witness) -----------------

    def mutation_cursor(self) -> int:
        """Current value of the global change cursor. Every structural
        mutation (node add/update/remove, pod attach/detach, assume/forget)
        advances it; the equivalence cache keys entry validity on it."""
        with self._lock:
            return self._mutation

    def snapshot_cursor(self) -> int:
        """Cursor value the LAST snapshot() was built at — i.e. the state
        this cycle's filters actually read. Differs from mutation_cursor()
        only when an informer event raced in after snapshot()."""
        with self._lock:
            return self._snap_mutation
