"""Scheduler cache: authoritative in-process view of nodes + pods, with
assume/confirm/expire semantics so concurrent cycles see in-flight decisions.

Rebuild of upstream internal/cache as the reference's hot loop depends on it
(snapshot at cycle start, SURVEY §3.2 "assume pod in cache"). NodeInfos are
maintained incrementally on every event (upstream's design) so snapshot() is
a cheap per-node clone, not a rebuild. Assumed pods expire if the bind is
never confirmed by the API server (watch event), which keeps the scheduler
restart-safe with annotations-as-truth (SURVEY §5 checkpoint/resume).

Sharded dispatch additions (ROADMAP item 1): every structural mutation is
attributed to the POOL it touched (``tpu.dev/pool`` of the node involved)
and bumps a per-pool cursor alongside the global one.  A shard's dispatch
cycle captures its partition's pool-cursor tuple atomically with the
snapshot it filters against (``snapshot_view``), and commits its placement
through the optimistic ``assume_pod_guarded`` compare-and-assume: the
assume lands only if the chosen pool's cursor is still the one the cycle's
filters read — a foreign mutation in that pool (an informer event, a
global-lane bind) fails the compare and the shard retries on fresh state
instead of binding a stale placement.  Mutations in OTHER pools do not
conflict: that independence is the whole point of partitioning dispatch by
pool.

O(Δ) cycle core (ISSUE 14): the snapshot is PERSISTENT and VERSIONED —
per-pool ``{node: NodeInfo}`` sub-maps built at a pool cursor and shared
structurally between every snapshot/partition view that includes the pool
(fwk.nodeinfo.PooledSnapshot).  A cycle over a quiet fleet composes its
view from existing sub-maps in O(pools-in-scope); a mutation re-clones ONE
pool's map (and inside it only the nodes whose generation moved).  The
gang-quorum index rides into every snapshot live by reference, cursor
tuples are memoized per snapshot epoch, and the flat candidate list is
cached per epoch — deleting the per-cycle O(hosts) dict builds, O(gangs)
copies and candidate materialization the pre-14 core paid on every cycle.

Quota ledger (ISSUE 14): each registered ElasticQuota namespace carries a
usage cursor — ``used`` resources of the namespace's known scheduled pods
(assumed + bound, non-terminated), maintained in the SAME critical
sections as the pod mutations themselves, plus incrementally-maintained
fleet aggregates (Σused, Σmin) and per-namespace change cursors / a
fleet-wide epoch for diagnosis.  CapacityScheduling's PreFilter reads its
admission inputs through ``quota_view()`` (one lock section) and the
commit generalizes to a SEMANTIC compare-and-reserve:
``assume_pod_guarded`` re-evaluates the pod's two admission bounds
(own-namespace max, fleet aggregate borrow gate — the ``QuotaReserve``
payload) against the LIVE ledger in O(resources) under the cache lock,
refusing (``QUOTA_CONFLICT``) exactly when concurrent quota'd traffic
genuinely consumed the room the verdict assumed — releases only loosen
the bounds, so teardown/confirm churn never refuses.  The lane re-derives
on refusal, exactly like pool conflicts.  This is what lets ElasticQuota
fleets dispatch on shard lanes instead of serializing wholesale through
the global lane.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.core import Node, Pod
from ..api.scheduling import POD_GROUP_LABEL
from ..api.topology import LABEL_POOL
from ..fwk.nodeinfo import NodeInfo, PooledSnapshot, Snapshot
from ..util import klog
from ..util.locking import GuardedLock, guarded_by
from ..util.podutil import (is_pod_terminated, pod_effective_request,
                            resources_over_bound)

ASSUME_EXPIRATION_S = 30.0


class _QuotaConflict:
    """Sentinel returned by ``assume_pod_guarded`` when the QUOTA
    compare-and-reserve failed (the pool compare still returns ``None``):
    the two conflict classes retry identically but are diagnosed
    separately (``tpusched_shard_quota_conflicts_total``, doc/ops.md)."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return "QUOTA_CONFLICT"


QUOTA_CONFLICT = _QuotaConflict()


# the ONE bound comparator shared with the admission side (see
# util.podutil.resources_over_bound: admission and commit must evaluate
# the identical rule or the compare-and-reserve is unsound)
_over = resources_over_bound


class QuotaReserve:
    """Commit-time quota admission payload (CapacityScheduling's PreFilter
    → ``Cache.assume_pod_guarded``): the pod's namespace plus the two
    request vectors its admission was judged with — ``in_eq`` (pod request
    + nominated same-namespace reservations, the own-max operand) and
    ``total`` (pod request + global nominated reservations, the aggregate
    borrow-gate operand).  The commit RE-EVALUATES both bounds against the
    LIVE ledger under the cache lock, so the reserve is semantic: it
    refuses exactly when the admission verdict genuinely no longer holds,
    never because unrelated quota traffic merely happened nearby.  (A
    first cut compared a fleet-wide quota epoch instead; under a storm,
    bind-confirm/teardown churn moved the epoch faster than cycles
    completed and essentially every concurrent quota'd commit thrashed —
    measured in the quota-storm bench before this design.)"""

    __slots__ = ("namespace", "in_eq", "total")

    def __init__(self, namespace: str, in_eq: Dict, total: Dict):
        self.namespace = namespace
        self.in_eq = in_eq
        self.total = total


def pool_of_node(node: Node) -> str:
    """The pool a node's mutations are attributed to.  Unpooled nodes
    (no ``tpu.dev/pool`` label) share the '' pool — they conflict with
    each other and with every cycle that places onto unpooled hardware,
    which is exactly the conservative behavior they need."""
    return node.meta.labels.get(LABEL_POOL, "")


class CacheView:
    """One cycle's atomically-captured view: the snapshot its filters read,
    the global cursor that snapshot was built at, and the per-pool cursors
    at the same instant (restricted to the cycle's partition when one was
    given — the equivalence-cache validity witness for shard lanes).

    ``pool_cursors`` is the SNAPSHOT's own cursor dict (shared, read-only;
    no per-cycle copy), so ``cursor_tuple`` can serve the snapshot's
    memoized sorted form."""

    __slots__ = ("snapshot", "cursor", "pool_cursors")

    def __init__(self, snapshot: Snapshot, cursor: int,
                 pool_cursors: Dict[str, int]):
        self.snapshot = snapshot
        self.cursor = cursor
        self.pool_cursors = pool_cursors

    def cursor_tuple(self) -> Tuple[Tuple[str, int], ...]:
        """Canonical (sorted) form for equivalence-entry validity —
        memoized on the snapshot when the view serves the snapshot's own
        cursors (the common case)."""
        snap = self.snapshot
        if (isinstance(snap, PooledSnapshot)
                and self.pool_cursors is snap.pool_cursors):
            return snap.cursor_tuple()
        return tuple(sorted(self.pool_cursors.items()))


@guarded_by("_lock", "_infos", "_pods", "_assumed", "_node_clones",
            "_pg_assigned", "_mutation", "_snap_mutation", "_last_snapshot",
            "_pool_mutation", "_pool_nodes", "_pool_members", "_part_snaps",
            "_pool_snap", "_full_snap", "_windex", "_quota_bounds",
            "_quota_used", "_quota_pods", "_quota_cursors", "_quota_epoch")
class Cache:
    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = GuardedLock("sched.Cache")
        self._infos: Dict[str, NodeInfo] = {}       # node name → live NodeInfo
        self._pods: Dict[str, Pod] = {}             # all known scheduled pods
        self._assumed: Dict[str, float] = {}        # pod key → bind deadline
        # earliest finite assume deadline (inf = none armed): the expiry
        # sweep is O(1) until something can actually expire — every
        # snapshot/view used to scan the whole assume table, which under
        # N concurrent dispatch lanes turned the cache lock into the
        # process hot spot and stalled informer ingestion behind it
        self._next_expiry = float("inf")
        # per-node snapshot clones keyed by generation — upstream's
        # UpdateSnapshot design: only nodes that changed re-clone.  Shared
        # by the full snapshot AND every partition snapshot (a node's
        # read-only clone is the same object in both), pruned on node
        # removal.
        self._node_clones: Dict[str, Tuple[int, NodeInfo]] = {}
        # gang full-name → members attached to a cached node (the Permit
        # quorum input), maintained incrementally at attach/detach so
        # assigned_count never walks the fleet (O(1) per cycle at any scale)
        self._pg_assigned: Dict[str, int] = {}
        # global change cursor: bumped by every structural mutation so an
        # unchanged cache returns the PREVIOUS Snapshot object outright —
        # back-to-back cycles over a quiet fleet otherwise rebuild two
        # O(nodes) dicts each (in-place pod mutations after assume stay
        # visible without a bump: snapshots share the pod objects)
        self._mutation = 0
        self._snap_mutation = -1
        self._last_snapshot: "Snapshot | None" = None
        # per-pool change cursors (sharded dispatch): every structural
        # mutation bumps the cursor of the pool it touched in the same
        # critical section as the global bump, so a partition's cursor
        # tuple is an exact witness of "nothing in MY pools changed"
        self._pool_mutation: Dict[str, int] = {}
        # pool → live node count (pools() without an O(nodes) walk)
        self._pool_nodes: Dict[str, int] = {}
        # bumped only when the pool SET changes (first node of a pool
        # arrives / last one leaves).  Read LOCK-FREE by dispatch lanes
        # (GIL-atomic int) to decide whether their partition needs a
        # recompute: a per-cycle pools() call under the cache lock from N
        # lanes was, measurably, the process's hottest contention point.
        self.pools_version = 0
        # pool → live node-name set: the partition snapshot builder's
        # iteration domain (a shard rebuilds its view from ITS pools'
        # nodes only, never walking the fleet)
        self._pool_members: Dict[str, Dict[str, None]] = {}
        # persistent per-pool snapshot sub-maps (the O(Δ) cycle core):
        # pool → (built-at cursor, {node: NodeInfo clone}, [clones]).
        # Rebuilt ONLY when the pool's own cursor moved; the dict and
        # list objects are shared by reference with every composed
        # snapshot, so a rebuild swaps in fresh ones and never mutates a
        # published one.  The list is the pool's slice of the candidate
        # chain (PoolChain) — kept here so an epoch re-lists only the
        # mutated pool.
        self._pool_snap: Dict[str, Tuple[int, Dict[str, NodeInfo],
                                         List[NodeInfo]]] = {}
        # the composed full-fleet snapshot, memoized on the global cursor
        # (any structural mutation is pool-attributed, so cursor equality
        # == every sub-map is fresh AND the pool set is unchanged)
        self._full_snap: "Tuple[int, PooledSnapshot] | None" = None
        # partition-snapshot cache: partition (pool tuple) → (the pool-
        # cursor tuple it was built at, composed PooledSnapshot).  A
        # shard's epoch view is re-COMPOSED (O(partition pools)) only when
        # its own pools mutated — and even then the sub-maps of untouched
        # pools are reused by reference.
        self._part_snaps: Dict[Tuple[str, ...],
                               Tuple[Tuple, PooledSnapshot]] = {}
        # incremental torus window index (topology/windowindex.py, ISSUE
        # 13): every structural mutation below feeds the index its
        # occupancy delta IN THE SAME critical section as the cursor bump,
        # so a plane whose version equals a snapshot's pool cursor is an
        # exact witness of identical occupancy.  None = no index attached
        # (TPUSCHED_NO_WINDOW_INDEX, or the index self-detached on error).
        self._windex = None
        # -- quota ledger (ISSUE 14) -----------------------------------------
        # namespace → (min, max) bounds of the namespace's ElasticQuota,
        # registered by the scheduler's EQ informer wiring.  Only
        # registered namespaces are tracked: non-quota traffic never pays
        # a ledger update and never bumps the quota epoch.
        self._quota_bounds: Dict[str, Tuple[Dict, Dict]] = {}
        # namespace → used resources of its known scheduled pods (assumed
        # + bound, non-terminated), and the pod keys counted (idempotency
        # witness for the at-least-once informer delivery contract)
        self._quota_used: Dict[str, Dict[str, float]] = {}
        self._quota_pods: Dict[str, set] = {}
        # per-namespace change cursors (diagnosis surface: WHICH quota is
        # hot) and the fleet-wide epoch (the commit compare key: quota
        # admission reads cross-namespace state — Σused vs Σmin — so ANY
        # registered quota's change invalidates an in-flight verdict)
        self._quota_cursors: Dict[str, int] = {}
        self._quota_epoch = 0
        # cached bounds signature (the equivalence cache's quota
        # fingerprint input under guarded commits): recomputed only on
        # bounds sync — a per-lookup recompute would put an O(quotas)
        # sort on the equivalence hot path
        self._quota_bounds_sig: Tuple = ()
        # incrementally-maintained aggregates for the commit-time borrow
        # gate: Σ used over registered namespaces (adjusted with every
        # quota_adjust) and Σ min (recomputed on bounds sync — rare)
        self._quota_used_sum: Dict[str, float] = {}
        self._quota_min_sum: Dict[str, float] = {}

    def _bump_locked(self, pool: str) -> int:
        self._mutation += 1
        cursor = self._pool_mutation.get(pool, 0) + 1
        self._pool_mutation[pool] = cursor
        return cursor

    def _pool_member_locked(self, pool: str, name: str, delta: int) -> None:
        if delta > 0:
            n = self._pool_nodes.get(pool, 0)
            if n == 0:
                self.pools_version += 1      # a pool was born
            self._pool_nodes[pool] = n + 1
            self._pool_members.setdefault(pool, {})[name] = None
            return
        n = self._pool_nodes.get(pool, 0) - 1
        if n <= 0:
            self._pool_nodes.pop(pool, None)
            self.pools_version += 1          # a pool emptied out
        else:
            self._pool_nodes[pool] = n
        members = self._pool_members.get(pool)
        if members is not None:
            members.pop(name, None)
            if not members:
                self._pool_members.pop(pool, None)

    # -- quota ledger ---------------------------------------------------------

    def _quota_adjust_locked(self, pod: Pod, delta: int) -> None:
        """Reserve (+1) / release (-1) a pod's effective request against its
        namespace's quota usage — in the SAME critical section as the pod
        mutation, so the quota epoch is an exact change witness.  No-op
        for unregistered namespaces and (on reserve) terminated pods;
        idempotent via the per-namespace pod-key set."""
        ns = pod.meta.namespace
        if ns not in self._quota_bounds:
            return
        pods = self._quota_pods.setdefault(ns, set())
        if delta > 0:
            if pod.key in pods or is_pod_terminated(pod):
                return
            pods.add(pod.key)
            sign = 1
        else:
            if pod.key not in pods:
                return
            pods.discard(pod.key)
            sign = -1
        used = self._quota_used.setdefault(ns, {})
        total = self._quota_used_sum
        for k, v in pod_effective_request(pod).items():
            used[k] = used.get(k, 0) + sign * v
            total[k] = total.get(k, 0) + sign * v
        self._quota_cursors[ns] = self._quota_cursors.get(ns, 0) + 1
        self._quota_epoch += 1

    def _quota_seed_locked(self, ns: str) -> None:
        """(Re)derive a newly registered namespace's usage from the pods
        the cache already knows — O(known pods), once per EQ registration."""
        used: Dict[str, float] = {}
        keys: set = set()
        for pod in self._pods.values():
            if pod.meta.namespace != ns or is_pod_terminated(pod):
                continue
            keys.add(pod.key)
            for k, v in pod_effective_request(pod).items():
                used[k] = used.get(k, 0) + v
        self._quota_used[ns] = used
        self._quota_pods[ns] = keys
        for k, v in used.items():
            self._quota_used_sum[k] = self._quota_used_sum.get(k, 0) + v

    def sync_quota_bounds(self, bounds: Dict[str, Tuple[Dict, Dict]]) -> None:
        """Reconcile the registered quota set against the informer's
        current view: ``{namespace: (min, max)}``.  Newly registered
        namespaces seed their usage from the cache's known pods; removed
        ones drop their ledger; a bounds CHANGE bumps the namespace cursor
        and the epoch (admission verdicts depend on min/max, so in-flight
        quota-guarded commits must conflict)."""
        with self._lock:
            changed = False
            for ns in list(self._quota_bounds):
                if ns not in bounds:
                    self._quota_bounds.pop(ns, None)
                    dropped = self._quota_used.pop(ns, None) or {}
                    for k, v in dropped.items():
                        self._quota_used_sum[k] = \
                            self._quota_used_sum.get(k, 0) - v
                    self._quota_pods.pop(ns, None)
                    self._quota_cursors[ns] = \
                        self._quota_cursors.get(ns, 0) + 1
                    self._quota_epoch += 1
                    changed = True
            for ns, (mn, mx) in bounds.items():
                old = self._quota_bounds.get(ns)
                new = (dict(mn or {}), dict(mx or {}))
                if old == new:
                    continue
                self._quota_bounds[ns] = new
                if old is None:
                    self._quota_seed_locked(ns)
                self._quota_cursors[ns] = \
                    self._quota_cursors.get(ns, 0) + 1
                self._quota_epoch += 1
                changed = True
            if changed:
                # Σ min + the bounds signature: recomputed on bounds
                # change only (rare)
                min_sum: Dict[str, float] = {}
                for mn, _mx in self._quota_bounds.values():
                    for k, v in mn.items():
                        min_sum[k] = min_sum.get(k, 0) + v
                self._quota_min_sum = min_sum
                self._quota_bounds_sig = tuple(sorted(
                    (ns, tuple(sorted(mn.items())),
                     tuple(sorted(mx.items())))
                    for ns, (mn, mx) in self._quota_bounds.items()))

    def quota_view(self):
        """Consistent admission inputs for CapacityScheduling's PreFilter:
        ``({namespace: (min, max, used, pod_keys_loader)}, epoch)``
        captured in ONE critical section — the epoch is an exact change
        witness of the usage the verdict judged (diagnosis surface; the
        COMMIT re-checks the admission bounds semantically via
        ``QuotaReserve``, so the epoch is not the compare key).  The
        pod-key sets are handed out as zero-arg LOADERS, not copies:
        only preemption dry-runs consume membership, and copying every
        namespace's key set per quota'd cycle was an O(scheduled pods)
        term under the cache lock.  ``(None, epoch)`` when no quota is
        registered (the fleet is quota-free)."""
        with self._lock:
            if not self._quota_bounds:
                return None, self._quota_epoch
            out = {}
            for ns, (mn, mx) in self._quota_bounds.items():
                out[ns] = (dict(mn), dict(mx),
                           dict(self._quota_used.get(ns) or {}),
                           self._quota_pods_loader(ns))
            return out, self._quota_epoch

    def _quota_pods_loader(self, ns: str):
        def load() -> set:
            with self._lock:
                return set(self._quota_pods.get(ns) or ())
        return load

    def quota_epoch(self) -> int:
        with self._lock:
            return self._quota_epoch

    def quota_bounds_signature(self) -> Tuple:
        """Canonical signature of the registered quota BOUNDS (not usage):
        the equivalence cache's quota fingerprint under guarded commits —
        usage changes need no invalidation there because every commit
        re-validates admission against the live ledger; bounds changes do
        (they change which QuotaReserve a cycle should have built)."""
        with self._lock:
            return self._quota_bounds_sig

    def quota_used_snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-namespace used resources of the registered quotas — the
        capacity collector's O(quotas) replacement for its per-scrape
        O(pods) fleet walk."""
        with self._lock:
            return {ns: dict(self._quota_used.get(ns) or {})
                    for ns in self._quota_bounds}

    def quota_health(self) -> Dict[str, object]:
        """health.shards quota block: registered namespace count, the
        epoch, and the per-namespace cursors (which quota is hot when
        ``tpusched_shard_quota_conflicts_total`` climbs — doc/ops.md)."""
        with self._lock:
            return {"namespaces": len(self._quota_bounds),
                    "epoch": self._quota_epoch,
                    "cursors": {ns: self._quota_cursors.get(ns, 0)
                                for ns in self._quota_bounds}}

    # -- window index plumbing ------------------------------------------------

    def attach_window_index(self, idx) -> None:
        """Attach (or replace) the torus window index and seed it from the
        CURRENT cache state + per-pool cursors in one critical section."""
        with self._lock:
            self._windex = idx
            if idx is None:
                return
            try:
                idx.cache_reset()
                for info in self._infos.values():
                    idx.cache_seed_node(info.node, info.pods)
                idx.rebuild_stale(
                    lambda p: self._pool_mutation.get(p, 0))
            except Exception as e:  # noqa: BLE001 — the index is an
                # accelerator: on ANY maintenance failure detach it and let
                # every consumer fall back to the Python recompute path
                klog.error_s(e, "window index attach failed; detaching")
                self._windex = None

    def window_index(self):
        with self._lock:
            return self._windex

    def sync_window_index(self) -> None:
        """Rebuild any stale index pools (topology CR change, differential
        self-heal) atomically with their pool cursors."""
        with self._lock:
            idx = self._windex
            if idx is None or not idx.stale_pools():
                return
            try:
                idx.rebuild_stale(lambda p: self._pool_mutation.get(p, 0))
            except Exception as e:  # noqa: BLE001 — see attach_window_index
                klog.error_s(e, "window index rebuild failed; detaching")
                self._windex = None

    def _windex_call_locked(self, method: str, *args) -> None:
        idx = self._windex
        if idx is None:
            return
        try:
            getattr(idx, method)(*args)
        except Exception as e:  # noqa: BLE001 — see attach_window_index
            klog.error_s(e, "window index update failed; detaching",
                         hook=method)
            self._windex = None

    def _pg_adjust_locked(self, pod: Pod, delta: int) -> None:
        name = pod.meta.labels.get(POD_GROUP_LABEL)
        if not name or not pod.spec.node_name:
            return
        key = f"{pod.meta.namespace}/{name}"
        n = self._pg_assigned.get(key, 0) + delta
        if n <= 0:
            self._pg_assigned.pop(key, None)
        else:
            self._pg_assigned[key] = n

    # -- nodes ----------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            pool = pool_of_node(node)
            stamps = [(pool, self._bump_locked(pool))]
            old = self._infos.get(node.name)
            if old is not None:
                old_pool = pool_of_node(old.node)
                if old_pool != pool:
                    # a replacement that MOVED pools dirties both: shards
                    # on either side of the move must see the change
                    stamps.append((old_pool, self._bump_locked(old_pool)))
                    self._pool_member_locked(old_pool, node.name, -1)
                    self._pool_member_locked(pool, node.name, +1)
                for p in old.pods:
                    self._pg_adjust_locked(p, -1)
            else:
                self._pool_member_locked(pool, node.name, +1)
            info = NodeInfo(node)
            self._infos[node.name] = info
            # attach pods already known to live on this node (their quota
            # usage never left the ledger: pods stay in _pods across node
            # churn, so re-attachment is quota-neutral)
            attached = []
            for p in self._pods.values():
                if p.spec.node_name == node.name:
                    info.add_pod(p)
                    self._pg_adjust_locked(p, +1)
                    attached.append(p)
            self._windex_call_locked("cache_node_upsert", node, attached,
                                     stamps)

    def update_node(self, node: Node) -> None:
        with self._lock:
            info = self._infos.get(node.name)
            if info is None:
                self.add_node(node)
            else:
                pool = pool_of_node(node)
                old_pool = pool_of_node(info.node)
                stamps = [(pool, self._bump_locked(pool))]
                if old_pool != pool:
                    stamps.append((old_pool, self._bump_locked(old_pool)))
                    self._pool_member_locked(old_pool, node.name, -1)
                    self._pool_member_locked(pool, node.name, +1)
                info.set_node(node)
                self._windex_call_locked("cache_node_upsert", node, None,
                                         stamps)

    def remove_node(self, node: Node) -> list:
        """Drop a node AND reconcile the pod state attached to it — node
        removal with bound/assumed pods is a first-class event, not a blind
        pop.

        - pods stay in ``_pods`` (upstream RemoveNode semantics: the API
          server still holds bound pods, and a node-object replacement —
          remove+add of the same name — must re-attach them); quorum
          accounting is decremented with the NodeInfo; quota usage is
          untouched (the pods still exist and hold their requests);
        - assumed pods with a still-∞ deadline get their expiry TTL armed
          NOW: their bind targets hardware that no longer exists, and
          without this a bind whose confirmation can never arrive would
          leak the assume-table entry (and its quorum count on re-add)
          forever. The scheduler's ``_on_node_delete`` additionally rejects
          barrier-parked members on the vanished node, whose failure path
          forgets them promptly — the TTL is the backstop.

        Returns the pods that were attached so the caller can reject
        barrier-parked members and requeue the affected gangs."""
        with self._lock:
            info = self._infos.pop(node.name, None)
            if info is None:
                # cursor semantics unchanged: a no-op removal still reads
                # as a mutation of the named node's pool (callers observed
                # an event; shards re-validate cheaply)
                pool = pool_of_node(node)
                self._windex_call_locked("cache_note", pool,
                                         self._bump_locked(pool))
                return []
            pool = pool_of_node(info.node)
            cursor = self._bump_locked(pool)
            self._pool_member_locked(pool, node.name, -1)
            self._node_clones.pop(node.name, None)
            self._windex_call_locked("cache_node_removed", node.name,
                                     [(pool, cursor)])
            affected = list(info.pods)
            deadline = self._clock() + ASSUME_EXPIRATION_S
            for p in affected:
                self._pg_adjust_locked(p, -1)
                if self._assumed.get(p.key) == float("inf"):
                    self._assumed[p.key] = deadline
                    self._next_expiry = min(self._next_expiry, deadline)
            return affected


    # -- pods -----------------------------------------------------------------

    def _attach_locked(self, pod: Pod) -> None:
        info = self._infos.get(pod.spec.node_name)
        if info is not None:
            pool = pool_of_node(info.node)
            cursor = self._bump_locked(pool)
            info.add_pod(pod)
            self._pg_adjust_locked(pod, +1)
            self._windex_call_locked("cache_pod_delta", pod.spec.node_name,
                                     pod, 1, [(pool, cursor)])

    def _detach_locked(self, pod: Pod) -> None:
        info = self._infos.get(pod.spec.node_name)
        if info is not None and info.remove_pod(pod):
            pool = pool_of_node(info.node)
            cursor = self._bump_locked(pool)
            self._pg_adjust_locked(pod, -1)
            self._windex_call_locked("cache_pod_delta", pod.spec.node_name,
                                     pod, -1, [(pool, cursor)])

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        """Stores the caller's object by reference (upstream shares the pod
        pointer too): Reserve plugins mutate the assumed pod's annotations
        *after* assume, and snapshots must see those writes — the chip model
        is rebuilt from annotations (tpuslice/chip_node.py)."""
        with self._lock:
            self._assume_locked(pod, node_name)

    def _assume_locked(self, pod: Pod, node_name: str) -> None:
        # replace-don't-stack: an entry already cached under this key (a
        # watch confirm that raced in, or a re-assume) is detached first —
        # stacking a second attached copy would double-count the gang's
        # permit-quorum index (found by the cross-shard-gang-quorum
        # interleaving scenario)
        old = self._pods.get(pod.key)
        if old is not None:
            self._detach_locked(old)
            self._quota_adjust_locked(old, -1)
        pod.spec.node_name = node_name
        self._pods[pod.key] = pod
        self._attach_locked(pod)
        self._quota_adjust_locked(pod, +1)
        self._assumed[pod.key] = float("inf")  # until finish_binding arms TTL

    def assume_pod_guarded(self, pod: Pod, node_name: str,
                           expected_pool_cursor: int,
                           pools: Optional[Sequence[str]] = None,
                           quota_guard: "QuotaReserve | None" = None):
        """Optimistic compare-and-assume (sharded dispatch commit point):
        assume ``pod`` onto ``node_name`` iff the chosen node's POOL cursor
        still equals ``expected_pool_cursor`` — the value the calling
        cycle's snapshot_view captured when its filters read the state —
        AND, when ``quota_guard`` is given, the pod's quota admission
        still holds against the LIVE ledger: used + guard.in_eq within the
        namespace's max, and Σused + guard.total within Σmin (the same two
        bounds CapacityScheduling's PreFilter judged, re-evaluated here in
        O(resources) under the lock).  The reserve is the attach itself:
        landing the assume adjusts the namespace's usage in the same
        critical section, so compare-and-assume IS compare-and-reserve —
        two lanes can never co-admit past a max or past the aggregate
        borrow gate, which is the overshoot that used to force quota
        fleets through the serialized global lane wholesale.

        Returns None (nothing assumed) when the pool saw a foreign
        mutation since, or when the node itself vanished, and the
        ``QUOTA_CONFLICT`` sentinel when only the quota re-check failed —
        i.e. concurrent quota'd traffic genuinely consumed the room this
        verdict assumed (semantic refusal, never "something merely
        changed nearby": usage RELEASES can only loosen both bounds, so
        teardown churn and bind-confirm replacements never refuse a
        commit).  Per-node filter outcomes are monotone under foreign
        ASSUMES in other pools (they only consume resources elsewhere),
        so the pool compare stays scoped to the one pool the placement
        touches — cross-pool traffic never serializes here.

        On success returns the post-assume cursor tuple of ``pools`` (the
        shard-scoped equivalence arming guard's input, read in the SAME
        critical section — a separate lock hop per cycle was measurable
        contention), or an empty tuple when ``pools`` is None."""
        with self._lock:
            info = self._infos.get(node_name)
            if info is None:
                return None
            pool = pool_of_node(info.node)
            if self._pool_mutation.get(pool, 0) != expected_pool_cursor:
                return None
            if quota_guard is not None:
                bounds = self._quota_bounds.get(quota_guard.namespace)
                if bounds is not None:
                    used = self._quota_used.get(quota_guard.namespace) or {}
                    if _over(used, quota_guard.in_eq, bounds[1]) \
                            or _over(self._quota_used_sum,
                                     quota_guard.total,
                                     self._quota_min_sum):
                        return QUOTA_CONFLICT
            self._assume_locked(pod, node_name)
            if pools is None:
                return ()
            return tuple(sorted(
                (p, self._pool_mutation.get(p, 0)) for p in pools))

    def finish_binding(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._assumed:
                deadline = self._clock() + ASSUME_EXPIRATION_S
                self._assumed[pod.key] = deadline
                self._next_expiry = min(self._next_expiry, deadline)

    def forget_pod(self, pod: Pod) -> None:
        with self._lock:
            if pod.key in self._assumed:
                self._assumed.pop(pod.key, None)
                old = self._pods.pop(pod.key, None)
                if old is not None:
                    self._detach_locked(old)
                    self._quota_adjust_locked(old, -1)

    def add_pod(self, pod: Pod) -> None:
        """Confirmed (bound) pod from the watch stream."""
        with self._lock:
            self._assumed.pop(pod.key, None)
            old = self._pods.get(pod.key)
            if old is not None:
                self._detach_locked(old)
                self._quota_adjust_locked(old, -1)
            self._pods[pod.key] = pod
            self._attach_locked(pod)
            self._quota_adjust_locked(pod, +1)

    def update_pod(self, pod: Pod) -> None:
        self.add_pod(pod)

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop(pod.key, None)
            old = self._pods.pop(pod.key, None)
            if old is not None:
                self._detach_locked(old)
                self._quota_adjust_locked(old, -1)

    def is_assumed(self, pod_key: str) -> bool:
        with self._lock:
            return pod_key in self._assumed

    def _cleanup_expired_locked(self) -> None:
        if self._next_expiry == float("inf") \
                or self._clock() < self._next_expiry:
            return                      # O(1) on the hot path
        now = self._clock()
        nxt = float("inf")
        for key, deadline in list(self._assumed.items()):
            if deadline < now:
                klog.warning_s("assumed pod expired without bind confirmation",
                               pod=key)
                self._assumed.pop(key, None)
                old = self._pods.pop(key, None)
                if old is not None:
                    self._detach_locked(old)
                    self._quota_adjust_locked(old, -1)
            else:
                nxt = min(nxt, deadline)
        self._next_expiry = nxt

    # -- snapshot (persistent / versioned — the O(Δ) cycle core) --------------

    def _clone_of_locked(self, name: str, info: NodeInfo) -> NodeInfo:
        ent = self._node_clones.get(name)
        if ent is None or ent[0] != info.generation:
            ent = (info.generation, info.clone())
            self._node_clones[name] = ent
        return ent[1]

    def _pool_entry_locked(self, pool: str) -> Tuple[int, Dict[str, NodeInfo],
                                                     List[NodeInfo]]:
        """The pool's persistent (cursor, sub-map, value-list) entry,
        rebuilt only when the pool's own cursor moved — and inside the
        rebuild, only nodes whose generation changed re-clone (upstream's
        UpdateSnapshot trick, lifted one level: per-pool instead of
        per-fleet)."""
        cursor = self._pool_mutation.get(pool, 0)
        ent = self._pool_snap.get(pool)
        if ent is not None and ent[0] == cursor:
            return ent
        infos = {name: self._clone_of_locked(name, self._infos[name])
                 for name in self._pool_members.get(pool, ())}
        ent = (cursor, infos, list(infos.values()))
        self._pool_snap[pool] = ent
        return ent

    def _compose_locked(self, pools: Sequence[str]) -> PooledSnapshot:
        """Compose a PooledSnapshot over ``pools`` from the persistent
        sub-maps.  O(len(pools)) plus the rebuild cost of pools that
        actually mutated.  The gang-quorum index rides in LIVE (by
        reference, not a frozen copy): gang assignments can land in pools
        outside a partition (escalated siblings, pool-pinned members)
        without bumping the partition's cursors, and a frozen copy would
        serve Coscheduling's permit barrier stale quorum counts for as
        long as the composed view is reused.  Reads are single-key dict
        gets (GIL-atomic against the locked writers), and live-is-fresher
        is exactly what admission wants — the quorum clock is shard-
        agnostic process state by design."""
        pool_maps: Dict[str, Dict[str, NodeInfo]] = {}
        pool_lists: Dict[str, List[NodeInfo]] = {}
        cursors: Dict[str, int] = {}
        for p in pools:
            cursor, infos, values = self._pool_entry_locked(p)
            pool_maps[p] = infos
            pool_lists[p] = values
            cursors[p] = cursor
        # prune sub-maps of pools that no longer exist (bounded memory
        # under pool churn; cheap: dict-size compare first)
        if len(self._pool_snap) > len(self._pool_nodes) + 8:
            for stale in [p for p in self._pool_snap
                          if p not in self._pool_nodes]:
                del self._pool_snap[stale]
        return PooledSnapshot(pool_maps, cursors, self._pg_assigned,
                              pool_lists=pool_lists)

    def _full_snapshot_locked(self) -> PooledSnapshot:
        """The composed full-fleet snapshot, memoized on the global cursor.
        Does NOT touch ``_snap_mutation``/``_last_snapshot`` — foreign
        threads (the /metrics capacity collector via shared_snapshot) can
        refresh it without laundering a concurrent mutation past the
        equivalence cache's arming guard (which compares cursors the CYCLE
        captured, never this memo's freshness)."""
        self._cleanup_expired_locked()
        if self._full_snap is not None \
                and self._full_snap[0] == self._mutation:
            return self._full_snap[1]
        snap = self._compose_locked(sorted(self._pool_nodes))
        self._full_snap = (self._mutation, snap)
        return snap

    def _snapshot_locked(self) -> Snapshot:
        snap = self._full_snapshot_locked()
        self._snap_mutation = self._mutation
        self._last_snapshot = snap
        return snap

    def snapshot(self) -> Snapshot:
        with self._lock:
            return self._snapshot_locked()

    def shared_snapshot(self) -> Snapshot:
        """The persistent full-fleet snapshot for FOREIGN threads (the
        /metrics capacity collector, housekeeping readers): always fresh,
        O(Δ) to serve, and — unlike snapshot() — it never advances the
        loop's ``_snap_mutation``/``_last_snapshot`` bookkeeping, so it
        cannot launder a concurrent foreign mutation past the equivalence
        cache's "cursor advanced by exactly my own assume" arming guard.
        This is what let the sharded core drop its housekeeping-tick full
        snapshot() refresh (ISSUE 14 satellite)."""
        with self._lock:
            return self._full_snapshot_locked()

    def snapshot_view(self,
                      pools: Optional[Sequence[str]] = None) -> CacheView:
        """Epoch view for one dispatch cycle: a snapshot plus the per-pool
        cursors it was built at, read in ONE critical section so the
        cursors are an exact witness of the state the cycle's filters see.

        ``pools`` = a shard's partition: the returned snapshot holds ONLY
        those pools' nodes — plugins sweeping the shared lister
        (TopologyMatch's window search, Coscheduling's capacity dry-run)
        are structurally restricted to the shard's world, which is where
        the per-cycle cost reduction sharding exists for actually lands.
        Gang quorum accounting stays fleet-global (the pg-assigned index
        rides in live).  The partition snapshot is cached against its
        pool-cursor tuple and RE-COMPOSED ONLY when the partition's own
        pools mutated; sub-maps and per-node clones are shared with the
        full snapshot, so a recompose re-clones only nodes that changed
        since any view saw them.

        ``pools=None`` is the global lane's view: the full fleet snapshot
        plus every live pool's cursor (the snapshot's own cursor dict —
        no per-cycle copy)."""
        with self._lock:
            if pools is None:
                snap = self._snapshot_locked()
                return CacheView(snap, self._snap_mutation,
                                 snap.pool_cursors)
            self._cleanup_expired_locked()
            key = tuple(pools)
            sig = tuple(self._pool_mutation.get(p, 0) for p in pools)
            ent = self._part_snaps.get(key)
            if ent is not None and ent[0] == sig:
                snap = ent[1]
            else:
                snap = self._compose_locked(pools)
                if len(self._part_snaps) > 64:   # partition churn backstop
                    self._part_snaps.clear()
                self._part_snaps[key] = (sig, snap)
            return CacheView(snap, self._mutation, snap.pool_cursors)

    def peek_snapshot(self) -> "Snapshot | None":
        """Read-only view of the LAST snapshot the scheduling loop built —
        never rebuilds.  Prefer ``shared_snapshot()`` for foreign-thread
        readers that need freshness: it serves the persistent composed
        snapshot without touching the loop's snapshot bookkeeping."""
        with self._lock:
            return self._last_snapshot

    def node_names(self):
        with self._lock:
            return list(self._infos)

    def pools(self) -> List[str]:
        """Sorted names of pools with at least one live node — the shard
        topology's partitioning input."""
        with self._lock:
            return sorted(self._pool_nodes)

    # -- mutation cursor (equivalence-cache validity witness) -----------------

    def mutation_cursor(self) -> int:
        """Current value of the global change cursor. Every structural
        mutation (node add/update/remove, pod attach/detach, assume/forget)
        advances it; the equivalence cache keys entry validity on it."""
        with self._lock:
            return self._mutation

    def snapshot_cursor(self) -> int:
        """Cursor value the LAST snapshot() was built at — i.e. the state
        this cycle's filters actually read. Differs from mutation_cursor()
        only when an informer event raced in after snapshot()."""
        with self._lock:
            return self._snap_mutation

    def pool_cursor(self, pool: str) -> int:
        """Current cursor of one pool (the sharded commit protocol's
        compare key; captured atomically via snapshot_view)."""
        with self._lock:
            return self._pool_mutation.get(pool, 0)

    def pool_cursors(self,
                     pools: Sequence[str]) -> Tuple[Tuple[str, int], ...]:
        """Canonical cursor tuple for a partition — the shard-scoped
        equivalence-cache arming guard reads this right after its own
        guarded assume to verify the chain "my partition advanced by
        EXACTLY its own attach"."""
        with self._lock:
            return tuple(sorted(
                (p, self._pool_mutation.get(p, 0)) for p in pools))
