"""Active-standby scheduler HA over a shared state directory.

The reference scheduler inherits HA from upstream kube-scheduler leader
election, configured in the very YAML this repo decodes
(/root/reference/manifests/coscheduling/scheduler-config.yaml:3-4); the
controller analog is cmd/controller/app/server.go:84-123. In this rebuild
the API server is in-process, so the shared state two replicas arbitrate is
the ``--state-dir`` WAL — and the lease must live where the state lives:
a FILE lease in the state directory, not a Lease object inside the active's
own (dying) API server.

Model (mirrors upstream leader election semantics):

- N replicas campaign on ``<state-dir>/scheduler.lease``; acquisition is
  serialized by an ``fcntl`` lock so check-then-write is atomic across
  processes.
- The winner recovers the WAL into a fresh APIServer (``persistence.attach``
  — whose startup compaction also ROTATES the WAL inode, fencing a deposed
  active's buffered writes into an orphaned file), starts the scheduler, and
  renews the lease every ``renew_interval_s``.
- A replica that fails to renew (lease stolen after an expiry it slept
  through) stops its schedulers and journal immediately — exit-on-lost-lease,
  the same policy as the controller runner.
- A standby that wins takeover resumes the fleet mid-flight: bound pods are
  in the WAL (chip annotations included), members parked at the dead
  active's permit barrier were process state and come back Pending, so the
  gang re-admits against the surviving binds.

Takeover latency = remaining lease time + WAL replay (measured at 0.3 s for
2k objects, BENCH r3) + first scheduling cycle; bench.py's ha_takeover line
measures the whole pipeline.
"""
from __future__ import annotations

import fcntl
import json
import os
import threading
import time
import uuid
from typing import Callable, List, Optional

from ..apiserver import APIServer
from ..apiserver import persistence
from ..fwk import PluginProfile
from ..plugins import default_registry
from ..util import klog

LEASE_FILE = "scheduler.lease"
LOCK_FILE = "scheduler.lease.lock"


class FileLease:
    """A kube Lease analog as a file: JSON {holder, renewed_at, duration}.
    Wall-clock based (cross-process, same machine or shared filesystem);
    every transition runs under an fcntl lock, so acquire is atomic."""

    def __init__(self, directory: str, clock: Callable[[], float] = time.time):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, LEASE_FILE)
        self._lock_path = os.path.join(directory, LOCK_FILE)
        self._clock = clock

    def _locked(self):
        f = open(self._lock_path, "a+")
        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
        return f

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            if not isinstance(data, dict):
                return None
            return data
        except (OSError, ValueError):
            return None   # absent or torn write: treat as no lease

    def acquire_or_renew(self, holder: str, duration_s: float) -> bool:
        """True iff ``holder`` is (now) the leader — acquires a free/expired
        lease, renews an owned one, refuses someone else's live lease."""
        with self._locked():
            cur = self._read()
            now = self._clock()
            if cur is not None and cur.get("holder") != holder and \
                    now - float(cur.get("renewed_at", 0)) <= \
                    float(cur.get("duration", 0)):
                return False
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"holder": holder, "renewed_at": now,
                           "duration": duration_s}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            return True

    def release(self, holder: str) -> None:
        """Drop the lease iff still held by ``holder`` (clean shutdown lets
        the standby take over without waiting out the duration)."""
        with self._locked():
            cur = self._read()
            if cur is not None and cur.get("holder") == holder:
                try:
                    os.remove(self.path)
                except OSError:
                    pass

    def holder(self) -> str:
        cur = self._read()
        if cur is None:
            return ""
        if self._clock() - float(cur.get("renewed_at", 0)) > \
                float(cur.get("duration", 0)):
            return ""
        return str(cur.get("holder", ""))


def campaign(lease: FileLease, holder: str, duration_s: float,
             stop: threading.Event,
             poll_s: Optional[float] = None) -> bool:
    """Block until ``holder`` acquires the lease or ``stop`` is set.
    Returns True iff leading. THE campaign policy — both the HAScheduler
    replica and the scheduler binary call this, so the poll cadence
    (well inside the lease duration, upstream retryPeriod ~ duration/7.5)
    has exactly one definition."""
    poll = poll_s if poll_s is not None else max(0.02, duration_s / 5)
    while not stop.is_set():
        if lease.acquire_or_renew(holder, duration_s):
            return True
        stop.wait(poll)
    return False


def hold(lease: FileLease, holder: str, duration_s: float,
         renew_interval_s: float, stop: threading.Event) -> bool:
    """Renew until ``stop`` is set or the lease is lost. Renew-then-sleep:
    the first check runs immediately, so a lease that expired during a
    slow activation (WAL replay) is caught before a full renew interval
    of split-brain scheduling. Returns True on clean stop, False on a
    lost lease (caller must stop doing work NOW — its writes are fenced
    by the new active's WAL rotation)."""
    while not stop.is_set():
        if not lease.acquire_or_renew(holder, duration_s):
            return False
        stop.wait(renew_interval_s)
    return True


class HAScheduler:
    """One scheduler replica: campaigns, and while leading runs the full
    stack (recovered APIServer + journal + Scheduler per profile)."""

    def __init__(self, state_dir: str,
                 profiles: Optional[List[PluginProfile]] = None,
                 registry=None, identity: Optional[str] = None,
                 lease_duration_s: float = 5.0,
                 renew_interval_s: float = 1.0,
                 fsync: bool = False,
                 clock: Callable[[], float] = time.time):
        from ..config.profiles import tpu_gang_profile
        self.state_dir = state_dir
        self.profiles = profiles or [tpu_gang_profile()]
        self.registry = registry or default_registry()
        self.identity = identity or f"scheduler-{uuid.uuid4().hex[:8]}"
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s
        self.fsync = fsync
        self.lease = FileLease(state_dir, clock=clock)
        self.is_active = threading.Event()   # leading AND schedulers running
        self.demoted = threading.Event()     # lost a lease it once held
        self.api: Optional[APIServer] = None
        self.schedulers: list = []
        self._journal = None
        self._stop = threading.Event()
        self._crashed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"tpusched-ha-{self.identity}")
        self._thread.start()

    def _run(self) -> None:
        if not campaign(self.lease, self.identity, self.lease_duration_s,
                        self._stop,
                        poll_s=max(0.02, min(self.renew_interval_s,
                                             self.lease_duration_s / 5))):
            return
        klog.info_s("scheduler replica started leading",
                    identity=self.identity, stateDir=self.state_dir)
        self._activate()
        try:
            if not hold(self.lease, self.identity, self.lease_duration_s,
                        self.renew_interval_s, self._stop):
                klog.error_s(None, "scheduler lease lost; demoting",
                             identity=self.identity)
                self.demoted.set()
        finally:
            if not self._crashed.is_set():
                self._deactivate()

    def _activate(self) -> None:
        self.api = APIServer()
        self._journal = persistence.attach(self.api, self.state_dir,
                                           fsync=self.fsync)
        from .scheduler import Scheduler
        self.schedulers = [Scheduler(self.api, self.registry, p)
                           for p in self.profiles]
        for s in self.schedulers:
            s.run()
        self.is_active.set()

    def _deactivate(self) -> None:
        self.is_active.clear()
        for s in self.schedulers:
            s.stop()
        self.schedulers = []
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    def stop(self, release_lease: bool = True) -> None:
        """Clean shutdown. ``release_lease=False`` keeps the lease on disk —
        test/bench hook simulating a crash (a SIGKILLed active releases
        nothing; the standby must wait out the lease duration)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if release_lease:
            self.lease.release(self.identity)

    def crash(self) -> None:
        """Die like SIGKILL, as far as the shared state can tell: the lease
        is NOT released (the standby must wait out the duration), and the
        clean-shutdown writes (permit-barrier rejections → unreserve →
        annotation patches) are disconnected from the journal FIRST, so
        nothing the dying replica does after "death" reaches the WAL. Only
        records accepted before the crash drain to disk."""
        self._crashed.set()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.is_active.clear()
        if self.api is not None:
            self.api.set_persistence_sink(None)
        for s in self.schedulers:
            s.stop()
        self.schedulers = []
        if self._journal is not None:
            self._journal.close()
            self._journal = None
