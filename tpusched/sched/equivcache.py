"""Equivalence-class scheduling cache: memoized PreFilter/Filter outcomes
for gang siblings (and identical singletons).

A 256-member slice gang is 256 equivalent pods popped back-to-back; without
this cache every member pays an identical PreFilter sweep (topology
occupancy + placement membership + gang bookkeeping) and a full per-node
Filter pass. An entry memoizes, for one equivalence class
(util/equivalence.equivalence_key):

- the PreFilter-written CycleState data (the TopologyMatch stash, claims
  guard set, quota snapshots, ...),
- the PreFilter-restricted candidate node set,
- the skip-Filter plugin set, and
- the node names that passed the full Filter sweep (the feasible set).

Validity is the strict triple the cache is keyed on:

- ``armed_mutation`` — the scheduler cache's mutation cursor. ANY node or
  pod mutation invalidates; the one sanctioned exception is the chain of
  the scheduler's own assumes for this same class: after a cycle assumes
  its pod, the scheduler re-arms the entry iff the cursor advanced by
  EXACTLY one (its own attach) — a concurrent foreign mutation breaks the
  chain and the entry dies at the next lookup.
- ``nominator_gen`` — the PodNominator generation. Nominated preemptors
  change per-node filter semantics (the dry-run path), so the fast path
  additionally requires an EMPTY nominator; the generation catches
  nominate→un-nominate races between cycles.
- ``fingerprints`` — per-plugin key material (EquivalenceAware) covering
  inputs the mutation cursor cannot see: PodGroup/topology CR resource
  versions, denial windows, freed-window claims, sibling counts.

Exactness contract (why a hit cannot drift from the full path): between
arming and lookup the only cluster change is assumes of pods from the SAME
class. Those only consume resources, so per-node Filter failures are
monotone — a node outside the feasible set stays outside. Nodes inside it
are re-checked by the still-running *dynamic* filters (resource/chip fit);
*static* filters (selector, taints, name, cordon, cached-stash membership)
re-run would read byte-identical inputs, so they are skipped. Score always
runs fresh on the live snapshot. Plugins whose PreFilter output is not
provably reusable veto entry creation via their fingerprint (e.g.
TopologyMatch vetoes multi-window placements). Quota admission is the
interesting case (ISSUE 14): a memoized verdict goes stale with every
sibling assume (usage moves; not-monotone), so under UNGUARDED commits
(single dispatch loop, the legacy serialize arm) CapacityScheduling still
vetoes — but under GUARDED commits (sharded dispatch) it fingerprints
only the quota BOUNDS and lets entries stay warm: the memoized
``QuotaReserve`` rides the entry into the sibling's commit, where
``Cache.assume_pod_guarded`` re-evaluates the admission bounds against
the live ledger and refuses exactly the stale case (the hit then falls
back to the full path). The safety argument for quota'd hits is that
commit-time semantic re-check, not snapshot freshness. The full path
stays the oracle: nominated pods bypass the cache entirely, and the
scheduler's differential mode re-runs the full path on every hit and
asserts the identical placement.

Single-threaded by design: only the scheduleOne loop touches it —
declared via @util.locking.thread_confined, asserted in debug mode
(the chaos soaks run with it on).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, FrozenSet, Hashable, Optional, Tuple

from ..util.locking import thread_confined

# Entries are per equivalence class; a handful of gangs plus singleton
# templates are live at once, so a small LRU bound is plenty.
DEFAULT_CAPACITY = 256


class EquivEntry:
    __slots__ = ("key", "armed_mutation", "armed_pool_cursors",
                 "nominator_gen", "fingerprints", "prefilter_data",
                 "skip_filter", "restricted", "feasible")

    def __init__(self, key: Hashable, fingerprints: Tuple,
                 nominator_gen: int, prefilter_data: Dict,
                 skip_filter: FrozenSet[str],
                 restricted: Optional[FrozenSet[str]],
                 feasible: Tuple[str, ...]):
        self.key = key
        self.armed_mutation = -1          # set by arm(); -1 never matches
        # Shard-lane validity witness (sharded dispatch): the partition's
        # ((pool, cursor), ...) tuple at arming.  A shard's entry stays
        # valid while ITS pools are untouched — foreign assumes in other
        # shards' pools no longer break the chain the way any global-cursor
        # advance does on the single-lane protocol.  None on single-lane
        # entries (they use armed_mutation).
        self.armed_pool_cursors: Optional[Tuple] = None
        self.nominator_gen = nominator_gen
        self.fingerprints = fingerprints
        self.prefilter_data = prefilter_data
        self.skip_filter = skip_filter
        self.restricted = restricted
        self.feasible = feasible


@thread_confined
class EquivalenceCache:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._capacity = capacity
        self._entries: "OrderedDict[Hashable, EquivEntry]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: Hashable) -> Optional[EquivEntry]:
        ent = self._entries.get(key)
        if ent is not None:
            self._entries.move_to_end(key)
        return ent

    def drop(self, key: Hashable) -> None:
        self._entries.pop(key, None)

    def arm(self, entry: EquivEntry, mutation_cursor: int,
            pool_cursors: Optional[Tuple] = None) -> None:
        """(Re)arm ``entry`` as valid exactly at ``mutation_cursor`` and
        (re)insert it. The caller has verified the cursor advanced by
        exactly its own assume since the state the entry describes.
        ``pool_cursors``: the partition cursor tuple for shard-lane
        entries (their validity witness instead of the global cursor)."""
        entry.armed_mutation = mutation_cursor
        entry.armed_pool_cursors = pool_cursors
        self._entries[entry.key] = entry
        self._entries.move_to_end(entry.key)
        while len(self._entries) > self._capacity:
            self._entries.popitem(last=False)

    def clear(self) -> None:
        self._entries.clear()
