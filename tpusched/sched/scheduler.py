"""The scheduler: scheduleOne loop + async binding cycles.

Rebuild of the hosting loop the reference plugs into (SURVEY §3.2):
pop from activeQ (QueueSort order) → snapshot → PreFilter → Filter (per node)
→ [PostFilter on failure] → Score → select → assume → Reserve → Permit →
async binding cycle (WaitOnPermit → PreBind → Bind → PostBind). Each binding
cycle runs on its own thread, crossing the same "goroutine boundary" as
upstream (vendored scheduler.go:425,557-604 in the reference tree).
"""
from __future__ import annotations

import collections
import os
import queue
import threading
import time
from typing import Dict, List, Optional

from ..api.core import Binding, Node, Pod
from ..apiserver import Clientset, InformerFactory
from ..apiserver import server as srv
from ..fwk import (CycleState, Framework, Handle, PluginProfile, Registry,
                   Status, PODS_TO_ACTIVATE_KEY, PodsToActivate)
from ..fwk.interfaces import (EVENT_ADD, EVENT_DELETE, EVENT_UPDATE,
                              RESOURCE_ELASTIC_QUOTA, RESOURCE_NODE,
                              RESOURCE_POD, RESOURCE_POD_GROUP,
                              RESOURCE_TPU_TOPOLOGY)
from .. import trace
from ..util import klog
from ..util.equivalence import equivalence_key
from ..util.metrics import (bind_total, e2e_scheduling_seconds,
                            equiv_cache_bypasses,
                            equiv_cache_differential_mismatches,
                            equiv_cache_fallbacks, equiv_cache_hits,
                            equiv_cache_invalidations, equiv_cache_misses,
                            equiv_cache_vetoes, extension_point_seconds,
                            queue_wait_seconds, schedule_attempts)
from ..util.podutil import assigned
from .cache import Cache
from .equivcache import EquivalenceCache, EquivEntry
from .queue import QueuedPodInfo, SchedulingQueue

# CycleState keys the equivalence cache must NOT memoize: per-cycle
# scheduler plumbing, re-created fresh by every cycle.
_EQUIV_EXCLUDE_KEYS = frozenset((PODS_TO_ACTIVATE_KEY, "tpusched/diagnosis"))

_KIND_TO_RESOURCE = {
    srv.PODS: RESOURCE_POD,
    srv.NODES: RESOURCE_NODE,
    srv.POD_GROUPS: RESOURCE_POD_GROUP,
    srv.ELASTIC_QUOTAS: RESOURCE_ELASTIC_QUOTA,
    srv.TPU_TOPOLOGIES: RESOURCE_TPU_TOPOLOGY,
}


class _BindingPool:
    """Bounded DAEMON-thread task pool for post-permit binding work.

    Not concurrent.futures: its workers are non-daemon and joined by an
    atexit hook, so one wedged Bind API call would block both stop() and
    interpreter exit forever. Daemon workers + a bounded-join drain keep the
    old thread-per-bind shutdown contract — a stuck bind delays stop() by at
    most the drain timeout and can never pin the process."""

    def __init__(self, workers: int):
        self._q: "queue.Queue" = queue.Queue()
        self._open = True
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"tpusched-bind-{i}")
            for i in range(workers)]
        for t in self._threads:
            t.start()

    def submit(self, fn, *args) -> None:
        if not self._open:
            raise RuntimeError("binding pool is shut down")
        self._q.put((fn, args))

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception as e:  # a binding task must never kill a worker
                klog.error_s(e, "binding task panicked")

    def shutdown(self, timeout: float = 5.0) -> None:
        """Queued tasks drain first (FIFO before the sentinels); workers are
        then joined with a shared bounded deadline. Tasks racing past the
        open-check are drained inline afterwards so no pod's failure path is
        silently dropped."""
        self._open = False
        for _ in self._threads:
            self._q.put(None)
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                fn, args = item
                try:
                    fn(*args)
                except Exception as e:
                    klog.error_s(e, "binding task panicked during drain")


class Scheduler:
    def __init__(self, api: srv.APIServer, registry: Registry,
                 profile: PluginProfile, clock=time.time,
                 recorder: Optional["trace.FlightRecorder"] = None):
        self.api = api
        self.clock = clock
        # Scheduling flight recorder (tpusched/trace): every cycle emits a
        # span tree into the process-global ring unless a private recorder
        # is injected (bench/test isolation).
        self.recorder = recorder if recorder is not None \
            else trace.default_recorder()
        self.clientset = Clientset(api)
        self.informer_factory = InformerFactory(api)
        self.cache = Cache(clock)
        self.profile = profile

        self._fw: Optional[Framework] = None
        self.handle = Handle(self.clientset, self.informer_factory,
                             lambda: self._fw, clock)
        self._fw = Framework(registry, profile, self.handle)

        # Plugins without EnqueueExtensions default to all-events (upstream
        # semantics: only declared hints narrow the requeue set).
        from ..fwk.interfaces import EnqueueExtensions, WILDCARD_EVENT
        cluster_event_map = {}
        for name, plugin in self._fw.plugins.items():
            if isinstance(plugin, EnqueueExtensions):
                cluster_event_map[name] = plugin.events_to_register()
            else:
                cluster_event_map[name] = [WILDCARD_EVENT]
        self.queue = SchedulingQueue(
            self._fw.less, cluster_event_map, clock,
            initial_backoff_s=profile.pod_initial_backoff_s,
            max_backoff_s=profile.pod_max_backoff_s)
        # upstream pending_pods{queue="active|backoff|unschedulable"} gauges,
        # computed at scrape time from the live queue. weakref: the global
        # registry must not keep a stopped scheduler (and everything it
        # holds) alive through the provider closure
        import weakref
        from ..util.metrics import REGISTRY
        queue_ref = weakref.ref(self.queue)
        # scheduler label: one process can host several profiles (upstream
        # shares ONE queue across profiles; here each profile owns a queue,
        # so the label keeps N schedulers from clobbering each other's gauge)
        # escape per the Prometheus text format: the name is the one
        # user-controlled string that reaches a label value
        esc = (profile.scheduler_name.replace("\\", r"\\")
               .replace('"', r'\"').replace("\n", r"\n"))
        sched_label = f'scheduler="{esc}",' if profile.scheduler_name else ""
        for q in ("active", "backoff", "unschedulable"):
            def depth(q=q, ref=queue_ref):
                live = ref()
                # None = dead provider: the registry prunes this series at
                # the next scrape instead of emitting stale zeros forever
                # (HA failover / what-if restarts construct schedulers
                # under fresh label sets constantly)
                return live.pending_counts()[q] if live is not None else None
            REGISTRY.gauge_func("tpusched_pending_pods", depth,
                                "Pods pending per scheduling sub-queue.",
                                labels=f'{sched_label}queue="{q}"')

        # adaptive node sampling (upstream percentageOfNodesToScore):
        # profile value 0 ⇒ adaptive 50 - nodes/125, floor 5%; round-robin
        # start index spreads scan load across cycles
        self.percentage_of_nodes_to_score = profile.percentage_of_nodes_to_score
        self._next_start_node_index = 0

        # per-node Filter/Score parallelism (upstream parallelism=16); the
        # pool is shared by the filter sweep and the score pass
        from ..util.parallelize import Parallelizer
        self._par = Parallelizer(profile.parallelism)
        self._fw.parallelizer = self._par

        # Equivalence-class scheduling cache (sched/equivcache.py): gang
        # siblings popped back-to-back skip straight to Score over the
        # memoized feasible set. Touched only by the scheduleOne thread.
        self._equiv_cache: Optional[EquivalenceCache] = (
            EquivalenceCache() if profile.equiv_cache else None)
        self._equiv_differential = profile.equiv_cache_differential
        # (entry, cycle cursor) awaiting arming: set by the cycle that built
        # or reused the entry, consumed right after assume_pod — the only
        # point where "the cursor advanced by EXACTLY my own attach" can be
        # verified.
        self._equiv_pending: Optional[tuple] = None

        self._stop = threading.Event()
        self._sched_thread: Optional[threading.Thread] = None
        # Binding cycles run on a bounded pool, dispatched only when the
        # permit barrier RESOLVES (Framework.notify_on_permit) — not one
        # parked thread per member. A 256-pod gang therefore costs zero
        # binding threads while waiting and at most pool-width while
        # draining, instead of 256 spawns + 256 blocked stacks per gang.
        self._bind_pool = _BindingPool(max(4, min(16, os.cpu_count() or 4)))
        self._wire_informers()

    @property
    def framework(self) -> Framework:
        return self._fw

    @property
    def running(self) -> bool:
        """Readiness: the scheduleOne loop is up and not shutting down."""
        return (self._sched_thread is not None
                and self._sched_thread.is_alive()
                and not self._stop.is_set())

    # -- informer wiring ------------------------------------------------------

    def _responsible(self, pod: Pod) -> bool:
        return pod.spec.scheduler_name == self.profile.scheduler_name

    def _wire_informers(self) -> None:
        pods = self.informer_factory.pods()
        pods.add_event_handler(
            on_add=self._on_pod_add,
            on_update=self._on_pod_update,
            on_delete=self._on_pod_delete)
        nodes = self.informer_factory.nodes()
        nodes.add_event_handler(
            on_add=lambda n: (self.cache.add_node(n),
                              self.queue.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_ADD)),
            on_update=lambda old, new: (self.cache.update_node(new),
                                        self.queue.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_UPDATE)),
            on_delete=lambda n: (self.cache.remove_node(n),
                                 self.queue.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_DELETE)))
        for kind in (srv.POD_GROUPS, srv.ELASTIC_QUOTAS, srv.TPU_TOPOLOGIES):
            res = _KIND_TO_RESOURCE[kind]
            self.informer_factory.informer(kind).add_event_handler(
                on_add=lambda o, r=res: self.queue.move_all_to_active_or_backoff(r, EVENT_ADD),
                on_update=lambda o, n, r=res: self.queue.move_all_to_active_or_backoff(r, EVENT_UPDATE),
                on_delete=lambda o, r=res: self.queue.move_all_to_active_or_backoff(r, EVENT_DELETE),
                replay=False)

    def _on_pod_add(self, pod: Pod) -> None:
        if assigned(pod):
            self.cache.add_pod(pod)
            self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_ADD)
        elif self._responsible(pod):
            self.queue.add(pod)

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        if assigned(new):
            # a bound pod is no longer a nominated (in-flight preemptor) —
            # leaving it nominated would double-count it against quotas
            self.handle.pod_nominator.delete_nominated_pod_if_exists(new)
        else:
            self.handle.pod_nominator.update_nominated_pod(old, new)
        if assigned(new):
            if not assigned(old):
                # our own bind confirmation (or an external bind)
                self.cache.add_pod(new)
                self.queue.delete(new)
            else:
                self.cache.update_pod(new)
            self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_UPDATE)
        elif self._responsible(new):
            self.queue.update(new)

    def _on_pod_delete(self, pod: Pod) -> None:
        self.handle.pod_nominator.delete_nominated_pod_if_exists(pod)
        if assigned(pod):
            self.cache.remove_pod(pod)
            self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_DELETE)
        else:
            self.queue.delete(pod)
        # a waiting gang member deleted mid-permit must be rejected
        self._fw.reject_waiting_pod(pod.meta.uid, msg=f"pod {pod.key} deleted")

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> None:
        self._sched_thread = threading.Thread(target=self._loop,
                                              name="tpusched-scheduleOne",
                                              daemon=True)
        self._sched_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        # unblock waiting gang members; their resolution callbacks enqueue
        # the (failing) binding tasks, which the pool drains before exit
        self._fw.iterate_over_waiting_pods(
            lambda wp: wp.reject("", "scheduler shutting down"))
        if self._sched_thread:
            self._sched_thread.join(timeout=5)
        self._bind_pool.shutdown(timeout=5.0)
        self._par.close()
        self._fw.close()
        # detach this scheduler's informers from the API server's watch
        # fan-out: a stopped scheduler must not keep consuming every write
        # (HA fail-over and the what-if planner restart schedulers against
        # a live server)
        self.informer_factory.close()

    def _loop(self) -> None:
        while not self._stop.is_set():
            info = self.queue.pop(timeout=0.5)
            if info is None:
                continue
            try:
                self.schedule_one(info)
            except Exception as e:
                klog.error_s(e, "scheduleOne panicked", pod=info.pod.key)
                self._handle_failure(info, Status.error(str(e)))

    # -- one scheduling cycle -------------------------------------------------

    def schedule_one(self, info: QueuedPodInfo) -> None:
        pod = info.pod
        # skip pods deleted/bound while queued
        live = self.api.try_get(srv.PODS, pod.key)
        if live is None or assigned(live) or live.is_terminating():
            return
        pod = live
        info.pod = live
        schedule_attempts.inc()
        start = self.clock()

        # flight recorder: one cycle trace per attempt, active on this
        # thread (klog/Events correlate via the id) until the cycle either
        # resolves or parks at the permit barrier; committed to the ring
        # unconditionally so even a still-waiting cycle is inspectable
        queue_wait_seconds.observe(max(0.0, start - info.timestamp))
        tr = None
        if trace.enabled():
            tr = self.recorder.begin_cycle(
                pod, info, start, scheduler=self.profile.scheduler_name)
        token = trace.activate(tr)
        try:
            self._schedule_cycle(info, pod, tr, start)
        except Exception as e:
            if tr is not None:
                tr.add_anomaly("cycle_panic", error=str(e))
                tr.finish("error")
            raise
        finally:
            if tr is not None:
                # cycles that resolved inside the scheduling half take the
                # fused commit+finalize (the permit-wait path finalizes
                # from the binding thread instead)
                self.recorder.commit(
                    tr, final=tr.outcome not in ("scheduling",
                                                 "waiting-permit", "bound"),
                    now=self.clock())
            trace.deactivate(token)

    def _schedule_cycle(self, info: QueuedPodInfo, pod: Pod,
                        tr, start: float) -> None:
        state = CycleState()
        pods_to_activate = PodsToActivate()
        state.write(PODS_TO_ACTIVATE_KEY, pods_to_activate)

        snapshot = self.cache.snapshot()
        self.handle.set_snapshot(snapshot)

        node_name, status = self._schedule_pod(state, pod, snapshot)
        if not status.is_success():
            self._run_post_filter(state, pod, status)
            if tr is not None:
                tr.finish("error" if status.is_error() else "unschedulable",
                          status=status,
                          diagnosis=state.try_read("tpusched/diagnosis"))
            self._handle_failure(info, status)
            self._activate_pods(pods_to_activate)
            return

        # clear any stale nomination; assume so parallel cycles see the pod
        self.handle.pod_nominator.delete_nominated_pod_if_exists(pod)
        assumed = pod.deepcopy()
        self.cache.assume_pod(assumed, node_name)
        # the sanctioned cursor advance: (re)arm the cycle's equivalence
        # entry iff the assume was the ONLY mutation since the snapshot
        self._equiv_after_assume()

        s = self._timed_point("Reserve", self._fw.run_reserve_plugins_reserve,
                              state, assumed, node_name)
        if not s.is_success():
            self._fw.run_reserve_plugins_unreserve(state, assumed, node_name)
            self._forget_and_signal(assumed)
            if tr is not None:
                tr.finish("reserve-failed", status=s, node=node_name)
            self._handle_failure(info, s)
            self._activate_pods(pods_to_activate)
            return

        s = self._timed_point("Permit", self._fw.run_permit_plugins,
                              state, assumed, node_name)
        if not s.is_success() and not s.is_wait():
            self._fw.run_reserve_plugins_unreserve(state, assumed, node_name)
            self._forget_and_signal(assumed)
            if tr is not None:
                tr.finish("permit-rejected", status=s, node=node_name)
            self._handle_failure(info, s)
            self._activate_pods(pods_to_activate)
            return

        if tr is not None and s.is_wait():
            # parked at the permit barrier: record which plugins hold it so
            # a wedged gang is explainable from the dump before any timeout
            wp = self._fw.get_waiting_pod(assumed.meta.uid)
            tr.mark_waiting(wp.get_pending_plugins() if wp else [])
            tr.node = node_name

        # sibling activation happens at end of the scheduling cycle
        self._activate_pods(pods_to_activate)

        def on_permit_resolved(permit_status: Status,
                               args=(state, info, assumed, node_name, start,
                                     pods_to_activate, tr)) -> None:
            try:
                self._bind_pool.submit(self._finish_binding, permit_status,
                                       *args)
            except RuntimeError:
                # pool already shut down (scheduler stopping): run the
                # failure path inline so the pod is not silently leaked
                self._finish_binding(permit_status, *args)

        self._fw.notify_on_permit(assumed, on_permit_resolved)

    def _timed_point(self, point: str, fn, *args):
        """framework_extension_point_duration_seconds recorder (upstream
        parity; see the metric's divergence note in util/metrics.py) — and
        the extension-point span of the active cycle trace (per-plugin
        child spans attach underneath via fwk.runtime._timed_plugin). The
        span reuses the metric's perf_counter reads: tracing adds one tuple
        append to the serial scheduleOne thread, nothing more."""
        hist = extension_point_seconds.with_labels(point)
        t0 = time.perf_counter()
        try:
            return fn(*args)
        finally:
            dur = time.perf_counter() - t0
            hist.observe(dur)
            tr = trace.current()
            if tr is not None:
                # inlined CycleTrace.add_event (hot write path)
                ev = tr._events
                if len(ev) < trace.MAX_SPANS_PER_TRACE:
                    ev.append((point, t0 - tr.perf_start, dur, None))
                else:
                    tr.truncated += 1

    def _schedule_pod(self, state: CycleState, pod: Pod, snapshot):
        """genericScheduler.Schedule analog: prefilter → filter → score —
        with the equivalence-class fast path in front: a gang sibling whose
        class has a valid cache entry skips PreFilter and the static
        filters entirely and goes straight to a dynamic re-filter + Score
        over the memoized feasible set."""
        self._equiv_pending = None
        num_nodes = snapshot.num_nodes()
        if num_nodes == 0:
            return "", Status.unschedulable("no nodes available")
        entry = self._equiv_lookup(pod)
        if entry is not None:
            result = self._schedule_from_cache(state, pod, snapshot, entry)
            if result is not None:
                return result
            # cached feasible set drained (or differential mismatch): the
            # entry is dropped and the full path runs as the oracle
            trace.annotate("equiv_cache", "fallback")
        return self._schedule_full(state, pod, snapshot, record=True)

    def _schedule_full(self, state: CycleState, pod: Pod, snapshot,
                       record: bool = False):
        """The full per-node path — always the oracle. ``record``: offer the
        completed cycle to the equivalence cache (False for differential
        re-runs, which must be side-effect-free on the cache)."""
        num_nodes = snapshot.num_nodes()
        s = self._timed_point("PreFilter", self._fw.run_pre_filter_plugins,
                              state, pod)
        if not s.is_success():
            if s.is_error():
                return "", s
            diagnosis = {n: s for n in snapshot.node_names()}
            state.write("tpusched/diagnosis", diagnosis)
            return "", s

        infos = snapshot.list()
        # PreFilterResult.NodeNames (upstream findNodesThatPassFilters):
        # a PreFilter that resolved the only viable hosts narrows the sweep
        rset = state.restricted_node_names
        if rset is not None:
            infos = [i for i in infos if i.node.name in rset]
            if not infos:
                return "", Status.unschedulable(
                    f"0/{num_nodes} nodes are available: none match the "
                    "PreFilter node set")
        want = self._num_feasible_nodes_to_find(len(infos))
        feasible, diagnosis, error = self._timed_point(
            "Filter", self._find_feasible, state, pod, infos, want)
        if error is not None:
            return "", error
        state.write("tpusched/diagnosis", diagnosis)

        if not feasible:
            # upstream-style aggregation: "0/N nodes are available:
            # 3 Insufficient google.com/tpu, 1 node(s) had untolerated taint"
            counts = collections.Counter(
                r for st in diagnosis.values()
                for r in (st.reasons or ["unknown"]))
            detail = ", ".join(f"{n} {r}" for r, n in counts.most_common())
            msg = (f"0/{num_nodes} nodes are available: {detail}"
                   if detail else f"0/{num_nodes} nodes are available")
            return "", Status.unschedulable(msg).with_plugin(
                next(iter(diagnosis.values())).plugin if diagnosis else "")
        # snapshot the data map BEFORE Score: an entry memoizes PreFilter/
        # Filter state only. Score-phase writes (per-node raw-score dicts
        # etc.) are per-cycle and often plain dicts with no .clone() —
        # letting them into an entry would share them by reference with
        # every hit cycle's Score, mutating the cached original in place.
        prefilter_export = None
        if record and self._equiv_cache is not None:
            prefilter_export = state.export(exclude=_EQUIV_EXCLUDE_KEYS)
        node_name, status = self._select_host(state, pod, feasible)
        if record and status.is_success():
            # a sampled sweep (want < candidates) is a partial feasible set:
            # memoizing it would pin siblings to the sample
            self._equiv_offer(pod, state, feasible,
                              swept_all=want >= len(infos),
                              prefilter_data=prefilter_export)
        return node_name, status

    def _select_host(self, state: CycleState, pod: Pod, feasible):
        """PreScore → Score → deterministic argmax. Shared verbatim by the
        full path and the cache-hit path so the two cannot diverge in
        selection semantics."""
        if len(feasible) == 1:
            return feasible[0].name, Status.success()
        s = self._timed_point("PreScore", self._fw.run_pre_score_plugins,
                              state, pod, feasible)
        if not s.is_success():
            return "", s
        totals, s = self._timed_point("Score", self._fw.run_score_plugins,
                                      state, pod, feasible)
        if not s.is_success():
            return "", s
        best = max(feasible, key=lambda n: (totals.get(n.name, 0), n.name))
        return best.name, Status.success()

    # -- equivalence-class fast path (sched/equivcache.py) --------------------

    def _equiv_lookup(self, pod: Pod) -> Optional[EquivEntry]:
        """Return a VALID entry for the pod's class or None. Validity is the
        strict triple: mutation cursor at the snapshot this cycle's filters
        read, nominator generation, and every EquivalenceAware plugin's
        recomputed fingerprint."""
        if self._equiv_cache is None:
            return None
        nominator = self.handle.pod_nominator
        if not nominator.empty():
            # nominated preemptors change per-node filter semantics (the
            # dry-run path): the full path is mandatory
            equiv_cache_bypasses.inc()
            trace.annotate("equiv_cache", "bypass")
            return None
        key = equivalence_key(pod)
        entry = self._equiv_cache.get(key)
        if entry is None:
            equiv_cache_misses.inc()
            trace.annotate("equiv_cache", "miss")
            return None
        if (entry.armed_mutation != self.cache.snapshot_cursor()
                or entry.nominator_gen != nominator.generation
                or entry.fingerprints != self._equiv_fingerprints(pod, None)):
            self._equiv_cache.drop(key)
            equiv_cache_invalidations.inc()
            trace.annotate("equiv_cache", "invalidated")
            return None
        return entry

    def _equiv_fingerprints(self, pod: Pod, state: Optional[CycleState]):
        """Tuple of (plugin, fingerprint) over the EquivalenceAware plugins,
        or None if any plugin vetoes."""
        fps = []
        for p in self._fw.equiv_aware_plugins:
            fp = p.equiv_fingerprint(pod, state)
            if fp is None:
                return None
            fps.append((p.name(), fp))
        return tuple(fps)

    def _schedule_from_cache(self, state: CycleState, pod: Pod, snapshot,
                             entry: EquivEntry):
        """The hit path: dynamic re-filter over the cached feasible set,
        then the shared Score tail. Returns (node, status) or None to fall
        back to the full path (entry already dropped)."""
        fw = self._fw
        # work on a throwaway state first: a fallback must leave the real
        # cycle state untouched (CapacityScheduling reuses a pre-existing
        # EQ snapshot key if one is present)
        cstate = CycleState()
        cstate.install(entry.prefilter_data)
        cstate.skip_filter_plugins |= set(entry.skip_filter)
        if entry.restricted is not None:
            cstate.restricted_node_names = set(entry.restricted)
        infos = []
        for name in entry.feasible:
            node_info = snapshot.get(name)
            if node_info is None:
                # a vanished node always bumps the cursor, so this is
                # unreachable in practice — belt and braces
                self._equiv_cache.drop(entry.key)
                equiv_cache_invalidations.inc()
                return None
            infos.append(node_info)
        # batch-capable dynamics keep their vectorized path on hits: one
        # fused resource-fit pass over the cached set, exactly as the full
        # path's pre-pass (the hit path guarantees an empty nominator, the
        # same condition the full path gates its batch pass on)
        tr = trace.current()
        # any fallback truncates the event log back to here: an abandoned
        # hit attempt must not leave its Filter/PreScore/Score spans next
        # to the full path's own set (double-counted roots in the dump)
        mark = len(tr._events) if tr is not None else 0
        t0 = time.perf_counter()

        def fallback():
            self._equiv_cache.drop(entry.key)
            equiv_cache_fallbacks.inc()
            if tr is not None:
                del tr._events[mark:]
            return None

        batch_fail, _ = self._run_batch_filters(
            fw.dynamic_batch_filter_plugins, cstate, pod, infos)
        feasible = []
        diagnosis: Dict[str, Status] = {}
        for i, node_info in enumerate(infos):
            fs = batch_fail[i]
            if fs is None:
                fs = fw.run_dynamic_filter_plugins(cstate, pod, node_info)
            if fs.is_success():
                feasible.append(node_info.node)
            elif fs.is_error():
                return fallback()
            else:
                diagnosis[node_info.node.name] = fs
        if not feasible:
            # the gang burst consumed every cached host: the full path
            # re-derives feasibility (and owns the unschedulable messaging)
            return fallback()
        if tr is not None:
            tr.add_event("Filter", t0, time.perf_counter() - t0,
                         {"equiv_cache": "hit"})
        node_name, status = self._select_host(cstate, pod, feasible)
        if not status.is_success():
            return fallback()
        if self._equiv_differential:
            full_node = self._differential_check(pod, snapshot, node_name)
            if full_node != node_name:
                return fallback()
        equiv_cache_hits.inc()
        trace.annotate("equiv_cache", "hit")
        # commit the throwaway state into the cycle: Reserve/Permit plugins
        # read the PreFilter stashes from it (e.g. TopologyMatch's
        # coordinate assignment). By-reference adopt — cstate dies here.
        state.adopt(cstate)
        state.skip_filter_plugins |= cstate.skip_filter_plugins
        state.restricted_node_names = cstate.restricted_node_names
        state.write("tpusched/diagnosis", diagnosis)
        self._equiv_pending = (entry, self.cache.snapshot_cursor())
        return node_name, status

    def _differential_check(self, pod: Pod, snapshot, cached_node: str):
        """Oracle assertion (equiv_cache_differential profiles only): re-run
        the FULL path on a fresh state and compare placements. Returns the
        full path's chosen node ('' on failure). Runs UNTRACED: the oracle's
        extension-point spans would double-count into the live cycle's
        flight-recorder entry."""
        token = trace.activate(None)
        try:
            full_state = CycleState()
            full_state.write(PODS_TO_ACTIVATE_KEY, PodsToActivate())
            full_node, full_status = self._schedule_full(
                full_state, pod, snapshot, record=False)
        finally:
            trace.deactivate(token)
        if full_node != cached_node or not full_status.is_success():
            equiv_cache_differential_mismatches.inc()
            klog.error_s(
                RuntimeError("equivalence-cache placement drift"),
                "cached placement differs from full path", pod=pod.key,
                cached=cached_node, full=full_node,
                full_status=full_status.message())
        return full_node

    def _equiv_offer(self, pod: Pod, state: CycleState, feasible,
                     swept_all: bool, prefilter_data: Dict) -> None:
        """Offer a completed full cycle as a cache entry (pending until the
        assume verifies the cursor chain). ``prefilter_data`` is the data
        map exported BEFORE Score ran — the only state an entry may hold."""
        if self._equiv_cache is None or not swept_all:
            return
        nominator = self.handle.pod_nominator
        if not nominator.empty():
            return
        key = equivalence_key(pod)
        fps = self._equiv_fingerprints(pod, state)
        if fps is None:
            equiv_cache_vetoes.inc()
            return
        entry = EquivEntry(
            key, fps, nominator.generation,
            prefilter_data,
            frozenset(state.skip_filter_plugins),
            (frozenset(state.restricted_node_names)
             if state.restricted_node_names is not None else None),
            tuple(sorted(n.name for n in feasible)))
        self._equiv_pending = (entry, self.cache.snapshot_cursor())

    def _equiv_after_assume(self) -> None:
        """Arm the pending entry iff the cursor advanced by EXACTLY the
        cycle's own assume; any concurrent foreign mutation breaks the
        chain and the entry is discarded."""
        pending, self._equiv_pending = self._equiv_pending, None
        if pending is None or self._equiv_cache is None:
            return
        entry, cycle_cursor = pending
        if self.cache.mutation_cursor() == cycle_cursor + 1:
            self._equiv_cache.arm(entry, cycle_cursor + 1)
        else:
            self._equiv_cache.drop(entry.key)

    @staticmethod
    def _run_batch_filters(plugins, state: CycleState, pod: Pod, infos):
        """First-failure-wins batch pre-pass, shared by _find_feasible and
        the equivalence-cache hit path so their batch semantics cannot
        drift. Returns (per-node failure list aligned with ``infos``,
        frozenset of plugin names that ran)."""
        batch_fail: List[Optional[Status]] = [None] * len(infos)
        names = []
        for p in plugins:
            if p.name() in state.skip_filter_plugins:
                continue
            names.append(p.name())
            res = p.filter_batch(state, pod, infos)
            for i, st in enumerate(res):
                if st is not None and batch_fail[i] is None:
                    batch_fail[i] = st.with_plugin(p.name())
        return batch_fail, frozenset(names)

    def _find_feasible(self, state: CycleState, pod: Pod, infos,
                       want: int):
        """findNodesThatPassFilters analog (generic_scheduler.go:266), in two
        stages tuned for Python-on-TPU-control-plane economics:

        1. a vectorized batch pre-pass: every BatchFilterPlugin evaluates the
           WHOLE candidate list in one numpy-backed call (no per-node Python
           dispatch, no GIL contention);
        2. a chunked thread-pool sweep running the remaining per-node plugins
           in round-robin order from the rotating start index, stopping once
           ``want`` feasible nodes are found (upstream ParallelizeUntil).

        The batch results are only consumed while no nominated pods exist —
        a preemption dry-run adds nominated pods to per-node state the batch
        pass never saw, so those cycles take the full per-node path.
        Returns (feasible_nodes, diagnosis, error_status_or_None).
        """
        n = len(infos)
        start = self._next_start_node_index % n
        fw = self._fw
        nominator_empty = self.handle.pod_nominator.empty()

        batch_fail: List[Optional[Status]] = [None] * n
        exclude: frozenset = frozenset()
        if nominator_empty and fw.batch_filter_plugins:
            batch_fail, exclude = self._run_batch_filters(
                fw.batch_filter_plugins, state, pod, infos)

        feasible: List[Node] = []
        diagnosis: Dict[str, Status] = {}
        errors: List[Status] = []
        lock = threading.Lock()
        visited = [0]

        def work(idx: int) -> None:
            oi = (start + idx) % n
            node_info = infos[oi]
            fs = batch_fail[oi]
            if fs is None:
                fs = fw.run_filter_plugins_with_nominated_pods(
                    state, pod, node_info, exclude)
                if fs.is_success():
                    with lock:
                        visited[0] += 1
                        feasible.append(node_info.node)
                    return
            with lock:
                visited[0] += 1
                if fs.is_error():
                    errors.append(fs)
                else:
                    diagnosis[node_info.node.name] = fs

        self._par.until(
            n, work, stop=lambda: len(feasible) >= want or bool(errors))
        self._next_start_node_index = (start + max(visited[0], 1)) % n
        if errors:
            return [], {}, errors[0]
        return feasible, diagnosis, None

    def _num_feasible_nodes_to_find(self, num_all: int) -> int:
        """Upstream numFeasibleNodesToFind (generic_scheduler.go): scan every
        node on small clusters; above minFeasibleNodesToFind=100, sample an
        adaptive percentage (50 - nodes/125, floor 5%) of the cluster."""
        MIN_FEASIBLE = 100
        if num_all < MIN_FEASIBLE:
            return num_all
        pct = self.percentage_of_nodes_to_score
        if pct <= 0:
            pct = max(5, 50 - num_all // 125)
        if pct >= 100:
            return num_all
        return max(MIN_FEASIBLE, num_all * pct // 100)

    def _run_post_filter(self, state: CycleState, pod: Pod, status: Status) -> None:
        from ..fwk.status import UNSCHEDULABLE
        if status.code != UNSCHEDULABLE or not self._fw.post_filter_plugins:
            return
        diagnosis = state.try_read("tpusched/diagnosis") or {}
        result, pf_status = self._timed_point(
            "PostFilter", self._fw.run_post_filter_plugins, state, pod,
            diagnosis)
        if pf_status.is_success() and result and result.nominated_node_name:
            node = result.nominated_node_name
            try:
                self.api.patch(srv.PODS, pod.key,
                               lambda p: setattr(p.status, "nominated_node_name", node))
            except srv.NotFound:
                return
            pod.status.nominated_node_name = node
            self.handle.pod_nominator.add_nominated_pod(pod, node)
            trace.record_anomaly("preemption_nominated", node=node,
                                 plugin=pf_status.plugin)
            klog.V(4).info_s("preemption nominated node", pod=pod.key, node=node)

    def _finish_binding(self, permit_status: Status, state: CycleState,
                        info: QueuedPodInfo, assumed: Pod, node_name: str,
                        cycle_start: float,
                        pods_to_activate: PodsToActivate, tr=None) -> None:
        """Post-permit half of the binding cycle, dispatched by
        notify_on_permit once the barrier resolves. Re-activates the cycle
        trace on this pool thread so the permit-wait span, the binding
        spans, and the outcome all land in the same flight-recorder entry
        (and klog/Events here keep the correlation id)."""
        token = trace.activate(tr)
        try:
            self._finish_binding_traced(permit_status, state, info, assumed,
                                        node_name, cycle_start,
                                        pods_to_activate, tr)
        finally:
            trace.deactivate(token)

    def _finish_binding_traced(self, permit_status: Status,
                               state: CycleState, info: QueuedPodInfo,
                               assumed: Pod, node_name: str,
                               cycle_start: float,
                               pods_to_activate: PodsToActivate,
                               tr) -> None:
        pod = assumed
        s = permit_status
        if tr is not None:
            tr.mark_permit_resolved()

        def fail(outcome: str, status: Status, anomaly: str) -> None:
            if tr is not None:
                tr.add_anomaly(anomaly, plugin=status.plugin,
                               message=status.message(), node=node_name)
                tr.finish(outcome, status=status, node=node_name)
                self.recorder.finalize(tr, now=self.clock())
            self._fw.run_reserve_plugins_unreserve(state, pod, node_name)
            self._forget_and_signal(pod)
            self._handle_failure(info, status)

        if not s.is_success():
            kind = ("permit_timeout" if "timeout" in s.message()
                    else "permit_rejected")
            fail("permit-rejected", s, kind)
            return
        s = self._timed_point("PreBind", self._fw.run_pre_bind_plugins,
                              state, pod, node_name)
        if not s.is_success():
            fail("bind-failed", s, "prebind_failed")
            return
        s = self._timed_point("Bind", self._fw.run_bind_plugins,
                              state, pod, node_name)
        if not s.is_success():
            fail("bind-failed", s, "bind_failed")
            return
        self.cache.finish_binding(pod)
        bind_total.inc()
        e2e_scheduling_seconds.observe(self.clock() - cycle_start)
        self.clientset.record_event(
            pod.key, "Pod", "Normal", "Scheduled",
            f"Successfully assigned {pod.key} to {node_name}")
        klog.V(4).info_s("bound", pod=pod.key, node=node_name)
        self._timed_point("PostBind", self._fw.run_post_bind_plugins,
                          state, pod, node_name)
        if tr is not None:
            tr.finish("bound", node=node_name)
            self.recorder.finalize(tr, now=self.clock())
        self._activate_pods(pods_to_activate)

    def _forget_and_signal(self, assumed: Pod) -> None:
        """Forget an assumed pod AND wake unschedulable pods that a pod
        deletion would wake. Releasing a reservation frees the same
        resources a deletion frees, but comes from inside the scheduler, so
        no informer event fires for it — without this, a gang whose rivals
        released an entire slice (permit timeout, multislice set teardown,
        failed bind) sits in unschedulableQ until the periodic flush."""
        self.cache.forget_pod(assumed)
        self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_DELETE)

    # -- failure path ---------------------------------------------------------

    def _handle_failure(self, info: QueuedPodInfo, status: Status) -> None:
        if status.plugin:
            info.unschedulable_plugins.add(status.plugin)
        pod = info.pod
        live = self.api.try_get(srv.PODS, pod.key)
        if live is None or assigned(live):
            return
        info.pod = live
        self.queue.requeue_after_failure(
            info, to_backoff=bool(live.status.nominated_node_name),
            delay_s=status.retry_after_s)
        self.clientset.record_event(
            pod.key, "Pod", "Warning", "FailedScheduling",
            status.message() or "unschedulable")
        klog.V(5).info_s("pod unschedulable", pod=pod.key,
                         reason=status.message(), plugin=status.plugin)

    def _activate_pods(self, pods_to_activate: PodsToActivate) -> None:
        with pods_to_activate.lock:
            pods = list(pods_to_activate.map.values())
            pods_to_activate.map.clear()
        if pods:
            self.queue.activate(pods)

