"""The scheduler: scheduleOne loop + async binding cycles.

Rebuild of the hosting loop the reference plugs into (SURVEY §3.2):
pop from activeQ (QueueSort order) → snapshot → PreFilter → Filter (per node)
→ [PostFilter on failure] → Score → select → assume → Reserve → Permit →
async binding cycle (WaitOnPermit → PreBind → Bind → PostBind). Each binding
cycle runs on its own thread, crossing the same "goroutine boundary" as
upstream (vendored scheduler.go:425,557-604 in the reference tree).
"""
from __future__ import annotations

import collections
import os
import queue
import threading
import time
from typing import Dict, List, Optional

from ..api.core import Binding, Node, Pod, heartbeat_only_update
from ..api.scheduling import pod_group_full_name
from ..apiserver import Clientset, InformerFactory
from ..apiserver import server as srv
from ..fwk import (CycleState, Framework, Handle, PluginProfile, Registry,
                   Status, GANG_ROLLBACK_STATE_KEY, PODS_TO_ACTIVATE_KEY,
                   QUOTA_GUARD_STATE_KEY, PodsToActivate)
from ..fwk.interfaces import (EVENT_ADD, EVENT_DELETE, EVENT_UPDATE,
                              RESOURCE_ELASTIC_QUOTA, RESOURCE_NODE,
                              RESOURCE_POD, RESOURCE_POD_GROUP,
                              RESOURCE_TPU_TOPOLOGY)
from .. import obs as obs_mod
from .. import trace
from ..util import klog, locking, tracectx
from ..util.equivalence import equivalence_key
from ..util.metrics import (bind_total, e2e_scheduling_seconds,
                            equiv_cache_bypasses,
                            equiv_cache_differential_mismatches,
                            equiv_cache_fallbacks, equiv_cache_hits,
                            equiv_cache_invalidations, equiv_cache_misses,
                            equiv_cache_vetoes, extension_point_seconds,
                            gang_bind_rollbacks, gang_stuck_total,
                            queue_wait_seconds, schedule_attempts,
                            shard_conflicts_total, shard_escalations_total,
                            shard_quota_conflicts_total)
from ..util.podutil import assigned
from .cache import Cache, CacheView, QUOTA_CONFLICT, pool_of_node
from .equivcache import EquivalenceCache, EquivEntry
from .queue import QueuedPodInfo, SchedulingQueue, ShardedQueues
from .shards import (GLOBAL_LANE, ShardRouter, ShardStats, shard_lane,
                     unit_key_of)

# CycleState keys the equivalence cache must NOT memoize: per-cycle
# scheduler plumbing, re-created fresh by every cycle.  The quota commit
# guard (QUOTA_GUARD_STATE_KEY) is deliberately MEMOIZED: its request
# vectors are a pure function of the equivalence class (identical pod
# requests, empty nominator — both preconditions of cache use), and a
# sibling's hit-path commit must carry them into the guarded assume or
# it would reserve quota unguarded.
_EQUIV_EXCLUDE_KEYS = frozenset((PODS_TO_ACTIVATE_KEY, "tpusched/diagnosis"))

_KIND_TO_RESOURCE = {
    srv.PODS: RESOURCE_POD,
    srv.NODES: RESOURCE_NODE,
    srv.POD_GROUPS: RESOURCE_POD_GROUP,
    srv.ELASTIC_QUOTAS: RESOURCE_ELASTIC_QUOTA,
    srv.TPU_TOPOLOGIES: RESOURCE_TPU_TOPOLOGY,
}

# Attribution plugin name for gang-atomic bind rollback rejections (not a
# real plugin: no cluster event will ever announce "the apiserver healed",
# so _handle_failure routes these through backoffQ, never unschedulableQ).
GANG_ROLLBACK_PLUGIN = "GangBindRollback"

# A gang-rollback entry older than this cannot match any in-flight binding
# task (permit dispatch → Bind is bounded by the bind pool's own drain
# timeout); lazily pruned on the next rollback.
_GANG_ABORT_TTL_S = 60.0

# Sharded dispatch: a cycle whose optimistic commit is refused (foreign
# mutation raced the chosen pool between snapshot and assume) re-derives
# on fresh state this many times before conceding the attempt to backoff.
# Conflicts need a mutation in the SAME pool inside a sub-millisecond
# window, so 1-2 retries resolve essentially all of them.
_MAX_CONFLICT_RETRIES = 4


class _LaneContext:
    """One dispatch lane's mutable cycle-local state.

    The pre-sharding scheduler kept these as Scheduler attributes because
    exactly one thread dispatched; with N concurrent lanes each worker
    owns a context instead — the per-lane equivalence cache (confined to
    its worker thread, like the old one was confined to scheduleOne), the
    pending-arm slot, and the rotating sweep start index.  The default
    context (single-loop configs, by-hand ``schedule_one`` callers in
    tests, and the sharded core's GLOBAL lane) behaves exactly like the
    pre-sharding scheduler: unrestricted candidates, global-cursor
    equivalence arming."""

    __slots__ = ("lane", "pools_scoped", "equiv_cache", "equiv_pending",
                 "next_start_node_index", "partition_pools",
                 "partition_sig", "thread", "queue_wait", "native_arena")

    def __init__(self, lane: str, pools_scoped: bool,
                 equiv_cache: Optional[EquivalenceCache],
                 telemetry: bool = True):
        self.lane = lane                      # "" | "s<N>" | "global"
        self.pools_scoped = pools_scoped      # True only for shard lanes
        self.equiv_cache = equiv_cache
        self.equiv_pending: Optional[tuple] = None
        self.next_start_node_index = 0
        # partition cache, refreshed when the fleet's pool set changes
        self.partition_pools: Optional[List[str]] = None
        self.partition_sig: Optional[tuple] = None
        self.thread: Optional[threading.Thread] = None
        # per-lane queue-wait histogram child, resolved once — the vec's
        # child lookup takes a process-wide lock and this is per-cycle.
        # Shadows resolve none: even an unobserved child registers a
        # series in the process-global family
        self.queue_wait = queue_wait_seconds.with_labels(lane) \
            if telemetry else None
        # native batched-dispatch scratch (sched/nativedispatch._Arena),
        # lane-confined like the equivalence cache; lazily created
        self.native_arena = None


class _DegradedMode:
    """API-degradation circuit breaker.

    Consecutive retry-exhausted API calls (the client burned its whole
    backoff budget and still failed) flip the scheduler into a degraded
    state: pop-dispatch pauses for an exponentially growing window instead
    of hot-looping doomed cycles against a dead apiserver. ANY successful
    API call recovers immediately (binding threads and sibling components
    keep probing, so recovery needs no dedicated prober). Transitions are
    published to the flight recorder's health section and the
    ``tpusched_degraded_mode`` gauge."""

    def __init__(self, threshold: int, initial_pause_s: float,
                 max_pause_s: float, publish=None, clock=None):
        self._threshold = threshold
        self._initial = initial_pause_s
        self._max = max_pause_s
        self._publish = publish or (lambda component, state: None)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._consecutive = 0
        self._pause = initial_pause_s
        self._until = 0.0
        self._entries = 0
        self._last_error = ""
        # half-open: an armed window lapsed with no API success yet — the
        # loop probes again, the escalated pause is kept until a success
        self._probing = False

    # Publishes happen UNDER self._lock (recorder.set_health only takes the
    # recorder's own lock, no back-edge here): an enter publish delayed past
    # a concurrent recovery publish would otherwise leave the health dict
    # claiming degraded while the breaker is closed.

    def on_retry_exhausted(self, verb: str, kind: str, exc: Exception) -> None:
        if self._threshold <= 0:
            return
        with self._lock:
            self._consecutive += 1
            self._last_error = f"{verb} {kind}: {exc}"
            if self._consecutive < self._threshold:
                return
            now = self._clock()
            if now < self._until:       # already paused: let the window run
                return
            pause = self._pause
            self._until = now + pause
            self._pause = min(self._pause * 2, self._max)
            self._entries += 1
            self._probing = False
            state = self._snapshot_locked()
            klog.warning_s("entering degraded mode: pausing pop-dispatch",
                           pause_s=pause,
                           consecutive_failures=state["consecutive_failures"],
                           last_error=state["last_error"])
            self._publish("degraded_mode", state)

    def on_success(self) -> None:
        # hot path: every successful API call lands here — exit without
        # the lock while healthy
        if self._consecutive == 0 and self._until == 0.0 \
                and not self._probing:
            return
        with self._lock:
            # an episode existed if a window was armed (still running,
            # lapsed, or half-open/probing) — publish the recovery even
            # when the success arrives AFTER the window lapsed, or the
            # health section would claim degraded forever
            had_episode = self._until != 0.0 or self._probing
            self._consecutive = 0
            self._pause = self._initial
            self._until = 0.0
            self._probing = False
            if had_episode:
                klog.info_s("leaving degraded mode: API call succeeded")
                self._publish("degraded_mode", self._snapshot_locked())

    def maybe_expire(self) -> None:
        """Scheduler-loop tick: an armed window that lapsed WITHOUT any API
        success moves to half-open — pop-dispatch resumes (probing), the
        health section stops claiming an expired pause, but the escalated
        pause is kept so a still-down apiserver re-trips into a longer
        window instead of restarting the ladder. Only a real success
        (on_success) resets the ladder."""
        if self._until == 0.0:          # lock-free fast path (healthy)
            return
        with self._lock:
            if self._until == 0.0 or self._clock() < self._until:
                return
            self._until = 0.0
            self._probing = True
            self._publish("degraded_mode", self._snapshot_locked())

    def pause_remaining(self) -> float:
        with self._lock:
            return max(0.0, self._until - self._clock())

    def active(self) -> bool:
        return self.pause_remaining() > 0

    def _snapshot_locked(self) -> Dict[str, object]:
        now = self._clock()
        return {"active": now < self._until,
                "probing": self._probing,
                "pause_remaining_s": round(max(0.0, self._until - now), 3),
                "entries_total": self._entries,
                "consecutive_failures": self._consecutive,
                "last_error": self._last_error[:200]}

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return self._snapshot_locked()


class _StuckGangWatchdog:
    """No-progress detector for gangs, swept from the scheduleOne loop.

    Tracing (PR 2) made a wedged gang *explainable*; the watchdog makes the
    scheduler *act*. Per gang with pending or barrier-parked members it
    tracks a progress signature — (assigned members, pending members,
    waiting-at-permit members) — and when the signature has not moved for
    ``stuck_after_s`` it: pins a ``gang_stuck`` anomaly, bumps
    ``tpusched_gang_stuck_total``, publishes the stuck set into the
    flight recorder's health section (/debug/flightrecorder), and force-
    reactivates the gang's parked members so a lost wakeup (the classic
    wedge) cannot strand the gang until the periodic flush. It also
    enforces permit-barrier deadlines missed by the event sweeper
    (``expire_if_due`` is idempotent), so a wedged sweeper thread cannot
    wedge gangs with it. Runs on the scheduling thread between cycles —
    snapshot access needs no extra locking."""

    def __init__(self, scheduler: "Scheduler", stuck_after_s: float,
                 sweep_interval_s: float, clock=None):
        from ..util.clock import as_clock
        self._sched = scheduler
        self._after = stuck_after_s
        self._interval = max(0.05, sweep_interval_s)
        self._clock_handle = as_clock(clock)
        self._clock = self._clock_handle.now
        self._last_sweep = 0.0
        # gang → [signature, since, last_fired, last_seen]
        self._state: Dict[str, list] = {}
        self._published: Dict[str, Dict[str, object]] = {}

    def sweep(self) -> None:
        if self._after <= 0:
            return
        now = self._clock()
        if now - self._last_sweep < self._interval:
            return
        self._last_sweep = now
        sched = self._sched

        waiting: Dict[str, int] = {}

        def visit(wp):
            wp.expire_if_due(now)   # belt-and-braces deadline enforcement
            gang = pod_group_full_name(wp.pod)
            if gang:
                waiting[gang] = waiting.get(gang, 0) + 1
        sched._fw.iterate_over_waiting_pods(visit)

        pending: Dict[str, List[Pod]] = {}
        for pod in sched.queue.pending_pods():
            gang = pod_group_full_name(pod)
            if gang:
                pending.setdefault(gang, []).append(pod)

        # the watchdog is itself a wall-clock retry gate (its forced
        # reactivations give parked members extra retries): arm the next
        # sweep whenever it has live gangs to watch, so a virtual-time
        # replay fires sweeps at deterministic instants — and an idle
        # fleet arms NOTHING, letting the replay driver jump a recorded
        # quiet hour in one hop
        if pending or waiting or self._state:
            self._clock_handle.arm("watchdog", now + self._interval)

        snapshot = sched.cache.snapshot()
        live = set(pending) | set(waiting)
        for gang in live:
            ns, _, name = gang.partition("/")
            sig = (snapshot.assigned_count(name, ns),
                   len(pending.get(gang, ())), waiting.get(gang, 0))
            ent = self._state.get(gang)
            if ent is None or ent[0] != sig:
                self._state[gang] = [sig, now, 0.0, now]
                continue
            ent[3] = now
            stalled_s = now - ent[1]
            if stalled_s < self._after:
                continue
            if now - ent[2] < self._after:
                continue            # fired for this epoch already
            ent[2] = now
            detail = {"assigned": sig[0], "pending": sig[1],
                      "waiting": sig[2], "stalled_s": round(stalled_s, 2)}
            gang_stuck_total.inc()
            trace.pin_event("gang_stuck", subject=gang,
                            recorder=sched.recorder, gang_name=gang, **detail)
            klog.warning_s("gang made no scheduling progress", gang=gang,
                           **detail)
            if pending.get(gang):
                sched.queue.activate(pending[gang])
        # absence grace: a gang whose only pending member is POPPED (mid
        # scheduling cycle) at sweep time vanishes from the queue view for
        # a beat — resetting its stall clock (or flickering the health
        # entry) on that would make the watchdog blind to exactly the
        # gangs it exists for. State drops only after a sustained absence
        # (a few sweeps), so a genuinely resolved gang leaves the stuck
        # report within ~3 sweep intervals.
        grace = 3 * self._interval
        stuck_now: Dict[str, Dict[str, object]] = {}
        for gang in list(self._state):
            sig, since, _, last_seen = self._state[gang]
            if now - last_seen > grace:
                del self._state[gang]
                continue
            stalled_s = now - since
            if stalled_s >= self._after:
                stuck_now[gang] = {
                    "assigned": sig[0], "pending": sig[1], "waiting": sig[2],
                    "stalled_s": round(stalled_s, 2)}
        if stuck_now != self._published:
            self._published = stuck_now
            sched.recorder.set_health(
                "stuck_gangs",
                {"count": len(stuck_now), "gangs": dict(stuck_now)}
                if stuck_now else None)


class _BindingPool:
    """Bounded DAEMON-thread task pool for post-permit binding work.

    Not concurrent.futures: its workers are non-daemon and joined by an
    atexit hook, so one wedged Bind API call would block both stop() and
    interpreter exit forever. Daemon workers + a bounded-join drain keep the
    old thread-per-bind shutdown contract — a stuck bind delays stop() by at
    most the drain timeout and can never pin the process."""

    def __init__(self, workers: int):
        self._q: "queue.Queue" = queue.Queue()
        self._open = True
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"tpusched-bind-{i}")
            for i in range(workers)]
        for t in self._threads:
            t.start()

    def backlog(self) -> int:
        """Binding tasks queued but not yet picked up by a worker — the
        first number to grow when Bind (API) throughput, not scheduling
        throughput, is the bottleneck (tpusched_bind_pool_backlog)."""
        return self._q.qsize()

    def submit(self, fn, abort, *args) -> None:
        """Queue a binding task. ``abort(*args)`` is the task's cheap
        failure path (unreserve + forget, no API calls): shutdown drains
        still-queued tasks through it instead of executing full bind
        cycles on the stopping thread.

        The post-put re-check closes the shutdown race the interleaving
        explorer (tpusched/verify, ``bindpool-shutdown-drain``) pins: a
        submit that passes the open-check, loses the CPU, and lands its
        task AFTER shutdown's drain finished would otherwise leave the
        task queued forever — its reservation leaked with nobody left to
        run OR abort it. Re-draining after the put guarantees a
        post-shutdown task is aborted by somebody: either shutdown's
        drain got it, or we do."""
        if not self._open:
            raise RuntimeError("binding pool is shut down")
        locking.verify_point("bindpool.submit")
        self._q.put((fn, abort, args))
        if not self._open:
            self._abort_queued()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, _, args = item
            try:
                fn(*args)
            except Exception as e:  # a binding task must never kill a worker
                klog.error_s(e, "binding task panicked")

    def _abort_queued(self) -> None:
        """Drain every queued task through its abort path. Worker-wakeup
        sentinels pulled out along the way are re-queued after the drain so
        a still-parked worker cannot be stranded in ``get()``."""
        sentinels = 0
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                sentinels += 1
                continue
            locking.verify_point("bindpool.drain-abort")
            fn, abort, args = item
            try:
                (abort or fn)(*args)
            except Exception as e:
                klog.error_s(e, "binding task abort panicked during drain")
        for _ in range(sentinels):
            self._q.put(None)

    def shutdown(self, timeout: float = 5.0) -> None:
        """Workers are joined with a shared bounded deadline (a wedged Bind
        API call delays stop() by at most ``timeout``). Tasks still queued
        after the join — including ones racing past the open-check — are
        ABORTED inline (reservations released, pods not leaked), never run
        as full bind cycles on the stopping thread."""
        self._open = False
        locking.verify_point("bindpool.shutdown")
        for _ in self._threads:
            self._q.put(None)
        # tpulint: disable=monotonic-clock — shutdown join bound on REAL
        # worker threads (live surface): a virtual clock never moves while
        # a wedged Bind blocks, so the drain budget must be wall time
        deadline = time.monotonic() + timeout
        for t in self._threads:
            # tpulint: disable=monotonic-clock — same real join bound
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self._abort_queued()


class Scheduler:
    def __init__(self, api: srv.APIServer, registry: Registry,
                 profile: PluginProfile, clock=time.time,
                 recorder: Optional["trace.FlightRecorder"] = None,
                 obs_engine: Optional["obs_mod.DiagnosisEngine"] = None,
                 telemetry: bool = True):
        """``telemetry=False`` marks a SHADOW scheduler (what-if planner,
        defrag trials): it schedules forked state holding the SAME pod
        keys as the live fleet, so it must never touch the process-global
        observability surfaces — a trial bind would evict the real pod's
        why-pending diagnosis, a trial's capacity collector would publish
        hypothetical pool gauges as real, and its SLO observations would
        dilute the production burn rate.  Shadows get private throwaway
        instances instead."""
        self.api = api
        # One injectable time substrate (util/clock): ``clock`` accepts the
        # legacy wall callable (tests inject fakes), a full Clock, or None.
        # clock_handle is the structured object — wall()/now() reads plus
        # the deadline registry every scheduler gate arms its expiry on —
        # and self.clock stays the wall-flavored callable the existing
        # latency math reads, so callable-injection sites are unchanged.
        from ..util.clock import as_clock
        self.clock_handle = as_clock(clock)
        self.clock = self.clock_handle.wall
        # Scheduling flight recorder (tpusched/trace): every cycle emits a
        # span tree into the process-global ring unless a private recorder
        # is injected (bench/test isolation).  Shadows get a private ring:
        # trial cycles over forked state (same gang keys as the live
        # fleet!) must not overwrite the live gang's stitched trace in
        # /debug/gangs//debug/explain or pin trial denials as real
        # anomalies.
        if recorder is not None:
            self.recorder = recorder
        elif telemetry:
            self.recorder = trace.default_recorder()
        else:
            self.recorder = trace.FlightRecorder()
        # Why-pending diagnosis engine (tpusched/obs): failed cycles feed
        # their structured attribution here regardless of whether tracing
        # is enabled — /debug/explain must answer during a trace outage too
        if obs_engine is not None:
            self.obs_engine = obs_engine
        elif telemetry:
            self.obs_engine = obs_mod.default_engine()
        else:
            self.obs_engine = obs_mod.DiagnosisEngine()
        # SLO layer: re-install the global tracker only when this profile
        # asks for DIFFERENT targets (HA standbys re-running the same
        # profile must not reset the rolling windows); shadows observe
        # into a private tracker that dies with them
        if not telemetry:
            # shadow trackers observe on THIS scheduler's clock: a
            # virtual-time replay's attainment windows then describe
            # replay time, so a replayed day reports real attainment
            self._slo = obs_mod.SLOTracker(profile.slo_pod_e2e_s,
                                           profile.slo_gang_bound_s,
                                           publish=False, clock=self.clock)
        else:
            if obs_mod.default_slo().targets != (profile.slo_pod_e2e_s,
                                                 profile.slo_gang_bound_s):
                obs_mod.install_slo(obs_mod.SLOTracker(
                    profile.slo_pod_e2e_s, profile.slo_gang_bound_s))
            # None = resolve the GLOBAL tracker at observe time: if a
            # later scheduler retargets/reinstalls it, earlier live
            # schedulers must follow instead of publishing from a
            # replaced tracker (two publishers would fight over the
            # shared burn-rate gauge children)
            self._slo = None
        self._telemetry = telemetry
        # degraded-mode circuit breaker, fed by the clientset's retry layer:
        # consecutive retry-exhausted calls pause pop-dispatch (see
        # _DegradedMode); any successful call recovers it
        self._degraded = _DegradedMode(
            profile.degraded_threshold, profile.degraded_initial_pause_s,
            profile.degraded_max_pause_s,
            publish=lambda comp, state: self.recorder.set_health(comp, state),
            clock=self.clock_handle.now)
        self.clientset = Clientset(
            api, on_retry_exhausted=self._degraded.on_retry_exhausted,
            on_success=self._degraded.on_success)
        self.informer_factory = InformerFactory(api)
        self.cache = Cache(self.clock)
        self.profile = profile

        self._fw: Optional[Framework] = None
        self.handle = Handle(self.clientset, self.informer_factory,
                             lambda: self._fw, self.clock,
                             clock_handle=self.clock_handle)
        # shadow marker for plugins that feed process-global telemetry
        # (Coscheduling's gang-bound SLO clock checks it): a trial bind's
        # latency must not count into the production burn rate
        self.handle.telemetry = telemetry
        # Incremental torus window index (topology/windowindex.py, ISSUE
        # 13): attached to the cache so every structural mutation feeds it
        # an O(Δcells) update inside the cache's own critical section;
        # TopologyMatch / the capacity collector / the defrag advisor read
        # it through the handle.  Shadows get a private publish=False
        # instance (their forked-state maintenance must not count into the
        # fleet's index metrics).
        self.window_index = None
        if profile.torus_window_index \
                and not os.environ.get("TPUSCHED_NO_WINDOW_INDEX"):
            from ..topology.windowindex import TorusWindowIndex
            self.window_index = TorusWindowIndex(publish=telemetry)
            self.cache.attach_window_index(self.window_index)
        self.handle.window_index = self.window_index
        self.handle.window_index_resync = self.cache.sync_window_index
        self._fw = Framework(registry, profile, self.handle)

        # Native batched dispatch inner loop (sched/nativedispatch.py,
        # ISSUE 16): the whole Filter→Score sweep for covered cycles runs
        # as one GIL-released kernel call; the pure-Python path stays on as
        # the sampled in-cycle oracle and the TPUSCHED_NO_NATIVE fallback.
        self._native = None
        if profile.native_dispatch \
                and not os.environ.get("TPUSCHED_NO_NATIVE") \
                and os.environ.get("TPUSCHED_NATIVE_DISPATCH") != "0":
            from .nativedispatch import NativeDispatch
            self._native = NativeDispatch(self)

        # health.fanout for /debug/flightrecorder: the apiserver's fan-out
        # batcher pushes a snapshot after every flush (mode, window, queue
        # depth, batch counters); in synchronous mode one static snapshot
        # is published so the mode is always inspectable.
        try:
            api.set_fanout_health_sink(
                lambda h: self.recorder.set_health("fanout", h))
        except Exception as e:  # noqa: BLE001 — advisory wiring only
            klog.V(4).info_s("fanout health sink wiring skipped",
                             err=str(e))

        # Plugins without EnqueueExtensions default to all-events (upstream
        # semantics: only declared hints narrow the requeue set).
        from ..fwk.interfaces import EnqueueExtensions, WILDCARD_EVENT
        cluster_event_map = {}
        for name, plugin in self._fw.plugins.items():
            if isinstance(plugin, EnqueueExtensions):
                cluster_event_map[name] = plugin.events_to_register()
            else:
                cluster_event_map[name] = [WILDCARD_EVENT]
        # Fleet throughput telemetry (tpusched/obs/throughput.py):
        # binds/cycles counters + arrival-rate gauge, labeled by scheduler
        # profile. Shadows get an inert publish=False shell — a what-if
        # trial's simulated binds must never count into fleet binds/sec.
        self._throughput = obs_mod.ThroughputTelemetry(
            profile.scheduler_name, publish=telemetry)
        # Hot-path sampling profiler: live schedulers make sure the
        # process-global sampler is running (idempotent); shadows must not
        # touch it — trial cycles publishing hot-path samples would read
        # as live scheduler load in /debug/profile.
        # Fleet trace capture (tpusched/obs/fleetrace.py): live schedulers
        # arm the process-global recorder from TPUSCHED_FLEETRACE_DIR
        # (idempotent, disarmed when unset); shadows hold a private
        # DISARMED recorder — a what-if trial's simulated binds must never
        # be journaled as fleet reality.
        # Gang runtime goodput telemetry (tpusched/obs/goodput.py): live
        # schedulers arm the process-global aggregator against this API
        # server's in-band status-report fan-out and register members at
        # bind commit; shadows hold a private inert (publish=False,
        # unattached) aggregator — a what-if trial's members must never
        # publish as fleet runtime telemetry.
        if telemetry:
            obs_mod.ensure_profiler()
            self._fleet = obs_mod.ensure_fleetrace(api)
            self._goodput = obs_mod.ensure_goodput(api)
        else:
            self._fleet = obs_mod.FleetTraceRecorder()
            # replay-time EWMA stamps: the shadow aggregator folds matrix
            # cells on this scheduler's clock, not the host's wall
            self._goodput = obs_mod.GoodputAggregator(publish=False,
                                                      clock=self.clock)
        # Sharded dispatch core (sched/shards.py, ROADMAP item 1): N
        # per-pool dispatch lanes plus a serialized global lane, each
        # lane a full SchedulingQueue behind one routed facade.  shards=1
        # keeps the classic single queue + single loop byte-for-byte.
        self._shards_n = profile.effective_dispatch_shards()
        self._sharded = self._shards_n > 1
        pg_informer = self.informer_factory.informer(srv.POD_GROUPS)
        from .shards import ESCALATION_TTL_S
        self._router = ShardRouter(
            self._shards_n, pg_lookup=pg_informer.get,
            clock=self.clock_handle,
            escalation_ttl_s=(profile.escalation_ttl_s
                              if profile.escalation_ttl_s is not None
                              else ESCALATION_TTL_S),
            quota_serialize=profile.quota_serialize_dispatch)
        # quota-aware sharded commits (ISSUE 14): the cache's quota ledger
        # mirrors the ElasticQuota bounds (seeded here, maintained by the
        # EQ handlers wired below) and maintains per-quota usage in its own
        # critical sections; CapacityScheduling reads admission inputs
        # through handle.quota_view and the commit compares the quota
        # epoch.  The router's quota flag remains for the legacy
        # quota_serialize_dispatch arm and the health report.
        self.handle.quota_view = self.cache.quota_view
        self.handle.quota_bounds_signature = \
            self.cache.quota_bounds_signature
        # sharded mode: every commit is a guarded assume, so the
        # equivalence cache may stay warm under quotas (the commit's
        # semantic re-check catches stale memoized admissions).  The
        # legacy serialize arm skips the guard — veto stays there.
        self.handle.quota_guarded_commits = \
            self._sharded and not profile.quota_serialize_dispatch
        self._sync_quota_ledger()

        def make_lane_queue() -> SchedulingQueue:
            return SchedulingQueue(
                self._fw.less, cluster_event_map, self.clock,
                initial_backoff_s=profile.pod_initial_backoff_s,
                max_backoff_s=profile.pod_max_backoff_s,
                arrival_cb=self._throughput.on_arrival,
                unschedulable_flush_s=profile.unschedulable_flush_s,
                handle_clock=self.clock_handle)

        if self._sharded:
            self._lanes = [shard_lane(i) for i in range(self._shards_n)] \
                + [GLOBAL_LANE]
            self.queue = ShardedQueues(self._lanes, make_lane_queue,
                                       self._router.lane_for)
        else:
            self._lanes = []
            self.queue = make_lane_queue()
        self._shard_stats = ShardStats(self._lanes,
                                       clock=self.clock_handle.now) \
            if self._sharded else None
        # upstream pending_pods{queue="active|backoff|unschedulable"} gauges,
        # computed at scrape time from the live queue. weakref: the global
        # registry must not keep a stopped scheduler (and everything it
        # holds) alive through the provider closure
        import weakref
        from ..util.metrics import REGISTRY
        queue_ref = weakref.ref(self.queue)
        # scheduler label: one process can host several profiles (upstream
        # shares ONE queue across profiles; here each profile owns a queue,
        # so the label keeps N schedulers from clobbering each other's gauge)
        # escape per the Prometheus text format: the name is the one
        # user-controlled string that reaches a label value
        from ..util.metrics import escape_label_value
        esc = escape_label_value(profile.scheduler_name)
        sched_label = f'scheduler="{esc}",' if profile.scheduler_name else ""
        # Shadows register NO gauge providers: a trial scheduler usually
        # runs under the SAME scheduler_name as the live one, so
        # gauge_func's re-register-replaces semantics would hijack the
        # live series with trial queue depths — and kill it outright when
        # the trial is garbage-collected (dead-provider pruning).
        if telemetry:
            for q in ("active", "backoff", "unschedulable"):
                def depth(q=q, ref=queue_ref):
                    live = ref()
                    # None = dead provider: the registry prunes this series
                    # at the next scrape instead of emitting stale zeros
                    # forever (HA failover / what-if restarts construct
                    # schedulers under fresh label sets constantly)
                    return live.pending_counts()[q] if live is not None \
                        else None
                REGISTRY.gauge_func("tpusched_pending_pods", depth,
                                    "Pods pending per scheduling sub-queue.",
                                    labels=f'{sched_label}queue="{q}"')
            # degraded-mode visibility: 1 while pop-dispatch is paused (same
            # weakref/prune discipline as the queue gauges above)
            degraded_ref = weakref.ref(self._degraded)

            def degraded_val(ref=degraded_ref):
                live = ref()
                return None if live is None else \
                    (1.0 if live.active() else 0.0)
            REGISTRY.gauge_func(
                "tpusched_degraded_mode", degraded_val,
                "1 while the scheduler pauses pop-dispatch after consecutive "
                "API retry exhaustions.", labels=sched_label.rstrip(","))

        # adaptive node sampling (upstream percentageOfNodesToScore):
        # profile value 0 ⇒ adaptive 50 - nodes/125, floor 5%; the
        # round-robin start index that spreads scan load across cycles
        # lives per dispatch lane (_LaneContext.next_start_node_index)
        self.percentage_of_nodes_to_score = profile.percentage_of_nodes_to_score

        # per-node Filter/Score parallelism (upstream parallelism=16); the
        # pool is shared by the filter sweep and the score pass
        from ..util.parallelize import Parallelizer
        self._par = Parallelizer(profile.parallelism)
        self._fw.parallelizer = self._par

        # Equivalence-class scheduling cache (sched/equivcache.py): gang
        # siblings popped back-to-back skip straight to Score over the
        # memoized feasible set. One instance per dispatch lane, each
        # confined to its worker thread (the pre-sharding cache was
        # confined to the one scheduleOne thread the same way).
        self._equiv_differential = profile.equiv_cache_differential

        def make_equiv() -> Optional[EquivalenceCache]:
            return EquivalenceCache() if profile.equiv_cache else None

        # The default context doubles as the sharded core's GLOBAL lane:
        # unrestricted candidates, global-cursor equivalence arming —
        # i.e. exactly the pre-sharding dispatch semantics.  Shard lanes
        # get pool-scoped contexts (partition-restricted candidates,
        # pool-cursor-tuple arming).
        self._ctx_default = _LaneContext(
            GLOBAL_LANE if self._sharded else "", False, make_equiv(),
            telemetry=telemetry)
        self._contexts: Dict[str, _LaneContext] = \
            {self._ctx_default.lane: self._ctx_default}
        if self._sharded:
            for i in range(self._shards_n):
                lane = shard_lane(i)
                self._contexts[lane] = _LaneContext(lane, True, make_equiv(),
                                                    telemetry=telemetry)

        self._stop = threading.Event()
        self._sched_thread: Optional[threading.Thread] = None
        # optional per-cycle tap for replay drivers: called
        # (pod_key, attempt_ordinal, wall_now) at the top of every real
        # scheduling cycle — the replay eval plane derives queueing delay
        # (arrival → first attempt) and the per-pod retry-ordinal record
        # from it.  None (the default) costs one attribute read per cycle.
        self.cycle_observer = None
        # cycle liveness counters (plain ints, GIL-atomic): a popped pod
        # mid-cycle is invisible to queue depths and (until it binds) to
        # the store — the replay driver's lockstep barrier reads these to
        # avoid applying the next recorded event while a cycle is still
        # deciding against the previous epoch (sim/replay._quiesce)
        self.cycles_started = 0
        self.cycles_finished = 0
        # Binding cycles run on a bounded pool, dispatched only when the
        # permit barrier RESOLVES (Framework.notify_on_permit) — not one
        # parked thread per member. A 256-pod gang therefore costs zero
        # binding threads while waiting and at most pool-width while
        # draining, instead of 256 spawns + 256 blocked stacks per gang.
        # Worker count is profile-configurable and sized relative to the
        # dispatch shard count (N concurrent lanes submit binds; a pool
        # sized for one lane would become the new serialization point).
        workers = profile.bind_pool_workers
        if workers <= 0:
            workers = min(32, max(4, min(16, os.cpu_count() or 4),
                                  2 * self._shards_n))
        self._bind_pool = _BindingPool(workers)
        # bind-pool backlog gauge (weakref: the registry must not keep a
        # stopped scheduler's pool alive; a dead ref prunes the series)
        pool_ref = weakref.ref(self._bind_pool)

        def bind_backlog(ref=pool_ref):
            pool = ref()
            return pool.backlog() if pool is not None else None
        self._throughput.register_bind_backlog(bind_backlog)
        # gang-atomic bind rollback registry: gang full-name →
        # (abort monotonic ts, triggering pod key, reason). A binding task
        # dispatched BEFORE the abort must not commit its Bind; tasks from
        # later cycles (dispatched after) proceed. Entries are pruned
        # lazily (_GANG_ABORT_TTL_S) — the dict only ever holds gangs that
        # failed a bind in the last minute.
        self._gang_aborts: Dict[str, tuple] = {}
        self._gang_aborts_lock = threading.Lock()
        # stuck-gang watchdog: no-progress detection + permit-deadline
        # belt-and-braces, swept between cycles on the scheduling thread
        self._watchdog = _StuckGangWatchdog(
            self, profile.stuck_gang_after_s,
            profile.stuck_gang_sweep_interval_s, clock=self.clock_handle)
        # capacity & fragmentation telemetry: a scrape-time collector over
        # this scheduler's informers + cache (unregistered at stop()).
        # Shadows register none — a trial's fork must not publish
        # hypothetical pool/quota gauges as real fleet state
        self._capacity = obs_mod.CapacityTelemetry(self) if telemetry \
            else None
        # The closed incident plane (ISSUE 20): health timeline +
        # anomaly sentinel + black-box incident bundles.  Live
        # schedulers wire the process-global instances (the bundle dir
        # arms from TPUSCHED_INCIDENT_DIR); shadows get private
        # publish=False instances on the scheduler's (possibly virtual)
        # clock with an in-memory bundle ring — the virtual-time
        # replay/evaluation plane accrues the same timeline and incident
        # censuses a live hour would, deterministically, without
        # touching the operator's black box.
        if telemetry:
            self._timeline = obs_mod.default_timeline()
            self._sentinel = obs_mod.default_sentinel()
            self._incidents = obs_mod.ensure_incidents()
        else:
            self._timeline = obs_mod.HealthTimeline(
                publish=False, clock=self.clock_handle)
            self._sentinel = obs_mod.AnomalySentinel(
                publish=False, recorder=self.recorder)
            self._incidents = obs_mod.IncidentManager(
                publish=False, clock=self.clock_handle)
        obs_mod.wire_incident_plane(self, self._timeline, self._sentinel,
                                    self._incidents)
        self._wire_informers()

    @property
    def framework(self) -> Framework:
        return self._fw

    @property
    def running(self) -> bool:
        """Readiness: the scheduleOne loop is up and not shutting down."""
        return (self._sched_thread is not None
                and self._sched_thread.is_alive()
                and not self._stop.is_set())

    @property
    def dispatch_shards(self) -> int:
        return self._shards_n

    def shard_router(self) -> ShardRouter:
        return self._router

    @property
    def _next_start_node_index(self) -> int:
        """Introspection compatibility: the default lane's rotating sweep
        start (pre-sharding this was a Scheduler attribute; it now lives
        per dispatch lane in _LaneContext)."""
        return self._ctx_default.next_start_node_index

    # -- informer wiring ------------------------------------------------------

    def _responsible(self, pod: Pod) -> bool:
        return pod.spec.scheduler_name == self.profile.scheduler_name

    def _wire_informers(self) -> None:
        pods = self.informer_factory.pods()
        pods.add_event_handler(
            on_add=self._on_pod_add,
            on_update=self._on_pod_update,
            on_delete=self._on_pod_delete)
        nodes = self.informer_factory.nodes()
        nodes.add_event_handler(
            on_add=lambda n: (self.cache.add_node(n),
                              self.queue.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_ADD)),
            on_update=self._on_node_update,
            on_delete=self._on_node_delete)
        for kind in (srv.POD_GROUPS, srv.ELASTIC_QUOTAS):
            res = _KIND_TO_RESOURCE[kind]
            self.informer_factory.informer(kind).add_event_handler(
                on_add=lambda o, r=res: self._on_cr_event(r, EVENT_ADD),
                on_update=lambda o, n, r=res: self._on_cr_event(r, EVENT_UPDATE),
                on_delete=lambda o, r=res: self._on_cr_event(r, EVENT_DELETE),
                replay=False)
        # TpuTopology events additionally feed the window index its grid
        # geometry (plane rebuilds are cursor-stamped via the cache)
        topo_informer = self.informer_factory.informer(srv.TPU_TOPOLOGIES)
        topo_informer.add_event_handler(
            on_add=lambda t: self._on_topology_event(t, EVENT_ADD),
            on_update=lambda o, t: self._on_topology_event(t, EVENT_UPDATE),
            on_delete=self._on_topology_delete,
            replay=False)
        # CRs present before this scheduler constructed never replay: seed
        # the index's geometry from the informer's current view
        if self.window_index is not None:
            pending = False
            for t in topo_informer.items():
                pending = self.window_index.observe_topology(t) or pending
            if pending:
                self.cache.sync_window_index()

    def _on_topology_event(self, topo, action: int) -> None:
        idx = self.window_index
        if idx is not None and idx.observe_topology(topo):
            self.cache.sync_window_index()
        self._on_cr_event(RESOURCE_TPU_TOPOLOGY, action)

    def _on_topology_delete(self, topo) -> None:
        idx = self.window_index
        if idx is not None:
            idx.forget_topology(topo.spec.pool)
        self._on_cr_event(RESOURCE_TPU_TOPOLOGY, EVENT_DELETE)

    def _sync_quota_ledger(self) -> None:
        """Reconcile the cache quota ledger (and the router's quota flag)
        from the EQ informer's current view — full resync so add/add/
        delete sequences converge regardless of delivery order."""
        quotas = list(
            self.informer_factory.informer(srv.ELASTIC_QUOTAS).items())
        self.cache.sync_quota_bounds(
            {eq.meta.namespace: (eq.spec.min, eq.spec.max)
             for eq in quotas})
        self._router.set_quota_mode(bool(quotas))

    def _on_cr_event(self, resource: str, action: int) -> None:
        if resource == RESOURCE_ELASTIC_QUOTA:
            self._sync_quota_ledger()
        self.queue.move_all_to_active_or_backoff(resource, action)

    def _on_pod_add(self, pod: Pod) -> None:
        if assigned(pod):
            self.cache.add_pod(pod)
            self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_ADD)
        elif self._responsible(pod):
            self.queue.add(pod)

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        if assigned(new):
            # a bound pod is no longer a nominated (in-flight preemptor) —
            # leaving it nominated would double-count it against quotas
            self.handle.pod_nominator.delete_nominated_pod_if_exists(new)
        else:
            self.handle.pod_nominator.update_nominated_pod(old, new)
        if assigned(new):
            if not assigned(old):
                # our own bind confirmation (or an external bind)
                self.cache.add_pod(new)
                self.queue.delete(new)
            else:
                self.cache.update_pod(new)
            self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_UPDATE)
        elif self._responsible(new):
            self.queue.update(new)

    # heartbeat-only updates are dropped: treating them as real updates
    # would bump the cache mutation cursor (disarming every equivalence
    # entry — PR 1's cache could never stay warm on a heartbeat-managed
    # fleet) and re-activate all parked pods once per node per heartbeat
    # period.  Shared predicate: the fleet trace capture must agree with
    # the informer path on what counts as a real node change.
    _heartbeat_only_update = staticmethod(heartbeat_only_update)

    def _on_node_update(self, old: Node, new: Node) -> None:
        if self._heartbeat_only_update(old, new):
            return
        self.cache.update_node(new)
        self.queue.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_UPDATE)

    def _on_node_delete(self, node: Node) -> None:
        """Node removal with bound/assumed pods is a FIRST-CLASS failure
        event, not a blind cache pop: assume-state is reconciled
        (cache.remove_node), members parked at the permit barrier on the
        vanished node are rejected before they can dispatch a doomed bind,
        affected gangs' parked siblings are woken, and the event is pinned
        in the flight recorder so an operator sees which gangs lost
        hardware without correlating logs."""
        affected = self.cache.remove_node(node)
        self.queue.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_DELETE)
        if not affected:
            return
        gangs = sorted({pod_group_full_name(p) for p in affected
                        if pod_group_full_name(p)})
        trace.pin_event("node_removed_with_pods", subject=f"node/{node.name}",
                        recorder=self.recorder, node=node.name,
                        pods=len(affected), gangs=",".join(gangs[:8]))
        klog.warning_s("node removed with pods attached", node=node.name,
                       pods=len(affected), gangs=len(gangs))

        def reject(waiting_pod):
            if waiting_pod.pod.spec.node_name == node.name:
                waiting_pod.reject(
                    "", f"node {node.name} deleted while pod waited at the "
                        f"permit barrier")
        self._fw.iterate_over_waiting_pods(reject)
        # released reservations / vanished members free the same resources a
        # pod deletion frees, and no pod event fires for them — wake parked
        # siblings the same way _forget_and_signal does
        self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_DELETE)

    def _on_pod_delete(self, pod: Pod) -> None:
        # a deleted pod is no longer pending-with-a-question: evict its
        # rolling diagnosis so the bounded table tracks live pods only
        self.obs_engine.on_resolved(pod.key, "deleted")
        # ...and no longer running-with-a-step-clock: evict its runtime
        # health entry, clearing any standing straggler verdict with it
        self._goodput.on_pod_delete(pod.key)
        self.handle.pod_nominator.delete_nominated_pod_if_exists(pod)
        if assigned(pod):
            self.cache.remove_pod(pod)
            self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_DELETE)
        else:
            self.queue.delete(pod)
        # a waiting gang member deleted mid-permit must be rejected
        self._fw.reject_waiting_pod(pod.meta.uid, msg=f"pod {pod.key} deleted")

    def _register_goodput_member(self, pod: Pod, gang: Optional[str],
                                 node_name: str) -> None:
        """Register a just-bound member with the goodput aggregator:
        node, pool generation (the node's accelerator label) and chip
        count, so heartbeat-piggybacked reports fold into the per-chip
        workload×generation matrix.  Best-effort by contract — runtime
        telemetry must never fail a bind commit."""
        try:
            from ..api.topology import LABEL_ACCELERATOR
            from ..obs.goodput import pod_chips
            # cluster-scoped key: a Node's informer key is "/<name>"
            node = self.informer_factory.nodes().get(f"/{node_name}")
            generation = node.meta.labels.get(LABEL_ACCELERATOR, "") \
                if node is not None else ""
            pg = self.informer_factory.informer(srv.POD_GROUPS).get(gang) \
                if gang else None
            self._goodput.register_member(
                pod.key, gang, node_name,
                workload=obs_mod.workload_fingerprint_of(pod, pg),
                generation=generation, chips=pod_chips(pod))
        except Exception as e:  # noqa: BLE001 — advisory by contract
            klog.V(4).info_s("goodput member registration failed",
                             pod=pod.key, err=str(e))

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> None:
        if not self._sharded:
            self._sched_thread = threading.Thread(
                target=self._loop, args=(self._ctx_default,),
                name="tpusched-scheduleOne", daemon=True)
            self._sched_thread.start()
            return
        # one dispatch worker per lane; thread names carry the lane id so
        # /debug/profile attribution rows name the shard (the profiler's
        # thread labels keep the -s<N>/-global suffix — only plain numeric
        # suffixes are folded)
        for lane, ctx in self._contexts.items():
            t = threading.Thread(target=self._loop, args=(ctx,),
                                 name=f"tpusched-dispatch-{lane}",
                                 daemon=True)
            ctx.thread = t
            t.start()
        # the global lane doubles as the housekeeping thread (watchdog,
        # shard-health publishing) and stands in for "the" loop thread in
        # the readiness property
        self._sched_thread = self._ctx_default.thread

    def stop(self) -> None:
        self._stop.set()
        if self._capacity is not None:
            self._capacity.close()
        self.queue.close()
        # unblock waiting gang members; their resolution callbacks enqueue
        # the (failing) binding tasks, which the pool drains before exit
        self._fw.iterate_over_waiting_pods(
            lambda wp: wp.reject("", "scheduler shutting down"))
        if self._sharded:
            # tpulint: disable=monotonic-clock — stop() join bound on
            # REAL dispatch threads (live surface, not a scheduling gate)
            deadline = time.monotonic() + 5.0
            for ctx in self._contexts.values():
                if ctx.thread is not None:
                    # tpulint: disable=monotonic-clock — same join bound
                    remaining = deadline - time.monotonic()
                    ctx.thread.join(timeout=max(0.1, remaining))
        elif self._sched_thread:
            self._sched_thread.join(timeout=5)
        self._bind_pool.shutdown(timeout=5.0)
        self._par.close()
        self._fw.close()
        # detach this scheduler's informers from the API server's watch
        # fan-out: a stopped scheduler must not keep consuming every write
        # (HA fail-over and the what-if planner restart schedulers against
        # a live server)
        self.informer_factory.close()

    def _loop(self, ctx: _LaneContext) -> None:
        housekeeping = ctx is self._ctx_default
        last_health = 0.0
        while not self._stop.is_set():
            if housekeeping:
                # the watchdog sweeps BEFORE the degraded-mode gate: during
                # an apiserver outage stuck gangs must stay visible (health
                # entry, pinned anomalies) and their stall clocks must keep
                # running — the sweep touches only local state (cache
                # snapshot, queue, waiting pods), never the API.  Sharded:
                # exactly one lane (global) runs housekeeping; the sweep's
                # state was never built for concurrent writers.
                self._watchdog.sweep()
                # tpulint: disable=monotonic-clock — health-publish pacing
                # of the REAL housekeeping thread (live surface); the
                # replay driver never runs this loop
                now = time.monotonic()
                if now - last_health >= 1.0:
                    last_health = now
                    if self._sharded:
                        self._publish_shard_health()
                    self._publish_index_health()
                    # health timeline tick (obs/timeline.py): paced
                    # here under WallClock; maybe_tick re-checks the
                    # interval on the timeline's own clock
                    self._timeline.maybe_tick()
            # degraded mode: pausing the pop IS the backoff — failed cycles
            # against a dead apiserver would only re-queue themselves
            pause = self._degraded.pause_remaining()
            if pause > 0:
                self._stop.wait(min(pause, 0.5))
                continue
            if housekeeping:
                self._degraded.maybe_expire()
            if self._sharded:
                info = self.queue.pop(timeout=0.5, lane=ctx.lane)
            else:
                info = self.queue.pop(timeout=0.5)
            if info is None:
                continue
            try:
                self.schedule_one(info, ctx)
            except Exception as e:
                klog.error_s(e, "scheduleOne panicked", pod=info.pod.key)
                try:
                    self._handle_failure(info, Status.error(str(e)))
                except Exception as e2:  # the loop thread must survive ANY
                    # failure-path failure (e.g. apiserver down): requeue on
                    # backoff so the pod is never lost
                    klog.error_s(e2, "failure path panicked; requeueing",
                                 pod=info.pod.key)
                    self.queue.requeue_after_failure(info, to_backoff=True)
            finally:
                # close the pop→cycle visibility gap: the popped pod stayed
                # counted (queue._in_cycle) from inside pop()'s own critical
                # section until here — the replay lockstep barrier relies on
                # "pending + mid-cycle == 0" being one gap-free observation
                if self._sharded:
                    self.queue.cycle_done(ctx.lane)
                else:
                    self.queue.cycle_done()

    def drive_dispatch_once(self) -> bool:
        """Single-step the sharded dispatch core on the CALLING thread:
        pop at most one pod per lane, in canonical lane order, and run its
        full scheduling cycle inline.  The deterministic-replay driver
        (sim/replay.py) uses this instead of run() — lockstep pacing makes
        EVENT order logical, and this makes CYCLE order logical too, so a
        sharded replay exercises the exact routing/partition/commit
        semantics of production lanes without the thread-interleaving
        nondeterminism physical concurrency brings (two lanes binding into
        different pools in either order score each other's occupancy
        differently).  Returns True iff any lane had work."""
        drove = False
        for lane in (self._lanes or [self._ctx_default.lane]):
            ctx = self._contexts[lane]
            info = self.queue.pop(timeout=0, lane=lane) if self._sharded \
                else self.queue.pop(timeout=0)
            if info is None:
                continue
            drove = True
            try:
                self.schedule_one(info, ctx)
            except Exception as e:
                klog.error_s(e, "scheduleOne panicked", pod=info.pod.key)
                try:
                    self._handle_failure(info, Status.error(str(e)))
                except Exception as e2:
                    klog.error_s(e2, "failure path panicked; requeueing",
                                 pod=info.pod.key)
                    self.queue.requeue_after_failure(info, to_backoff=True)
            finally:
                if self._sharded:
                    self.queue.cycle_done(lane)
                else:
                    self.queue.cycle_done()
        return drove

    def run_timers_once(self) -> int:
        """Fire every due time-based gate NOW, on the calling thread — the
        virtual-time replay driver's companion to ``drive_dispatch_once``:
        after jumping the clock to an armed deadline it calls this so the
        gate the deadline belongs to actually lapses (permit barriers
        expire, the stuck-gang watchdog sweeps, degraded-mode windows
        close, plugin flush windows drain).  Everything here is idempotent
        and cheap when nothing is due; the queue-side gates (backoff
        release, unschedulableQ flush) need no call — they fire inside the
        next ``pop()``.  Returns the number of permit barriers that
        expired: their failure paths run ASYNC on the bind pool, so a
        replay driver must fully settle when this is nonzero."""
        now = self.clock_handle.now()
        expired = self._fw.expire_due_permits(now)
        self._watchdog.sweep()
        self._degraded.maybe_expire()
        for plugin in self._fw.plugins.values():
            tick = getattr(plugin, "on_clock_tick", None)
            if tick is not None:
                tick()
        # virtual-time health timeline: the replay driver jumps the
        # clock to the armed timeline-tick deadline and this fires it
        # (tick re-arms the next one); under WallClock the housekeeping
        # lane paces this instead and the call is an interval re-check
        self._timeline.maybe_tick(now)
        return expired

    def _publish_shard_health(self) -> None:
        """health.shards for /debug/flightrecorder: per-lane cycle/bind/
        conflict/escalation counters, queue depths and partition sizes —
        the hot/starved-shard diagnosis surface (doc/ops.md)."""
        try:
            # (the pre-14 full-snapshot refresh tick is gone: the capacity
            # collector reads the cache's PERSISTENT composed snapshot via
            # shared_snapshot(), which is always fresh at O(Δ) cost — no
            # housekeeping rebuild needed, and no foreign advance of the
            # loop's snapshot bookkeeping)
            pools = self.cache.pools()
            partitions = {lane: len(self._router.partition(pools, lane))
                          for lane in self._lanes}
            state = self._shard_stats.snapshot(
                queue_depths=self.queue.pending_counts_by_lane(),
                partitions=partitions)
            state["quota_fleet"] = self._router.quota_mode()
            state["quota_serialized"] = self._router.quota_serialized()
            state["quota"] = self.cache.quota_health()
            state["escalations_total"] = self._router.escalations()
            self.recorder.set_health("shards", state)
        except Exception as e:  # noqa: BLE001 — health publishing is
            # advisory; a reporting bug must not take a dispatch lane down
            klog.V(4).info_s("shard health publish failed", err=str(e))

    def _publish_index_health(self) -> None:
        """health.torus_index for /debug/flightrecorder: per-pool index
        version + cursor lag (staleness vs the live pool cursor), shape
        survivor counts, and the cumulative maintenance counters — the
        diagnosis surface for a native-fallback regression (doc/ops.md)."""
        idx = self.window_index
        if idx is None or not self._telemetry:
            return
        try:
            self.recorder.set_health(
                "torus_index", idx.health(self.cache.pool_cursor))
        except Exception as e:  # noqa: BLE001 — health publishing is
            # advisory; a reporting bug must not take the loop down
            klog.V(4).info_s("torus index health publish failed",
                             err=str(e))

    # -- one scheduling cycle -------------------------------------------------

    def _live_pod(self, key: str) -> Optional[Pod]:
        """Pre-read through the shared pod informer cache (upstream
        semantics: the scheduling loop READS via informers; only writes hit
        the API). Immune to the two failure shapes a live API read has —
        transient unavailability burning a scheduling attempt, and the
        stale-NotFound race that would make the scheduler silently DROP a
        pod that still exists (the chaos soak's C1).

        Returns the informer-SHARED object: read-only by the informer
        contract (the queue already holds these shared objects via
        _on_pod_add).  The cycle's one owned copy is made at assume time;
        the rare mutation site (_run_post_filter's nomination) copies for
        itself.  A deepcopy per popped pod was a measurable slice of the
        per-cycle budget under sharded dispatch."""
        return self.informer_factory.pods().get(key)

    def schedule_one(self, info: QueuedPodInfo,
                     ctx: Optional[_LaneContext] = None) -> None:
        self.cycles_started += 1
        try:
            self._schedule_one(info, ctx)
        finally:
            self.cycles_finished += 1

    def _schedule_one(self, info: QueuedPodInfo,
                      ctx: Optional[_LaneContext] = None) -> None:
        ctx = ctx or self._ctx_default
        pod = info.pod
        # skip pods deleted/bound while queued
        live = self._live_pod(pod.key)
        if live is None or assigned(live) or live.is_terminating():
            # no longer pending: its why-pending entry is answered
            self.obs_engine.on_resolved(pod.key)
            return
        pod = live
        info.pod = live
        if self._sharded:
            # lane drift: the pod's unit was escalated by a sibling, quota
            # mode flipped, or an escalation TTL lapsed since this pod was
            # enqueued — hand it to the lane that owns it NOW instead of
            # scheduling it under the wrong restriction.  pop() charged an
            # attempt for a cycle that never ran; give it back so backoff
            # ladders stay exact.
            target = self._router.lane_for(pod)
            if target != ctx.lane:
                info.attempts = max(0, info.attempts - 1)
                self.queue.push_active(info, target)
                return
        start = self.clock()
        if self.cycle_observer is not None:
            self.cycle_observer(pod.key, getattr(info, "attempts", 0), start)
        # global counters are live-fleet data: shadow trials (what-if,
        # defrag) must not inflate them with simulated cycles
        if self._telemetry:
            schedule_attempts.inc()
            self._throughput.on_cycle(ctx.lane)
            ctx.queue_wait.observe(max(0.0, start - info.timestamp))
        if self._shard_stats is not None:
            self._shard_stats.on_cycle(ctx.lane)
        # flight recorder: one cycle trace per attempt, active on this
        # thread (klog/Events correlate via the id) until the cycle either
        # resolves or parks at the permit barrier; committed to the ring
        # unconditionally so even a still-waiting cycle is inspectable
        tr = None
        if trace.enabled():
            tr = self.recorder.begin_cycle(
                pod, info, start, scheduler=self.profile.scheduler_name,
                shard=ctx.lane)
        token = trace.activate(tr)
        try:
            self._schedule_cycle(info, pod, tr, start, ctx)
        except Exception as e:
            if tr is not None:
                tr.add_anomaly("cycle_panic", error=str(e))
                tr.finish("error")
            raise
        finally:
            if tr is not None:
                # cycles that resolved inside the scheduling half take the
                # fused commit+finalize (the permit-wait path finalizes
                # from the binding thread instead)
                self.recorder.commit(
                    tr, final=tr.outcome not in ("scheduling",
                                                 "waiting-permit", "bound"),
                    now=self.clock())
            trace.deactivate(token)

    def _refresh_partition(self, ctx: _LaneContext) -> None:
        """Rebuild the lane's pool partition when the fleet's pool SET
        changed (pool add/remove only — the pool→shard hash is static, so
        survivors never reshuffle).  The version probe is a lock-free int
        read: N lanes taking the cache lock here every cycle was the
        hottest contention point in the whole process.  A stale read
        costs one cycle on the old partition — the per-pool cursor guard
        still protects the commit."""
        ver = self.cache.pools_version
        if ver != ctx.partition_sig:
            ctx.partition_pools = self._router.partition(
                self.cache.pools(), ctx.lane)
            ctx.partition_sig = ver

    def _maybe_escalate(self, info: QueuedPodInfo, pod: Pod, status: Status,
                        tr, ctx: _LaneContext,
                        pods_to_activate: PodsToActivate) -> bool:
        """Shard-lane miss: the restricted sweep found no home.  Escalate
        the pod's unit to the serialized global lane (full-fleet
        candidates, pre-sharding semantics) instead of parking it — a pod
        only THIS shard's pools cannot host is not unschedulable, and no
        cluster event ever announces "another shard had room".  Also the
        reason shard lanes never run PostFilter: preemption dry-runs
        mutate the global nominator, so nomination decisions stay
        serialized on the global lane."""
        if not ctx.pools_scoped or status.is_error():
            return False
        unit = self._router.escalate(pod)
        if self._telemetry:
            # live-fleet counters only: a shadow replay/what-if trial's
            # simulated escalations must not publish as fleet state
            shard_escalations_total.with_labels(ctx.lane).inc()
        if self._shard_stats is not None:
            self._shard_stats.on_escalation(ctx.lane)
        if tr is not None:
            tr.annotate("shard_escalated", unit)
            tr.finish("shard-escalated", status=status)
        self.obs_engine.on_attempt(
            pod.key, pod_group_full_name(pod) or None, "shard-escalated",
            status.plugin or ctx.lane,
            f"shard {ctx.lane} partition exhausted; retrying on the "
            f"global lane", None, getattr(info, "attempts", 0))
        klog.V(4).info_s("shard escalation", pod=pod.key, lane=ctx.lane,
                         unit=unit)
        self.queue.push_active(info, GLOBAL_LANE)
        self._activate_pods(pods_to_activate)
        return True

    def _schedule_cycle(self, info: QueuedPodInfo, pod: Pod,
                        tr, start: float, ctx: _LaneContext) -> None:
        conflicts = 0
        while True:
            state = CycleState()
            pods_to_activate = PodsToActivate()
            state.write(PODS_TO_ACTIVATE_KEY, pods_to_activate)

            view: Optional[CacheView] = None
            if self._sharded:
                if ctx.pools_scoped:
                    self._refresh_partition(ctx)
                    view = self.cache.snapshot_view(ctx.partition_pools)
                else:
                    view = self.cache.snapshot_view()
                snapshot = view.snapshot
                # partition views are thread-local ONLY: the shared
                # fallback slot (bind workers, informer-thread unreserve)
                # must keep seeing a full-fleet snapshot
                self.handle.set_snapshot(snapshot,
                                         shared=not ctx.pools_scoped)
                self.handle.set_dispatch_scope(
                    "partition" if ctx.pools_scoped else "")
            else:
                snapshot = self.cache.snapshot()
                self.handle.set_snapshot(snapshot)
                self.handle.set_dispatch_scope("")

            if ctx.pools_scoped:
                # the lanes ARE the parallelism: a shard's partition sweep
                # is small and pure-Python — pool dispatch inside it only
                # buys GIL handoffs (util/parallelize.inline_scope)
                with self._par.inline_scope():
                    node_name, status = self._schedule_pod(
                        state, pod, snapshot, ctx, view)
            else:
                node_name, status = self._schedule_pod(state, pod, snapshot,
                                                       ctx, view)
            if not status.is_success():
                if self._maybe_escalate(info, pod, status, tr, ctx,
                                        pods_to_activate):
                    return
                self._run_post_filter(state, pod, status)
                diagnosis = state.try_read("tpusched/diagnosis")
                if tr is not None:
                    tr.finish("error" if status.is_error()
                              else "unschedulable",
                              status=status, diagnosis=diagnosis)
                self._obs_failure(info, pod, status, diagnosis=diagnosis)
                self._handle_failure(info, status)
                self._activate_pods(pods_to_activate)
                return

            # clear stale nomination; assume so parallel cycles see the pod
            self.handle.pod_nominator.delete_nominated_pod_if_exists(pod)
            assumed = pod.deepcopy()
            if self._sharded:
                # optimistic commit: the assume lands only if the chosen
                # pool's cursor is still the one this cycle's filters read
                # (Cache.assume_pod_guarded) AND — for quota'd pods — the
                # quota epoch is still the one CapacityScheduling's
                # admission read (the compare-and-reserve of ISSUE 14).
                # A refusal means a foreign mutation — an informer event,
                # another lane's bind into this pool, a concurrent quota'd
                # commit anywhere — raced the cycle: re-derive on fresh
                # state instead of binding a stale placement.
                ni = snapshot.get(node_name)
                pool = pool_of_node(ni.node) if ni is not None else ""
                expected = view.pool_cursors.get(pool, 0)
                # legacy quota-serialized arm: the global lane owns ALL
                # quota traffic, so verdict→reserve is already atomic by
                # serialization — the pre-14 semantics the arm reproduces
                quota_guard = None if self._router.quota_serialized() \
                    else state.try_read(QUOTA_GUARD_STATE_KEY)
                committed = self.cache.assume_pod_guarded(
                    assumed, node_name, expected,
                    pools=ctx.partition_pools if ctx.pools_scoped else None,
                    quota_guard=quota_guard)
                if committed is None or committed is QUOTA_CONFLICT:
                    quota_raced = committed is QUOTA_CONFLICT
                    conflicts += 1
                    ctx.equiv_pending = None
                    if self._telemetry:
                        shard_conflicts_total.with_labels(ctx.lane).inc()
                        if quota_raced:
                            shard_quota_conflicts_total.with_labels(
                                ctx.lane).inc()
                    if self._shard_stats is not None:
                        self._shard_stats.on_conflict(ctx.lane,
                                                      quota=quota_raced)
                    if tr is not None:
                        tr.annotate("shard_conflicts", conflicts)
                    if conflicts < _MAX_CONFLICT_RETRIES:
                        continue
                    if quota_raced and ctx.pools_scoped:
                        # quota-conflict starvation is fleet-wide pressure
                        # (every concurrent quota'd commit moves the
                        # epoch), not pool contention: the serialized
                        # global lane is the contention-free path, so
                        # escalate the unit instead of parking it in
                        # backoff to lose the same race again
                        status = Status.unschedulable(
                            f"quota epoch raced {conflicts} commit "
                            f"attempts")
                        if self._maybe_escalate(info, pod, status, tr, ctx,
                                                pods_to_activate):
                            return
                    status = Status.unschedulable(
                        f"dispatch conflict: "
                        f"{'quota epoch' if quota_raced else 'pool ' + repr(pool)}"
                        f" raced {conflicts} commit attempts")
                    if tr is not None:
                        tr.finish("conflict-starved", status=status,
                                  node=node_name)
                    self._obs_failure(info, pod, status,
                                      outcome="conflict-starved")
                    self._handle_failure(info, status, to_backoff=True)
                    self._activate_pods(pods_to_activate)
                    return
                # the sanctioned cursor advance, pool-scoped: (re)arm the
                # cycle's equivalence entry iff the partition advanced by
                # EXACTLY this cycle's own attach
                self._equiv_after_assume(ctx, pool, committed)
            else:
                self.cache.assume_pod(assumed, node_name)
                # the sanctioned cursor advance: (re)arm the cycle's
                # equivalence entry iff the assume was the ONLY mutation
                # since the snapshot
                self._equiv_after_assume(ctx, None)
            break

        s = self._timed_point("Reserve", self._fw.run_reserve_plugins_reserve,
                              state, assumed, node_name)
        if not s.is_success():
            self._fw.run_reserve_plugins_unreserve(state, assumed, node_name)
            self._forget_and_signal(assumed)
            if tr is not None:
                tr.finish("reserve-failed", status=s, node=node_name)
            self._obs_failure(info, pod, s, outcome="reserve-failed")
            self._handle_failure(info, s)
            self._activate_pods(pods_to_activate)
            return

        s = self._timed_point("Permit", self._fw.run_permit_plugins,
                              state, assumed, node_name)
        if not s.is_success() and not s.is_wait():
            self._fw.run_reserve_plugins_unreserve(state, assumed, node_name)
            self._forget_and_signal(assumed)
            if tr is not None:
                tr.finish("permit-rejected", status=s, node=node_name)
            self._obs_failure(info, pod, s, outcome="permit-rejected")
            self._handle_failure(info, s)
            self._activate_pods(pods_to_activate)
            return

        if s.is_wait():
            # parked at the permit barrier: record which plugins hold it so
            # a wedged gang is explainable (trace dump AND /debug/explain)
            # before any timeout fires
            wp = self._fw.get_waiting_pod(assumed.meta.uid)
            pending = wp.get_pending_plugins() if wp else []
            if tr is not None:
                tr.mark_waiting(pending)
                tr.node = node_name
            self.obs_engine.on_attempt(
                pod.key, pod_group_full_name(pod) or None, "waiting-permit",
                "/".join(pending) or "Permit",
                "waiting at the permit barrier", None,
                getattr(info, "attempts", 0))

        # sibling activation happens at end of the scheduling cycle
        self._activate_pods(pods_to_activate)

        def on_permit_resolved(permit_status: Status,
                               args=(state, info, assumed, node_name, start,
                                     pods_to_activate, tr, ctx.lane)) -> None:
            # dispatch timestamp: the gang-rollback registry compares it
            # against abort times so only tasks of the aborted burst (not
            # later retry cycles) are rolled back
            dispatch_ts = self.clock_handle.now()
            try:
                self._bind_pool.submit(self._finish_binding,
                                       self._abort_binding, permit_status,
                                       dispatch_ts, *args)
            except RuntimeError:
                # pool already shut down (scheduler stopping): release the
                # pod's reserved state only — NEVER run a full bind cycle
                # inline on the signaling (informer/sweeper) thread
                self._abort_binding(permit_status, dispatch_ts, *args)

        self._fw.notify_on_permit(assumed, on_permit_resolved)

    def _timed_point(self, point: str, fn, *args):
        """framework_extension_point_duration_seconds recorder (upstream
        parity; see the metric's divergence note in util/metrics.py) — and
        the extension-point span of the active cycle trace (per-plugin
        child spans attach underneath via fwk.runtime._timed_plugin). The
        span reuses the metric's perf_counter reads: tracing adds one tuple
        append to the serial scheduleOne thread, nothing more."""
        hist = extension_point_seconds.with_labels(point)
        # profiler attribution: publish the active extension point for the
        # sampling profiler (one thread-local list store each way — the
        # same budget class as the perf_counter reads below)
        prev_point = tracectx.set_point(point)
        t0 = time.perf_counter()
        try:
            return fn(*args)
        finally:
            dur = time.perf_counter() - t0
            tracectx.set_point(prev_point)
            hist.observe(dur)
            tr = trace.current()
            if tr is not None:
                # inlined CycleTrace.add_event (hot write path)
                ev = tr._events
                if len(ev) < trace.MAX_SPANS_PER_TRACE:
                    ev.append((point, t0 - tr.perf_start, dur, None))
                else:
                    tr.truncated += 1

    def _candidate_infos(self, snapshot, ctx: _LaneContext):
        """A lane's candidate node set.  Shard lanes already schedule
        against a partition-restricted snapshot (Cache.snapshot_view), so
        its node list IS the partition — the restriction is structural,
        and every fleet-sweeping plugin (TopologyMatch's window search,
        Coscheduling's capacity dry-run) inherits it for free.  Pooled
        snapshots serve a lazy pool-ordered chain (ISSUE 14: len/iter/
        index over the persistent per-pool lists, O(pools) per epoch —
        the old per-cycle flat materialization was the last O(hosts)
        term); plain test snapshots fall back to list()."""
        seq = getattr(snapshot, "candidate_seq", None)
        return seq() if seq is not None else snapshot.list()

    def _schedule_pod(self, state: CycleState, pod: Pod, snapshot,
                      ctx: _LaneContext, view: Optional[CacheView] = None):
        """genericScheduler.Schedule analog: prefilter → filter → score —
        with the equivalence-class fast path in front: a gang sibling whose
        class has a valid cache entry skips PreFilter and the static
        filters entirely and goes straight to a dynamic re-filter + Score
        over the memoized feasible set."""
        ctx.equiv_pending = None
        num_nodes = snapshot.num_nodes()
        if num_nodes == 0:
            return "", Status.unschedulable("no nodes available")
        entry = self._equiv_lookup(pod, ctx, view)
        if entry is not None:
            result = self._schedule_from_cache(state, pod, snapshot, entry,
                                               ctx, view)
            if result is not None:
                return result
            # cached feasible set drained (or differential mismatch): the
            # entry is dropped and the full path runs as the oracle
            trace.annotate("equiv_cache", "fallback")
        return self._schedule_full(state, pod, snapshot, ctx, view,
                                   record=True)

    def _schedule_full(self, state: CycleState, pod: Pod, snapshot,
                       ctx: _LaneContext, view: Optional[CacheView] = None,
                       record: bool = False):
        """The full per-node path — always the oracle. ``record``: offer the
        completed cycle to the equivalence cache (False for differential
        re-runs, which must be side-effect-free on the cache)."""
        s = self._timed_point("PreFilter", self._fw.run_pre_filter_plugins,
                              state, pod)
        if not s.is_success():
            if s.is_error():
                return "", s
            diagnosis = {n: s for n in snapshot.node_names()}
            state.write("tpusched/diagnosis", diagnosis)
            return "", s

        infos = self._candidate_infos(snapshot, ctx)
        num_nodes = len(infos)
        # PreFilterResult.NodeNames (upstream findNodesThatPassFilters):
        # a PreFilter that resolved the only viable hosts narrows the sweep
        rset = state.restricted_node_names
        if rset is not None:
            infos = [i for i in infos if i.node.name in rset]
            if not infos:
                return "", Status.unschedulable(
                    f"0/{num_nodes} nodes are available: none match the "
                    "PreFilter node set")
        if not infos:
            return "", Status.unschedulable(
                "0 nodes are available: dispatch shard owns no pools")
        want = self._num_feasible_nodes_to_find(len(infos))
        if self._native is not None and record:
            # ``record=False`` marks a differential/oracle re-run — those
            # must exercise the pure-Python path by definition
            result = self._native.attempt(state, pod, snapshot, infos, want,
                                          ctx, restricted=rset is not None,
                                          view=view)
            if result is not None:
                return result
        feasible, diagnosis, error = self._timed_point(
            "Filter", self._find_feasible, state, pod, infos, want, ctx)
        if error is not None:
            return "", error
        state.write("tpusched/diagnosis", diagnosis)

        if not feasible:
            # upstream-style aggregation: "0/N nodes are available:
            # 3 Insufficient google.com/tpu, 1 node(s) had untolerated taint"
            counts = collections.Counter(
                r for st in diagnosis.values()
                for r in (st.reasons or ["unknown"]))
            detail = ", ".join(f"{n} {r}" for r, n in counts.most_common())
            msg = (f"0/{num_nodes} nodes are available: {detail}"
                   if detail else f"0/{num_nodes} nodes are available")
            return "", Status.unschedulable(msg).with_plugin(
                next(iter(diagnosis.values())).plugin if diagnosis else "")
        # snapshot the data map BEFORE Score: an entry memoizes PreFilter/
        # Filter state only. Score-phase writes (per-node raw-score dicts
        # etc.) are per-cycle and often plain dicts with no .clone() —
        # letting them into an entry would share them by reference with
        # every hit cycle's Score, mutating the cached original in place.
        prefilter_export = None
        if record and ctx.equiv_cache is not None:
            prefilter_export = state.export(exclude=_EQUIV_EXCLUDE_KEYS)
        node_name, status = self._select_host(state, pod, feasible)
        if record and status.is_success():
            # a sampled sweep (want < candidates) is a partial feasible set:
            # memoizing it would pin siblings to the sample
            self._equiv_offer(pod, state, feasible,
                              swept_all=want >= len(infos),
                              prefilter_data=prefilter_export,
                              ctx=ctx, view=view)
        return node_name, status

    def _select_host(self, state: CycleState, pod: Pod, feasible):
        """PreScore → Score → deterministic argmax. Shared verbatim by the
        full path and the cache-hit path so the two cannot diverge in
        selection semantics."""
        if len(feasible) == 1:
            return feasible[0].name, Status.success()
        s = self._timed_point("PreScore", self._fw.run_pre_score_plugins,
                              state, pod, feasible)
        if not s.is_success():
            return "", s
        totals, s = self._timed_point("Score", self._fw.run_score_plugins,
                                      state, pod, feasible)
        if not s.is_success():
            return "", s
        best = max(feasible, key=lambda n: (totals.get(n.name, 0), n.name))
        return best.name, Status.success()

    # -- equivalence-class fast path (sched/equivcache.py) --------------------

    def _equiv_lookup(self, pod: Pod, ctx: _LaneContext,
                      view: Optional[CacheView]) -> Optional[EquivEntry]:
        """Return a VALID entry for the pod's class or None. Validity is the
        strict triple: mutation cursor at the snapshot this cycle's filters
        read (the partition's pool-cursor tuple on shard lanes — foreign
        assumes in OTHER shards' pools no longer break the chain), the
        nominator generation, and every EquivalenceAware plugin's
        recomputed fingerprint."""
        if ctx.equiv_cache is None:
            return None
        nominator = self.handle.pod_nominator
        if not nominator.empty():
            # nominated preemptors change per-node filter semantics (the
            # dry-run path): the full path is mandatory
            equiv_cache_bypasses.inc()
            trace.annotate("equiv_cache", "bypass")
            return None
        key = equivalence_key(pod)
        entry = ctx.equiv_cache.get(key)
        if entry is None:
            equiv_cache_misses.inc()
            trace.annotate("equiv_cache", "miss")
            return None
        if ctx.pools_scoped:
            cursor_ok = (entry.armed_pool_cursors is not None
                         and view is not None
                         and entry.armed_pool_cursors
                         == view.cursor_tuple())
        else:
            cursor = view.cursor if view is not None \
                else self.cache.snapshot_cursor()
            cursor_ok = entry.armed_mutation == cursor
        if (not cursor_ok
                or entry.nominator_gen != nominator.generation
                or entry.fingerprints != self._equiv_fingerprints(pod, None)):
            ctx.equiv_cache.drop(key)
            equiv_cache_invalidations.inc()
            trace.annotate("equiv_cache", "invalidated")
            return None
        return entry

    def _equiv_fingerprints(self, pod: Pod, state: Optional[CycleState]):
        """Tuple of (plugin, fingerprint) over the EquivalenceAware plugins,
        or None if any plugin vetoes."""
        fps = []
        for p in self._fw.equiv_aware_plugins:
            fp = p.equiv_fingerprint(pod, state)
            if fp is None:
                return None
            fps.append((p.name(), fp))
        return tuple(fps)

    def _schedule_from_cache(self, state: CycleState, pod: Pod, snapshot,
                             entry: EquivEntry, ctx: _LaneContext,
                             view: Optional[CacheView] = None):
        """The hit path: dynamic re-filter over the cached feasible set,
        then the shared Score tail. Returns (node, status) or None to fall
        back to the full path (entry already dropped)."""
        fw = self._fw
        # work on a throwaway state first: a fallback must leave the real
        # cycle state untouched (CapacityScheduling reuses a pre-existing
        # EQ snapshot key if one is present)
        cstate = CycleState()
        cstate.install(entry.prefilter_data)
        cstate.skip_filter_plugins |= set(entry.skip_filter)
        if entry.restricted is not None:
            cstate.restricted_node_names = set(entry.restricted)
        infos = []
        for name in entry.feasible:
            node_info = snapshot.get(name)
            if node_info is None:
                # a vanished node always bumps the cursor, so this is
                # unreachable in practice — belt and braces
                ctx.equiv_cache.drop(entry.key)
                equiv_cache_invalidations.inc()
                return None
            infos.append(node_info)
        # batch-capable dynamics keep their vectorized path on hits: one
        # fused resource-fit pass over the cached set, exactly as the full
        # path's pre-pass (the hit path guarantees an empty nominator, the
        # same condition the full path gates its batch pass on)
        tr = trace.current()
        # any fallback truncates the event log back to here: an abandoned
        # hit attempt must not leave its Filter/PreScore/Score spans next
        # to the full path's own set (double-counted roots in the dump)
        mark = len(tr._events) if tr is not None else 0
        t0 = time.perf_counter()

        def fallback():
            ctx.equiv_cache.drop(entry.key)
            equiv_cache_fallbacks.inc()
            if tr is not None:
                del tr._events[mark:]
            return None

        batch_fail, _ = self._run_batch_filters(
            fw.dynamic_batch_filter_plugins, cstate, pod, infos)
        feasible = []
        diagnosis: Dict[str, Status] = {}
        for i, node_info in enumerate(infos):
            fs = batch_fail[i]
            if fs is None:
                fs = fw.run_dynamic_filter_plugins(cstate, pod, node_info)
            if fs.is_success():
                feasible.append(node_info.node)
            elif fs.is_error():
                return fallback()
            else:
                diagnosis[node_info.node.name] = fs
        if not feasible:
            # the gang burst consumed every cached host: the full path
            # re-derives feasibility (and owns the unschedulable messaging)
            return fallback()
        if tr is not None:
            tr.add_event("Filter", t0, time.perf_counter() - t0,
                         {"equiv_cache": "hit"})
        node_name, status = self._select_host(cstate, pod, feasible)
        if not status.is_success():
            return fallback()
        if self._equiv_differential:
            full_node = self._differential_check(pod, snapshot, node_name,
                                                 ctx)
            if full_node != node_name:
                return fallback()
        equiv_cache_hits.inc()
        trace.annotate("equiv_cache", "hit")
        # commit the throwaway state into the cycle: Reserve/Permit plugins
        # read the PreFilter stashes from it (e.g. TopologyMatch's
        # coordinate assignment). By-reference adopt — cstate dies here.
        state.adopt(cstate)
        state.skip_filter_plugins |= cstate.skip_filter_plugins
        state.restricted_node_names = cstate.restricted_node_names
        state.write("tpusched/diagnosis", diagnosis)
        if ctx.pools_scoped and view is not None:
            ctx.equiv_pending = (entry, view.cursor_tuple())
        else:
            ctx.equiv_pending = (entry, view.cursor if view is not None
                                 else self.cache.snapshot_cursor())
        return node_name, status

    def _differential_check(self, pod: Pod, snapshot, cached_node: str,
                            ctx: _LaneContext):
        """Oracle assertion (equiv_cache_differential profiles only): re-run
        the FULL path on a fresh state and compare placements. Returns the
        full path's chosen node ('' on failure). Runs UNTRACED: the oracle's
        extension-point spans would double-count into the live cycle's
        flight-recorder entry."""
        token = trace.activate(None)
        try:
            full_state = CycleState()
            full_state.write(PODS_TO_ACTIVATE_KEY, PodsToActivate())
            full_node, full_status = self._schedule_full(
                full_state, pod, snapshot, ctx, record=False)
        finally:
            trace.deactivate(token)
        if full_node != cached_node or not full_status.is_success():
            equiv_cache_differential_mismatches.inc()
            klog.error_s(
                RuntimeError("equivalence-cache placement drift"),
                "cached placement differs from full path", pod=pod.key,
                cached=cached_node, full=full_node,
                full_status=full_status.message())
        return full_node

    def _equiv_offer(self, pod: Pod, state: CycleState, feasible,
                     swept_all: bool, prefilter_data: Dict,
                     ctx: _LaneContext,
                     view: Optional[CacheView] = None) -> None:
        """Offer a completed full cycle as a cache entry (pending until the
        assume verifies the cursor chain). ``prefilter_data`` is the data
        map exported BEFORE Score ran — the only state an entry may hold."""
        if ctx.equiv_cache is None or not swept_all:
            return
        nominator = self.handle.pod_nominator
        if not nominator.empty():
            return
        key = equivalence_key(pod)
        fps = self._equiv_fingerprints(pod, state)
        if fps is None:
            equiv_cache_vetoes.inc()
            return
        entry = EquivEntry(
            key, fps, nominator.generation,
            prefilter_data,
            frozenset(state.skip_filter_plugins),
            (frozenset(state.restricted_node_names)
             if state.restricted_node_names is not None else None),
            tuple(sorted(n.name for n in feasible)))
        if ctx.pools_scoped and view is not None:
            ctx.equiv_pending = (entry, view.cursor_tuple())
        else:
            ctx.equiv_pending = (entry, view.cursor if view is not None
                                 else self.cache.snapshot_cursor())

    def _equiv_after_assume(self, ctx: _LaneContext,
                            chosen_pool: Optional[str],
                            current_cursors: Optional[tuple] = None) -> None:
        """Arm the pending entry iff the cursor advanced by EXACTLY the
        cycle's own assume; any concurrent foreign mutation breaks the
        chain and the entry is discarded.

        Shard lanes compare the PARTITION's pool-cursor tuple instead of
        the global cursor: the chain requires the chosen pool to have
        advanced by exactly 1 (this cycle's own attach, just verified by
        the guarded assume) and every other partition pool to be
        untouched.  Foreign traffic in other shards' pools is invisible
        here — the sharded equivalence cache stays warm through exactly
        the concurrency that used to invalidate it."""
        pending, ctx.equiv_pending = ctx.equiv_pending, None
        if pending is None or ctx.equiv_cache is None:
            return
        entry, marker = pending
        if ctx.pools_scoped:
            cursors = marker            # ((pool, cursor), ...) at snapshot
            # post-assume cursors were read inside the guarded assume's
            # own critical section (assume_pod_guarded returns them) — a
            # second lock hop here was measurable under 8 lanes
            current = current_cursors \
                if current_cursors is not None \
                else self.cache.pool_cursors([p for p, _ in cursors])
            expect = tuple((p, c + 1 if p == chosen_pool else c)
                           for p, c in cursors)
            if current == expect:
                ctx.equiv_cache.arm(entry, -1, pool_cursors=current)
            else:
                ctx.equiv_cache.drop(entry.key)
            return
        cycle_cursor = marker
        if self.cache.mutation_cursor() == cycle_cursor + 1:
            ctx.equiv_cache.arm(entry, cycle_cursor + 1)
        else:
            ctx.equiv_cache.drop(entry.key)

    @staticmethod
    def _run_batch_filters(plugins, state: CycleState, pod: Pod, infos):
        """First-failure-wins batch pre-pass, shared by _find_feasible and
        the equivalence-cache hit path so their batch semantics cannot
        drift. Returns (per-node failure list aligned with ``infos``,
        frozenset of plugin names that ran)."""
        batch_fail: List[Optional[Status]] = [None] * len(infos)
        names = []
        for p in plugins:
            if p.name() in state.skip_filter_plugins:
                continue
            names.append(p.name())
            res = p.filter_batch(state, pod, infos)
            for i, st in enumerate(res):
                if st is not None and batch_fail[i] is None:
                    batch_fail[i] = st.with_plugin(p.name())
        return batch_fail, frozenset(names)

    def _find_feasible(self, state: CycleState, pod: Pod, infos,
                       want: int, ctx: _LaneContext):
        """findNodesThatPassFilters analog (generic_scheduler.go:266), in two
        stages tuned for Python-on-TPU-control-plane economics:

        1. a vectorized batch pre-pass: every BatchFilterPlugin evaluates the
           WHOLE candidate list in one numpy-backed call (no per-node Python
           dispatch, no GIL contention);
        2. a chunked thread-pool sweep running the remaining per-node plugins
           in round-robin order from the rotating start index, stopping once
           ``want`` feasible nodes are found (upstream ParallelizeUntil).

        The batch results are only consumed while no nominated pods exist —
        a preemption dry-run adds nominated pods to per-node state the batch
        pass never saw, so those cycles take the full per-node path.
        Returns (feasible_nodes, diagnosis, error_status_or_None).
        """
        n = len(infos)
        start = ctx.next_start_node_index % n
        fw = self._fw
        nominator_empty = self.handle.pod_nominator.empty()
        # the cycle's snapshot, re-installed into each pool worker's
        # thread-local slot below: a filter plugin (or nominated-pod
        # evaluation) reading the shared lister from a worker thread must
        # see THIS cycle's epoch view, not the cross-thread fallback
        cycle_snapshot = self.handle.snapshot_shared_lister()

        batch_fail: List[Optional[Status]] = [None] * n
        exclude: frozenset = frozenset()
        if nominator_empty and fw.batch_filter_plugins:
            batch_fail, exclude = self._run_batch_filters(
                fw.batch_filter_plugins, state, pod, infos)

        feasible: List[Node] = []
        diagnosis: Dict[str, Status] = {}
        errors: List[Status] = []
        lock = threading.Lock()
        visited = [0]

        def work(idx: int) -> None:
            oi = (start + idx) % n
            node_info = infos[oi]
            fs = batch_fail[oi]
            if fs is None:
                self.handle.set_snapshot(cycle_snapshot, shared=False)
                fs = fw.run_filter_plugins_with_nominated_pods(
                    state, pod, node_info, exclude)
                if fs.is_success():
                    with lock:
                        visited[0] += 1
                        feasible.append(node_info.node)
                    return
            with lock:
                visited[0] += 1
                if fs.is_error():
                    errors.append(fs)
                else:
                    diagnosis[node_info.node.name] = fs

        self._par.until(
            n, work, stop=lambda: len(feasible) >= want or bool(errors))
        ctx.next_start_node_index = (start + max(visited[0], 1)) % n
        if errors:
            return [], {}, errors[0]
        return feasible, diagnosis, None

    def _num_feasible_nodes_to_find(self, num_all: int) -> int:
        """Upstream numFeasibleNodesToFind (generic_scheduler.go): scan every
        node on small clusters; above minFeasibleNodesToFind=100, sample an
        adaptive percentage (50 - nodes/125, floor 5%) of the cluster."""
        MIN_FEASIBLE = 100
        if num_all < MIN_FEASIBLE:
            return num_all
        pct = self.percentage_of_nodes_to_score
        if pct <= 0:
            pct = max(5, 50 - num_all // 125)
        if pct >= 100:
            return num_all
        return max(MIN_FEASIBLE, num_all * pct // 100)

    def _run_post_filter(self, state: CycleState, pod: Pod, status: Status) -> None:
        from ..fwk.status import UNSCHEDULABLE
        if status.code != UNSCHEDULABLE or not self._fw.post_filter_plugins:
            return
        diagnosis = state.try_read("tpusched/diagnosis") or {}
        result, pf_status = self._timed_point(
            "PostFilter", self._fw.run_post_filter_plugins, state, pod,
            diagnosis)
        if pf_status.is_success() and result and result.nominated_node_name:
            node = result.nominated_node_name
            try:
                self.clientset.pods.patch(
                    pod.key,
                    lambda p: setattr(p.status, "nominated_node_name", node))
            except srv.NotFound:
                return
            except Exception as e:  # noqa: BLE001 — nomination is advisory:
                # losing it costs a preemption round trip, not correctness
                klog.V(3).info_s("nomination patch failed; skipping",
                                 pod=pod.key, err=str(e))
                return
            # own the object before mutating: ``pod`` is the informer-
            # shared copy (see _live_pod) and must stay read-only
            pod = pod.deepcopy()
            pod.status.nominated_node_name = node
            self.handle.pod_nominator.add_nominated_pod(pod, node)
            trace.record_anomaly("preemption_nominated", node=node,
                                 plugin=pf_status.plugin)
            klog.V(4).info_s("preemption nominated node", pod=pod.key, node=node)

    def _abort_binding(self, permit_status: Status, dispatch_ts: float,
                       state: CycleState, info: QueuedPodInfo, assumed: Pod,
                       node_name: str, cycle_start: float,
                       pods_to_activate: PodsToActivate, tr=None,
                       lane: str = "") -> None:
        """Shutdown-path resolution of a dispatched binding task: release
        the pod's reserved state (unreserve + forget) and finalize its
        trace — no API calls, no requeue, cheap enough for the signaling
        thread or the pool's shutdown drain. The pod comes back Pending at
        the next scheduler start (annotations-as-truth restart contract)."""
        token = trace.activate(tr)
        try:
            self._fw.run_reserve_plugins_unreserve(state, assumed, node_name)
            self.cache.forget_pod(assumed)
            if tr is not None:
                tr.add_anomaly("binding_aborted",
                               reason="scheduler shutting down",
                               node=node_name)
                tr.finish("bind-aborted", node=node_name)
                self.recorder.finalize(tr, now=self.clock())
        finally:
            trace.deactivate(token)

    def _finish_binding(self, permit_status: Status, dispatch_ts: float,
                        state: CycleState, info: QueuedPodInfo, assumed: Pod,
                        node_name: str, cycle_start: float,
                        pods_to_activate: PodsToActivate, tr=None,
                        lane: str = "") -> None:
        """Post-permit half of the binding cycle, dispatched by
        notify_on_permit once the barrier resolves. Re-activates the cycle
        trace on this pool thread so the permit-wait span, the binding
        spans, and the outcome all land in the same flight-recorder entry
        (and klog/Events here keep the correlation id)."""
        token = trace.activate(tr)
        try:
            self._finish_binding_traced(permit_status, dispatch_ts, state,
                                        info, assumed, node_name, cycle_start,
                                        pods_to_activate, tr, lane)
        finally:
            trace.deactivate(token)

    def _finish_binding_traced(self, permit_status: Status,
                               dispatch_ts: float, state: CycleState,
                               info: QueuedPodInfo,
                               assumed: Pod, node_name: str,
                               cycle_start: float,
                               pods_to_activate: PodsToActivate,
                               tr, lane: str = "") -> None:
        pod = assumed
        s = permit_status
        gang = pod_group_full_name(pod) or None
        if tr is not None:
            tr.mark_permit_resolved()

        def fail(outcome: str, status: Status, anomaly: str,
                 to_backoff: bool = False, rollback: bool = False,
                 **detail) -> None:
            if rollback:
                # tell gang-aware Unreserve plugins this failure is an API
                # outage, not unschedulability: no denial window, the gang
                # re-admits through pod backoff (GANG_ROLLBACK_STATE_KEY)
                state.write(GANG_ROLLBACK_STATE_KEY, True)
            if tr is not None:
                tr.add_anomaly(anomaly, plugin=status.plugin,
                               message=status.message(), node=node_name,
                               **detail)
                tr.finish(outcome, status=status, node=node_name)
                self.recorder.finalize(tr, now=self.clock())
            self._obs_failure(info, pod, status, outcome=outcome)
            self._fw.run_reserve_plugins_unreserve(state, pod, node_name)
            self._forget_and_signal(pod)
            self._handle_failure(info, status, to_backoff=to_backoff)

        if not s.is_success():
            if s.plugin == GANG_ROLLBACK_PLUGIN:
                # a sibling's terminal bind failure rejected this member's
                # barrier: per-member attribution + straight to backoffQ
                fail("permit-rejected", s, "gang_bind_rollback",
                     to_backoff=True, rollback=True, gang=gang,
                     role="waiting-member")
                return
            kind = ("permit_timeout" if "timeout" in s.message()
                    else "permit_rejected")
            fail("permit-rejected", s, kind)
            return

        rolled = self._gang_rollback_entry(gang, dispatch_ts)
        if rolled is not None:
            fail("bind-failed", self._rollback_status(rolled),
                 "gang_bind_rollback", to_backoff=True, rollback=True,
                 gang=gang, trigger_pod=rolled[1], role="sibling")
            return
        s = self._timed_point("PreBind", self._fw.run_pre_bind_plugins,
                              state, pod, node_name)
        if not s.is_success():
            fail("bind-failed", s, "prebind_failed", to_backoff=True)
            return
        # last look before the commit point: a sibling may have failed
        # terminally while PreBind ran — binding now would re-open the
        # partially-bound-gang window the rollback just closed
        rolled = self._gang_rollback_entry(gang, dispatch_ts)
        if rolled is not None:
            fail("bind-failed", self._rollback_status(rolled),
                 "gang_bind_rollback", to_backoff=True, rollback=True,
                 gang=gang, trigger_pod=rolled[1], role="sibling")
            return
        s = self._timed_point("Bind", self._fw.run_bind_plugins,
                              state, pod, node_name)
        if not s.is_success():
            # terminal mid-gang bind failure (the client already burned its
            # retry budget): roll the WHOLE gang back coherently before
            # requeueing this member. Guard: a bind that failed because the
            # pod itself is GONE (deleted mid-flight — the informer no
            # longer holds it) tears down nothing; its gang needs no
            # rollback
            rollback = (gang is not None
                        and self.informer_factory.pods().get(pod.key)
                        is not None)
            if rollback:
                self._trigger_gang_rollback(gang, pod, node_name, s)
            fail("bind-failed", s, "bind_failed", to_backoff=True,
                 rollback=rollback)
            return
        self.cache.finish_binding(pod)
        if self._telemetry:
            # live-fleet counters only: a shadow trial's simulated
            # (in-memory, near-zero-latency) binds would inflate
            # bind_total and pollute the e2e latency histogram
            bind_total.inc()
            self._throughput.on_bind(lane)
            e2e_scheduling_seconds.observe(self.clock() - cycle_start)
        if self._shard_stats is not None:
            self._shard_stats.on_bind(lane)
        # decision attribution for the fleet trace: the watch-derived
        # bind-commit (fired inside the API patch above) is the placement
        # record; this names WHO decided and at what cost. No-op unless
        # capture is armed — and shadows hold a disarmed private recorder.
        self._fleet.record_bind_decision(
            pod.key, node_name, scheduler=self.profile.scheduler_name,
            gang=gang, e2e_s=max(0.0, self.clock() - cycle_start),
            attempts=getattr(info, "attempts", 0))
        # bind→running registration for the goodput plane: name the
        # member's node, pool generation and chip count NOW so later
        # heartbeat-piggybacked reports fold straight into the per-chip
        # workload×generation matrix without another lookup
        self._register_goodput_member(pod, gang, node_name)
        # bound: the why-pending question is answered; feed the pod-e2e SLO
        # with the user-perceived interval (first enqueue → bind commit)
        self.obs_engine.on_resolved(pod.key)
        slo = self._slo if self._slo is not None else obs_mod.default_slo()
        slo.observe(
            obs_mod.POD_E2E,
            max(0.0, self.clock() - getattr(info,
                                            "initial_attempt_timestamp",
                                            cycle_start)))
        self.clientset.record_event_deferred(
            pod.key, "Pod", "Normal", "Scheduled",
            lambda: f"Successfully assigned {pod.key} to {node_name}")
        klog.V(4).info_s("bound", pod=pod.key, node=node_name)
        self._timed_point("PostBind", self._fw.run_post_bind_plugins,
                          state, pod, node_name)
        if tr is not None:
            tr.finish("bound", node=node_name)
            self.recorder.finalize(tr, now=self.clock())
        self._activate_pods(pods_to_activate)

    # -- gang-atomic bind rollback -------------------------------------------

    @staticmethod
    def _rollback_status(entry: tuple) -> Status:
        return Status.unschedulable(
            f"gang bind rollback: member {entry[1]} failed to bind "
            f"({entry[2]})").with_plugin(GANG_ROLLBACK_PLUGIN)

    def _gang_rollback_entry(self, gang: Optional[str],
                             dispatch_ts: float) -> Optional[tuple]:
        """The gang's active rollback entry, if it applies to a binding
        task dispatched at ``dispatch_ts`` (aborts only reach BACKWARD:
        tasks of later retry cycles were dispatched after the abort and
        must proceed)."""
        if gang is None:
            return None
        with self._gang_aborts_lock:
            entry = self._gang_aborts.get(gang)
            if entry is not None \
                    and self.clock_handle.now() - entry[0] > _GANG_ABORT_TTL_S:
                # expired entries are pruned HERE too (not only when the
                # next rollback fires), so the registry really does hold
                # only gangs that failed a bind within the TTL
                del self._gang_aborts[gang]
                entry = None
        if entry is None or entry[0] < dispatch_ts:
            return None
        return entry

    def _trigger_gang_rollback(self, gang: str, pod: Pod, node_name: str,
                               status: Status) -> None:
        """A member's bind failed terminally: make the whole gang's failure
        coherent. (1) arm the rollback registry so every sibling task of
        this burst that has not passed its Bind commit point unreserves +
        forgets instead of binding; (2) reject siblings still parked at the
        permit barrier with a structured reason; (3) pin a
        ``gang_bind_rollback`` anomaly on the triggering cycle's trace.
        Members already bound stay bound — they count toward quorum when
        the rolled-back members retry through backoff, so the gang
        completes once the faults clear instead of wedging half-bound."""
        now = self.clock_handle.now()
        with self._gang_aborts_lock:
            for g, ent in list(self._gang_aborts.items()):
                if now - ent[0] > _GANG_ABORT_TTL_S:
                    del self._gang_aborts[g]
            self._gang_aborts[gang] = (now, pod.key, status.message()[:200])
        gang_bind_rollbacks.inc()
        trace.record_anomaly("gang_bind_rollback", gang=gang,
                             trigger_pod=pod.key, node=node_name,
                             plugin=status.plugin, role="trigger",
                             message=status.message())
        def reject(waiting_pod):
            # membership via the same derivation coscheduling uses — one
            # source of truth for "which pods are this gang"
            if pod_group_full_name(waiting_pod.pod) == gang:
                waiting_pod.reject(
                    GANG_ROLLBACK_PLUGIN,
                    f"gang bind rollback: member {pod.key} failed to bind "
                    f"({status.message()})")
        self._fw.iterate_over_waiting_pods(reject)
        klog.warning_s("gang bind rollback", gang=gang, trigger=pod.key,
                       node=node_name, reason=status.message())

    def _forget_and_signal(self, assumed: Pod) -> None:
        """Forget an assumed pod AND wake unschedulable pods that a pod
        deletion would wake. Releasing a reservation frees the same
        resources a deletion frees, but comes from inside the scheduler, so
        no informer event fires for it — without this, a gang whose rivals
        released an entire slice (permit timeout, multislice set teardown,
        failed bind) sits in unschedulableQ until the periodic flush."""
        self.cache.forget_pod(assumed)
        self.queue.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_DELETE)

    # -- failure path ---------------------------------------------------------

    def _obs_failure(self, info: QueuedPodInfo, pod: Pod, status: Status,
                     diagnosis: Optional[Dict[str, Status]] = None,
                     outcome: Optional[str] = None) -> None:
        """Feed the why-pending diagnosis engine one failed cycle.  Works
        with tracing disabled: the inputs are the merged Status and the
        Filter sweep's per-node diagnosis the cycle produced anyway.  The
        per-node map is summarized through the same bounded aggregator the
        flight recorder uses, so the two surfaces cannot disagree."""
        rows = trace.summarize_diagnosis(diagnosis) if diagnosis else None
        self.obs_engine.on_attempt(
            pod.key, pod_group_full_name(pod) or None,
            outcome or ("error" if status.is_error() else "unschedulable"),
            status.plugin, status.message(), rows,
            getattr(info, "attempts", 0))

    def _handle_failure(self, info: QueuedPodInfo, status: Status,
                        to_backoff: bool = False) -> None:
        """``to_backoff`` forces backoffQ over unschedulableQ — the bind/
        rollback failure paths use it because no cluster event ever fires
        when an apiserver outage clears, so event-driven requeue would
        strand those pods until the periodic flush."""
        if status.plugin:
            info.unschedulable_plugins.add(status.plugin)
        pod = info.pod
        # informer-cache re-read (see _live_pod): the failure path must
        # never itself fail in a way that loses the pod
        live = self._live_pod(pod.key)
        if live is None or assigned(live):
            return
        info.pod = live
        self.queue.requeue_after_failure(
            info,
            to_backoff=to_backoff or bool(live.status.nominated_node_name),
            delay_s=status.retry_after_s)
        self.clientset.record_event(
            pod.key, "Pod", "Warning", "FailedScheduling",
            status.message() or "unschedulable")
        klog.V(5).info_s("pod unschedulable", pod=pod.key,
                         reason=status.message(), plugin=status.plugin)

    def _activate_pods(self, pods_to_activate: PodsToActivate) -> None:
        with pods_to_activate.lock:
            pods = list(pods_to_activate.map.values())
            pods_to_activate.map.clear()
        if pods:
            self.queue.activate(pods)

