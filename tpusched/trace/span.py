"""Cycle spans: the structured span tree one scheduling cycle emits.

A ``CycleTrace`` is born when the scheduler pops a pod, collects timing
events as the cycle crosses extension points (and each point's per-plugin
child calls), survives the permit barrier onto whichever binding thread
resolves it, and is finalized with an outcome + structured rejection
attribution.

Bounded-overhead discipline (this is ALWAYS ON in the hot scheduling loop):

- the write path records **complete events** — ``(name, t0_off, dur)``
  tuples appended to a flat list — not span objects. The instrumentation
  sites already read ``perf_counter`` twice for the duration metrics, so a
  span costs one subtraction, one tuple and one list append on top of work
  the metrics layer was doing anyway. Nothing here is per-node (the
  per-node Filter/Score sweeps stay untraced, exactly like the metrics
  layer).
- the span TREE is reconstructed lazily at read time (``/debug`` endpoints,
  export): events are appended in end-time order and properly nested, so a
  single O(n) stack pass rebuilds parent/child structure.
- no per-trace lock: a trace is only ever mutated by one thread at a time
  (the scheduleOne thread until Permit resolves, then exactly one
  binding-pool thread), every mutation is a GIL-atomic list/dict operation,
  and the concurrent /debug readers copy before iterating — they may
  observe a cycle mid-flight, never a torn structure.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

# Event-list size guard: a runaway plugin cannot balloon a trace past the
# flight recorder's byte budget (excess activity is dropped and counted).
MAX_SPANS_PER_TRACE = 256
MAX_ATTR_STR = 200
_EVENT_EST_BYTES = 72            # flat per-event contribution to estimates


def _clip(v: Any) -> Any:
    if isinstance(v, str) and len(v) > MAX_ATTR_STR:
        return v[:MAX_ATTR_STR] + "…"
    return v


class Span:
    """Read-side span node (built lazily from the event list)."""

    __slots__ = ("name", "t0_off", "dur_s", "attrs", "children")

    def __init__(self, name: str, t0_off: float, dur_s: Optional[float],
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.t0_off = t0_off          # seconds since the trace epoch
        self.dur_s = dur_s
        self.attrs = attrs
        self.children: Optional[List["Span"]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name,
                             "t0_off_s": round(self.t0_off, 6),
                             "dur_s": (round(self.dur_s, 6)
                                       if self.dur_s is not None else None)}
        if self.attrs:
            d["attrs"] = {k: _clip(v) for k, v in self.attrs.items()}
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


def build_span_tree(events: List[tuple]) -> List[Span]:
    """Reconstruct the span forest from end-ordered complete events.

    Properly nested intervals appended in END order mean: walking the list,
    any already-seen span that STARTED at-or-after my start is my
    descendant (it also ended before me, or it would appear later). One
    stack pass, O(n)."""
    stack: List[Span] = []
    for name, t0, dur, attrs in events:
        sp = Span(name, t0, dur, attrs)
        children: List[Span] = []
        while stack and stack[-1].t0_off >= t0:
            children.append(stack.pop())
        if children:
            children.reverse()
            sp.children = children
        stack.append(sp)
    return stack


class CycleTrace:
    """One scheduling cycle's event log + outcome attribution."""

    __slots__ = ("trace_id", "pod_key", "pod_uid", "gang", "attempt",
                 "scheduler", "shard", "wall_start", "perf_start",
                 "first_enqueue",
                 "queue_wait_s", "outcome", "node", "plugin",
                 "reasons", "rejections", "annotations", "anomalies",
                 "diagnosis", "blocked_on", "permit_wait_off",
                 "permit_wait_s", "end_off", "truncated", "_events",
                 "_extra_bytes", "_ring_entry")

    def __init__(self, trace_id: str, pod_key: str, pod_uid: str,
                 gang: Optional[str], attempt: int, scheduler: str,
                 wall_start: float, first_enqueue: float,
                 queue_wait_s: float, shard: str = ""):
        self.trace_id = trace_id
        self.pod_key = pod_key
        self.pod_uid = pod_uid
        self.gang = gang                      # "ns/name" or None
        self.attempt = attempt
        self.scheduler = scheduler
        self.shard = shard                    # dispatch lane ('' = single loop)
        self.wall_start = wall_start          # epoch seconds at cycle start
        self.perf_start = time.perf_counter()
        self.first_enqueue = first_enqueue    # epoch seconds, first add
        self.queue_wait_s = queue_wait_s      # since LAST enqueue
        self.outcome = "scheduling"
        self.node = ""
        self.plugin = ""
        # attribution containers are LAZY (most cycles bind cleanly and
        # carry none of these; six empty-container allocations per cycle
        # were measurable on the serial scheduleOne thread)
        self.reasons: tuple = ()
        self.rejections: Optional[List[Dict[str, Any]]] = None
        self.annotations: Optional[Dict[str, Any]] = None
        self.anomalies: Optional[List[Dict[str, Any]]] = None
        self.diagnosis: tuple = ()
        self.blocked_on: tuple = ()           # permit plugins still pending
        self.permit_wait_off: Optional[float] = None
        self.permit_wait_s: Optional[float] = None
        self.end_off: Optional[float] = None
        self.truncated = 0
        # flat (name, t0_off, dur_s, attrs) complete events, end-ordered
        self._events: List[tuple] = []
        self._extra_bytes = 0
        self._ring_entry = None      # recorder bookkeeping (O(1) finalize)

    # -- event log (the hot write path) ---------------------------------------

    def _off(self) -> float:
        return time.perf_counter() - self.perf_start

    def add_event(self, name: str, t0_abs: float, dur_s: float,
                  attrs: Optional[Dict[str, Any]] = None) -> None:
        """Record one completed span. ``t0_abs`` is the raw perf_counter
        value the caller already read for its duration metric."""
        if len(self._events) >= MAX_SPANS_PER_TRACE:
            self.truncated += 1
            return
        self._events.append((name, t0_abs - self.perf_start, dur_s, attrs))

    # -- attribution ----------------------------------------------------------

    def annotate(self, key: str, value: Any) -> None:
        if self.annotations is None:
            self.annotations = {}
        self.annotations[key] = _clip(value)
        self._extra_bytes += 32

    def add_rejection(self, plugin: str, reason: str, **detail: Any) -> None:
        if self.rejections is None:
            self.rejections = []
        if len(self.rejections) < 16:
            self.rejections.append(
                {"plugin": plugin, "reason": _clip(reason),
                 **{k: _clip(v) for k, v in detail.items()}})
            self._extra_bytes += 96 + len(reason)

    def add_anomaly(self, kind: str, **detail: Any) -> None:
        if self.anomalies is None:
            self.anomalies = []
        if len(self.anomalies) < 8:
            self.anomalies.append(
                {"kind": kind,
                 **{k: _clip(v) for k, v in detail.items()}})
            self._extra_bytes += 96

    def mark_waiting(self, pending_plugins: List[str]) -> None:
        self.blocked_on = list(pending_plugins)
        self.permit_wait_off = self._off()
        self.outcome = "waiting-permit"

    def mark_permit_resolved(self) -> None:
        """Record the permit-barrier wait as a first-class span (called by
        the binding thread the resolution dispatched)."""
        off = self.permit_wait_off
        if off is None:
            return
        self.permit_wait_off = None
        dur = self._off() - off
        self.permit_wait_s = dur
        if len(self._events) < MAX_SPANS_PER_TRACE:
            self._events.append(("PermitWait", off, dur, None))

    def finish(self, outcome: str, status=None, node: str = "",
               diagnosis=None) -> None:
        """Set the final outcome. ``status`` is duck-typed (fwk.Status):
        only ``.plugin`` and ``.reasons`` are read. ``diagnosis`` is the
        per-node Status map from the Filter sweep — summarized (bounded),
        never stored per node."""
        self.node = node
        self.blocked_on = ()
        self.end_off = self._off()
        if status is not None:
            self.plugin = getattr(status, "plugin", "") or ""
            self.reasons = tuple(
                _clip(r) for r in (getattr(status, "reasons", None)
                                   or ())[:8])
            self._extra_bytes += sum(len(r) for r in self.reasons)
        if diagnosis:
            self.diagnosis = summarize_diagnosis(diagnosis)
            self._extra_bytes += 96 * len(self.diagnosis)
        self.outcome = outcome

    # -- views ----------------------------------------------------------------

    def root_spans(self) -> List[Span]:
        return build_span_tree(list(self._events))

    def extension_point_s(self) -> Dict[str, float]:
        """Root-span durations by name — the queue-wait vs extension-point
        decomposition the gang stitcher and the endpoints expose. Computed
        by a reversed scan over the flat events (a root is any event not
        inside the most recent root seen so far) — no tree allocation, the
        commit path calls this per cycle."""
        out: Dict[str, float] = {}
        root_t0 = float("inf")
        for name, t0, dur, _ in reversed(self._events):
            if t0 < root_t0:
                root_t0 = t0
                if dur is not None:
                    out[name] = out.get(name, 0.0) + dur
        return out

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "pod": self.pod_key,
            "gang": self.gang,
            "attempt": self.attempt,
            "scheduler": self.scheduler,
            "shard": self.shard,
            "wall_start": self.wall_start,
            "first_enqueue": self.first_enqueue,
            "queue_wait_s": round(self.queue_wait_s, 6),
            "outcome": self.outcome,
            "spans": [sp.to_dict() for sp in self.root_spans()],
        }
        if self.node:
            d["node"] = self.node
        if self.plugin:
            d["plugin"] = self.plugin
        if self.reasons:
            d["reasons"] = list(self.reasons)
        if self.rejections:
            d["rejections"] = list(self.rejections)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.anomalies:
            d["anomalies"] = list(self.anomalies)
        if self.diagnosis:
            d["diagnosis"] = list(self.diagnosis)
        if self.blocked_on:
            d["blocked_on"] = list(self.blocked_on)
        if self.permit_wait_s is not None:
            d["permit_wait_s"] = round(self.permit_wait_s, 6)
        if self.end_off is not None:
            d["total_s"] = round(self.end_off, 6)
        if self.truncated:
            d["truncated_spans"] = self.truncated
        return d

    def estimate_bytes(self) -> int:
        """O(1) size estimate for the recorder's byte budget (event count ×
        flat cost + the attribution extras tracked at write time)."""
        return (200 + len(self.pod_key)
                + _EVENT_EST_BYTES * len(self._events)
                + self._extra_bytes)


def summarize_diagnosis(diagnosis) -> List[Dict[str, Any]]:
    """Aggregate a {node: Status} Filter diagnosis into bounded
    (plugin, reason) → node-count rows. At fleet scale the raw map is 1024
    entries; the dump needs the shape, not the roster. Statuses are
    deduplicated by identity first — a PreFilter rejection shares ONE
    Status across every node, so the common worst case collapses to a
    single attribute read instead of an O(nodes) getattr storm."""
    counts: Dict[Tuple[str, Tuple[str, ...]], int] = {}
    by_id: Dict[int, Tuple[str, Tuple[str, ...]]] = {}
    for st in diagnosis.values():
        k = by_id.get(id(st))
        if k is None:
            plugin = getattr(st, "plugin", "") or ""
            reasons = tuple(getattr(st, "reasons", None) or ("unknown",))
            k = by_id[id(st)] = (plugin, reasons)
        counts[k] = counts.get(k, 0) + 1
    flat: Dict[Tuple[str, str], int] = {}
    for (plugin, reasons), n in counts.items():
        for r in reasons:
            kr = (plugin, r)
            flat[kr] = flat.get(kr, 0) + n
    top = sorted(flat.items(), key=lambda kv: -kv[1])[:8]
    return [{"plugin": p, "reason": _clip(r), "nodes": n}
            for (p, r), n in top]
