"""Gang stitching: per-PodGroup aggregation of member cycle traces.

The flight recorder feeds every committed/finalized cycle trace of a
gang-labeled pod into a ``GangBook``; the book maintains one bounded
``GangTrace`` per PodGroup exposing the PodGroup-to-Bound critical path
(first-enqueue → last-bind), the permit-barrier wait, per-member outcome
attribution and the straggler set — the "where did the 0.46 s go / which
plugin parked us" view the /debug/gangs endpoint serves.

Write-path discipline: ``on_cycle``/``on_final`` run on the serial
scheduleOne thread (via recorder.commit) and the binding pool — they store
a REFERENCE to the member's latest cycle trace plus two scalars, nothing
more; all extraction (outcome, extension-point decomposition, critical
path) happens lazily at dump time. Memory stays bounded: an LRU of gangs,
a per-gang member cap, one trace reference per member (the trace itself is
already retained by the recorder's ring or about to be garbage — holding
the ref extends the last cycle's life per member, which is exactly the
"explain the stuck gang" retention we want).
"""
from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional

MAX_GANGS = 64
MAX_MEMBERS = 4096
# global cap on member records across ALL retained gangs: each member
# holds one trace reference (~1 KB typical), so this bounds the book to
# ~10 MB worst case no matter how many huge gangs churn through
MAX_TOTAL_MEMBERS = 8192
STRAGGLER_K = 5


class _Member:
    __slots__ = ("tr", "bound_at", "first_enqueue")

    def __init__(self) -> None:
        self.tr = None                        # latest CycleTrace
        self.bound_at: Optional[float] = None
        self.first_enqueue: Optional[float] = None


class GangTrace:
    __slots__ = ("pod_group", "members", "first_cycle_start", "lock")

    def __init__(self, pod_group: str):
        self.pod_group = pod_group
        self.members: Dict[str, _Member] = {}
        self.first_cycle_start: Optional[float] = None
        self.lock = threading.Lock()

    def _member(self, key: str) -> Optional[_Member]:
        m = self.members.get(key)
        if m is None:
            if len(self.members) >= MAX_MEMBERS:
                return None
            m = self.members[key] = _Member()
        return m

    # -- feed (hot path: reference + two scalars, no extraction) --------------

    def on_cycle(self, tr, final_now: Optional[float] = None) -> None:
        """A member's scheduling cycle completed (any outcome, including
        waiting-permit); with ``final_now`` set, the cycle also RESOLVED in
        the same breath (the scheduler fuses commit+finalize for cycles
        that fail before the permit barrier). ``tr`` is a span.CycleTrace."""
        with self.lock:
            m = self._member(tr.pod_key)
            if m is None:
                return
            m.tr = tr
            if m.first_enqueue is None or tr.first_enqueue < m.first_enqueue:
                m.first_enqueue = tr.first_enqueue
            if (self.first_cycle_start is None
                    or tr.wall_start < self.first_cycle_start):
                self.first_cycle_start = tr.wall_start
            if final_now is not None:
                m.bound_at = final_now if tr.outcome == "bound" else None

    def on_final(self, tr, now: float) -> None:
        """A member's binding cycle resolved (bound / permit-rejected /
        bind-failed / unschedulable)."""
        with self.lock:
            m = self._member(tr.pod_key)
            if m is None:
                return
            m.tr = tr
            m.bound_at = now if tr.outcome == "bound" else None

    # -- view (all extraction happens here) -----------------------------------

    def to_dict(self) -> Dict[str, Any]:
        with self.lock:
            snapshot = [(k, m.tr, m.bound_at, m.first_enqueue)
                        for k, m in self.members.items() if m.tr is not None]
            first_cycle_start = self.first_cycle_start

        rows: Dict[str, Dict[str, Any]] = {}
        points: Dict[str, float] = {}
        permit_waits: List[float] = []
        waiting: List[tuple] = []
        bound: List[tuple] = []
        unschedulable = 0
        first_enq = None
        for key, tr, bound_at, fe in snapshot:
            if fe is not None and (first_enq is None or fe < first_enq):
                first_enq = fe
            mpoints = tr.extension_point_s()
            # the permit-barrier wait is idle time, not scheduling work —
            # it's surfaced via permit_barrier/critical_path instead
            mpoints.pop("PermitWait", None)
            for k, v in mpoints.items():
                points[k] = points.get(k, 0.0) + v
            if tr.permit_wait_s is not None:
                permit_waits.append(tr.permit_wait_s)
            outcome = tr.outcome
            if outcome == "waiting-permit":
                wait_start = tr.wall_start + (tr.permit_wait_off or 0.0)
                waiting.append((key, tr, wait_start))
            elif outcome in ("unschedulable", "error"):
                unschedulable += 1
            if bound_at is not None:
                bound.append((key, tr, bound_at, fe))
            # the member's last verdict — the per-member attribution the
            # wedged-gang dump is read for (bounded: scalars only)
            rows[key] = {
                "outcome": outcome,
                "plugin": tr.plugin or "/".join(tr.blocked_on),
                "reason": tr.reasons[0] if tr.reasons else "",
                "attempts": tr.attempt,
                "queue_wait_s": round(tr.queue_wait_s, 6),
                "sched_s": round(sum(mpoints.values()), 6),
                "node": tr.node,
                "trace_id": tr.trace_id,
            }

        d: Dict[str, Any] = {
            "pod_group": self.pod_group,
            "members_seen": len(snapshot),
            "bound": len(bound),
            "waiting_at_permit": len(waiting),
            "unschedulable": unschedulable,
            "first_enqueue": first_enq,
            "extension_point_s": {k: round(v, 6)
                                  for k, v in sorted(points.items())},
            "members": dict(sorted(rows.items())),
        }
        if waiting:
            d["permit_barrier"] = {
                "first_wait_start": min(w[2] for w in waiting),
                "resolved": False,
                "waiting_members": sorted(w[0] for w in waiting)[:16],
                "blocking_plugins": sorted(
                    {p for w in waiting for p in w[1].blocked_on}),
            }
        elif permit_waits:
            d["permit_barrier"] = {
                "first_wait_start": None,
                "resolved": True,
                "max_wait_s": round(max(permit_waits), 6),
            }
        if first_enq is not None and bound:
            last_bind = max(b[2] for b in bound)
            first_bind = min(b[2] for b in bound)
            cp: Dict[str, Any] = {
                "total_s": round(last_bind - first_enq, 6),
                "first_enqueue": first_enq,
                "last_bind": last_bind,
            }
            if first_cycle_start is not None:
                cp["queue_wait_s"] = round(
                    max(0.0, first_cycle_start - first_enq), 6)
            if permit_waits:
                cp["permit_barrier_s"] = round(max(permit_waits), 6)
            if len(bound) > 1:
                cp["bind_burst_s"] = round(last_bind - first_bind, 6)
            d["critical_path"] = cp
            if len(bound) > 1:
                worst = sorted(bound, key=lambda b: -b[2])
                d["stragglers"] = [
                    {"pod": k,
                     "enqueue_to_bound_s": round(
                         at - (fe if fe is not None else first_enq), 6),
                     "node": tr.node}
                    for k, tr, at, fe in worst[:STRAGGLER_K]]
        return d


class GangBook:
    """LRU of per-gang stitched traces."""

    def __init__(self, max_gangs: int = MAX_GANGS):
        self._lock = threading.Lock()
        self._gangs: "collections.OrderedDict[str, GangTrace]" = \
            collections.OrderedDict()
        self._max = max_gangs

    def _get(self, full: str) -> GangTrace:
        # lock-free fast path (GIL-atomic dict read): the per-cycle feed
        # must not pay a lock + LRU shuffle for an existing gang. Recency is
        # tracked at creation and dump time only — eviction of a gang that
        # is actively scheduling is still effectively impossible (creation
        # order tracks activity at MAX_GANGS=64 concurrent gangs).
        g = self._gangs.get(full)
        if g is not None:
            return g
        with self._lock:
            g = self._gangs.get(full)
            if g is None:
                g = self._gangs[full] = GangTrace(full)
                while len(self._gangs) > self._max:
                    self._gangs.popitem(last=False)
                # gang creation is the (rare) point where total member
                # retention is re-bounded: evict oldest gangs until the
                # book-wide member count fits the global cap
                while (len(self._gangs) > 1
                       and sum(len(x.members)
                               for x in self._gangs.values())
                       > MAX_TOTAL_MEMBERS):
                    self._gangs.popitem(last=False)
            return g

    def on_cycle(self, tr, final_now: Optional[float] = None) -> None:
        if tr.gang:
            self._get(tr.gang).on_cycle(tr, final_now)

    def on_final(self, tr, now: float) -> None:
        if tr.gang:
            self._get(tr.gang).on_final(tr, now)

    def get(self, full: str) -> Optional[GangTrace]:
        with self._lock:
            return self._gangs.get(full)

    def dump(self) -> List[Dict[str, Any]]:
        with self._lock:
            gangs = list(self._gangs.values())
        return [g.to_dict() for g in gangs]

    def __len__(self) -> int:
        with self._lock:
            return len(self._gangs)
