"""Flight recorder: a lock-cheap bounded ring of the last N cycle traces,
plus pinned anomaly traces (permit timeout, bind failure, gang denial,
preemption) that survive ring eviction.

Budgets are enforced on BOTH axes (entry count and approximate bytes) at
every commit/finalize — an always-on control plane must hold its memory
ceiling through any workload. Byte accounting uses each trace's cheap
estimate (span.CycleTrace.estimate_bytes); a trace that grows after commit
(permit-wait + binding spans land later) has its delta re-charged at
finalize and the ring re-trimmed.
"""
from __future__ import annotations

import collections
import itertools
import time
from typing import Any, Dict, List, Optional

from ..api.scheduling import POD_GROUP_LABEL
from ..util.locking import GuardedLock, guarded_by
from ..util.metrics import flight_recorder_anomalies
from .gang import GangBook
from .span import CycleTrace

DEFAULT_MAX_ENTRIES = 256
DEFAULT_MAX_BYTES = 4 << 20          # ~4 MiB of trace estimate in the ring
DEFAULT_MAX_PINNED = 64
DEFAULT_MAX_PINNED_BYTES = 1 << 20


@guarded_by("_lock", "_ring", "_ring_bytes", "_pinned", "_pinned_bytes",
            "_committed", "_evicted", "_health")
class FlightRecorder:
    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 max_pinned: int = DEFAULT_MAX_PINNED,
                 max_pinned_bytes: int = DEFAULT_MAX_PINNED_BYTES):
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.max_pinned = max_pinned
        self.max_pinned_bytes = max_pinned_bytes
        self._lock = GuardedLock("trace.FlightRecorder",
                                 reentrant=False)
        # ring entries: [trace, cached_byte_estimate]
        self._ring: "collections.deque[list]" = collections.deque()
        self._ring_bytes = 0
        self._pinned: "collections.deque[list]" = collections.deque()
        self._pinned_bytes = 0
        self._seq = itertools.count(1)
        self._committed = 0
        self._evicted = 0
        self.gangs = GangBook()
        # operator-facing health facts (degraded-mode state, fault-injector
        # stats in chaos runs): tiny dicts keyed by component, replaced
        # wholesale on every transition so /debug/flightrecorder always
        # shows current state even when no cycle is running
        self._health: Dict[str, Dict[str, Any]] = {}

    # -- trace lifecycle ------------------------------------------------------

    def begin_cycle(self, pod, info, wall_start: float,
                    scheduler: str = "", shard: str = "") -> CycleTrace:
        """Create the cycle trace for a popped pod. ``info`` is the queue's
        QueuedPodInfo (duck-typed: timestamp / initial_attempt_timestamp /
        attempts). ``shard``: the dispatch lane that ran the cycle ('' on
        the classic single loop)."""
        gang_name = pod.meta.labels.get(POD_GROUP_LABEL)
        gang = f"{pod.meta.namespace}/{gang_name}" if gang_name else None
        tr = CycleTrace(
            trace_id=f"c{next(self._seq):08x}",
            pod_key=pod.key,
            pod_uid=pod.meta.uid,
            gang=gang,
            attempt=getattr(info, "attempts", 0),
            scheduler=scheduler,
            shard=shard,
            wall_start=wall_start,
            first_enqueue=getattr(info, "initial_attempt_timestamp",
                                  wall_start),
            queue_wait_s=max(0.0, wall_start
                             - getattr(info, "timestamp", wall_start)))
        return tr

    def commit(self, tr: CycleTrace, final: bool = False,
               now: Optional[float] = None) -> None:
        """End of the scheduling half of a cycle: the trace enters the ring
        (it may still gain permit/binding spans — finalize re-charges).
        ``final=True`` fuses finalize in (for cycles that resolved before
        the permit barrier — the common failure/retry shape — one ring pass
        and one gang feed instead of two). ``now``: the caller's clock (the
        scheduler passes its injected clock so gang timestamps share one
        domain with first_enqueue; wall clock otherwise)."""
        est = tr.estimate_bytes()
        entry = [tr, est, True]      # [trace, charged bytes, still in ring]
        tr._ring_entry = entry
        with self._lock:
            self._ring.append(entry)
            self._ring_bytes += est
            self._committed += 1
            self._trim_locked()
        if final:
            # tpulint: disable=monotonic-clock — fallback only: the
            # scheduler passes its injected clock; the gang book's
            # timestamps share the queue's wall-clock domain
            self.gangs.on_cycle(tr, final_now=(time.time() if now is None
                                               else now))
            if tr.anomalies:
                self.pin(tr)
        else:
            self.gangs.on_cycle(tr)

    def finalize(self, tr: CycleTrace, now: Optional[float] = None) -> None:
        """The cycle's final resolution (bound / failed). Re-charges the
        trace's byte estimate and pins it if it carries anomalies."""
        est = tr.estimate_bytes()
        with self._lock:
            entry = tr._ring_entry
            if entry is not None and entry[2]:
                self._ring_bytes += est - entry[1]
                entry[1] = est
                self._trim_locked()
        # tpulint: disable=monotonic-clock — same wall-domain fallback
        # as commit(): callers on latency paths pass now= explicitly
        self.gangs.on_final(tr, time.time() if now is None else now)
        if tr.anomalies:
            self.pin(tr)

    def pin(self, tr: CycleTrace) -> None:
        """Retain an anomaly trace beyond ring eviction (bounded FIFO).

        Coalesced per (gang-or-pod, anomaly kind): a 256-member gang denial
        resolves every sibling's permit barrier with a rejection — pinning
        each one would flush the FIFO of the distinct root-cause traces
        (the triggering member's gang_denied, earlier bind failures) that
        pinning exists to retain. The FIRST instance per key is kept (it is
        closest to the root cause); repeats only bump its counter."""
        kinds = tuple(sorted({a.get("kind", "") for a in tr.anomalies})) \
            if tr.anomalies else ()
        key = (tr.gang or tr.pod_key, kinds)
        est = tr.estimate_bytes()
        with self._lock:
            for entry in self._pinned:
                if entry[0] is tr:
                    self._pinned_bytes += est - entry[1]
                    entry[1] = est
                    return
            for entry in self._pinned:
                if entry[2] == key:
                    prev = (entry[0].annotations or {}).get(
                        "anomaly_repeats", 1)
                    entry[0].annotate("anomaly_repeats", prev + 1)
                    return
            self._pinned.append([tr, est, key])
            self._pinned_bytes += est
            # one inc per distinct anomaly kind on the trace (almost always
            # exactly one): the family total stays ~= pinned traces while
            # dashboards can alert per failure mode
            for k in (kinds or ("unknown",)):
                flight_recorder_anomalies.with_labels(k or "unknown").inc()
            while self._pinned and (len(self._pinned) > self.max_pinned
                                    or self._pinned_bytes
                                    > self.max_pinned_bytes):
                entry = self._pinned.popleft()
                self._pinned_bytes -= entry[1]

    def _trim_locked(self) -> None:
        while self._ring and (len(self._ring) > self.max_entries
                              or self._ring_bytes > self.max_bytes):
            entry = self._ring.popleft()
            entry[2] = False         # a late finalize must not re-charge
            self._ring_bytes -= entry[1]
            self._evicted += 1

    def set_health(self, component: str, state: Optional[Dict[str, Any]]) -> None:
        """Publish (or clear, with None) a component's health facts into
        the /debug/flightrecorder dump — the scheduler's degraded mode
        reports its transitions here so an operator sees WHY pop-dispatch
        paused without correlating metrics first."""
        with self._lock:
            if state is None:
                self._health.pop(component, None)
            else:
                self._health[component] = dict(state)

    def health(self) -> Dict[str, Dict[str, Any]]:
        """Every published health section, by component — the incident
        plane (obs/incident.py) freezes this whole map into a black-box
        bundle, and the health timeline samples single fields from it
        (fan-out backlog) without paying for a full dump()."""
        with self._lock:
            return {k: dict(v) for k, v in self._health.items()}

    # -- views (the /debug surface) ------------------------------------------

    def traces(self) -> List[CycleTrace]:
        with self._lock:
            return [e[0] for e in self._ring]

    def pinned_traces(self) -> List[CycleTrace]:
        with self._lock:
            return [e[0] for e in self._pinned]

    def cycles(self, n: Optional[int] = None,
               pod: Optional[str] = None) -> List[Dict[str, Any]]:
        out = self.traces()
        if pod:
            out = [t for t in out if pod in t.pod_key]
        if n is not None:
            out = out[-n:] if n > 0 else []
        return [t.to_dict() for t in out]

    def pinned_dump(self) -> List[Dict[str, Any]]:
        return [t.to_dict() for t in self.pinned_traces()]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._ring),
                "approx_bytes": self._ring_bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "pinned": len(self._pinned),
                "pinned_approx_bytes": self._pinned_bytes,
                "max_pinned": self.max_pinned,
                "committed_total": self._committed,
                "evicted_total": self._evicted,
                "gangs": len(self.gangs),
            }

    def dump(self) -> Dict[str, Any]:
        """The full /debug/flightrecorder payload: a wedged gang must be
        explainable from this one document."""
        with self._lock:
            health = {k: dict(v) for k, v in self._health.items()}
        return {
            "stats": self.stats(),
            "health": health,
            "cycles": self.cycles(),
            "pinned": self.pinned_dump(),
            "gangs": self.gangs.dump(),
        }
