"""tpusched.trace — the scheduling flight recorder.

Always-on, bounded-overhead cycle tracing:

- ``span.CycleTrace`` / ``span.Span``: the per-cycle structured span tree
  (queue-wait, extension points, per-plugin child spans, equivcache
  annotations, outcome + unschedulable-reason attribution);
- ``recorder.FlightRecorder``: a lock-cheap ring of the last N cycle traces
  plus pinned anomaly traces, with per-PodGroup gang stitching
  (``gang.GangBook``) exposing the PodGroup-to-Bound critical path;
- ``export``: Chrome/Perfetto trace-event JSON for offline viewing;
- this module: the thread-local *trace context* the scheduler activates for
  the duration of a cycle. Instrumentation sites (``fwk/runtime``,
  ``sched/scheduler``, plugins) call the module-level helpers below, which
  are near-free no-ops when no trace is active, so plugin code never needs
  a recorder handle threaded through it.

The id of the active trace is mirrored into ``util.tracectx`` so klog lines
and API-server Events emitted inside the cycle correlate back to the
flight-recorder entry.
"""
from __future__ import annotations

import itertools as _itertools
import os
import threading
import time as _time
from typing import Any, Optional

from ..util import tracectx
from .gang import GangBook, GangTrace
from .recorder import FlightRecorder
from .span import (CycleTrace, MAX_SPANS_PER_TRACE, Span,
                   summarize_diagnosis)
from . import export  # noqa: F401  (re-export)

__all__ = [
    "FlightRecorder", "GangBook", "GangTrace", "CycleTrace", "Span",
    "MAX_SPANS_PER_TRACE", "summarize_diagnosis", "export",
    "default_recorder", "install_recorder", "enabled", "set_enabled",
    "current", "activate", "deactivate", "span", "annotate",
    "record_rejection", "record_anomaly", "pin_event",
]

_tls = threading.local()
_enabled = os.environ.get("TPUSCHED_TRACE", "1") not in ("0", "false", "off")
_default = FlightRecorder()


# -- recorder registry --------------------------------------------------------

def default_recorder() -> FlightRecorder:
    return _default


def install_recorder(rec: FlightRecorder) -> FlightRecorder:
    """Swap the process-global recorder (bench/test isolation). Components
    that captured the old one keep feeding it; the /debug endpoints resolve
    the global at request time."""
    global _default
    _default = rec
    return rec


def enabled() -> bool:
    return _enabled


def set_enabled(v: bool) -> None:
    """Kill switch (and the tracing-off arm of the trace-smoke A/B). Takes
    effect at the next cycle; in-flight traces complete normally."""
    global _enabled
    _enabled = bool(v)


# -- trace context ------------------------------------------------------------

def current() -> Optional[CycleTrace]:
    return getattr(_tls, "trace", None)


def activate(tr: Optional[CycleTrace]):
    """Install ``tr`` as this thread's active trace; returns a token for
    deactivate(). Accepts None (no-op trace context)."""
    prev = (getattr(_tls, "trace", None), tracectx.get())
    _tls.trace = tr
    tracectx.set(tr.trace_id if tr is not None else "")
    return prev


def deactivate(token) -> None:
    prev_trace, prev_id = token
    _tls.trace = prev_trace
    tracectx.set(prev_id)


class _SpanCM:
    """Context manager recording one complete span on the active trace
    (no-op when tracing is off / no trace is active). The instrumentation
    hot path (extension points + cold plugin calls) does NOT use this — it
    fuses the span into the perf_counter reads the duration metrics already
    make (see sched.scheduler._timed_point / fwk.runtime._timed_plugin);
    this CM serves the colder block-structured sites."""

    __slots__ = ("_name", "_attrs", "_tr", "_t0")

    def __init__(self, name: str, attrs: Optional[dict]):
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_SpanCM":
        tr = current()
        self._tr = tr
        if tr is not None:
            self._t0 = _time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        tr = self._tr
        if tr is not None:
            t0 = self._t0
            tr.add_event(self._name, t0, _time.perf_counter() - t0,
                         self._attrs)


def span(name: str, **attrs: Any) -> _SpanCM:
    return _SpanCM(name, attrs or None)


def annotate(key: str, value: Any) -> None:
    tr = current()
    if tr is not None:
        tr.annotate(key, value)


def record_rejection(plugin: str, reason: str, **detail: Any) -> None:
    """Structured rejection attribution: plugins call this next to returning
    an unschedulable Status so the flight recorder carries machine-readable
    WHY (quorum counts, quota arithmetic, surviving-window counts) instead
    of only the human message."""
    tr = current()
    if tr is not None:
        tr.add_rejection(plugin, reason, **detail)


def record_anomaly(kind: str, **detail: Any) -> None:
    """Mark the active cycle anomalous (gang denial, preemption, permit
    timeout, bind failure); the recorder pins such traces beyond ring
    eviction when the cycle finalizes."""
    tr = current()
    if tr is not None:
        tr.add_anomaly(kind, **detail)


_event_seq = _itertools.count(1)


def pin_event(kind: str, subject: str = "",
              recorder: Optional[FlightRecorder] = None,
              **detail: Any) -> None:
    """Pin an OUT-OF-CYCLE anomaly: controller/watchdog events with no
    scheduling cycle to attach to (node NotReady transitions, gang repair,
    stuck-gang findings, node removal with bound pods). Builds a minimal
    trace shell whose only content is the anomaly and commits it final —
    it shows up in /debug/flightrecorder's pinned set and counts into
    ``tpusched_flight_recorder_anomalies_total`` exactly like an in-cycle
    anomaly. No-op while tracing is disabled."""
    if not _enabled:
        return
    rec = recorder if recorder is not None else _default
    # tpulint: disable=monotonic-clock — anomaly timestamps share the
    # wall-clock domain of first_enqueue/creation timestamps; this is
    # an event time, never a duration operand on its own
    now = _time.time()
    tr = CycleTrace(trace_id=f"e{next(_event_seq):08x}", pod_key=subject,
                    pod_uid="", gang=None, attempt=0, scheduler="",
                    wall_start=now, first_enqueue=now, queue_wait_s=0.0)
    tr.add_anomaly(kind, **detail)
    tr.finish(kind)
    rec.commit(tr, final=True, now=now)
