"""Chrome/Perfetto trace-event export of cycle traces.

Emits the JSON object form of the Trace Event Format ("X" complete events +
"M" metadata), loadable in ui.perfetto.dev or chrome://tracing for offline
inspection of where a gang's PodGroup-to-Bound interval went.

Lane model: pid 1 = the scheduler; each pod gets a tid (stable per pod key
within one export) named by an "M" thread_name record, so a gang renders as
a stacked set of member lanes with their extension-point spans aligned on
one wall-clock axis.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .span import CycleTrace, Span, build_span_tree

PID = 1


def _emit_span(events: List[dict], sp: Span, tid: int, epoch_off_us: float,
               cat: str) -> None:
    if sp.dur_s is None:
        return
    events.append({
        "name": sp.name,
        "cat": cat,
        "ph": "X",
        "ts": round(epoch_off_us + sp.t0_off * 1e6, 3),
        "dur": round(sp.dur_s * 1e6, 3),
        "pid": PID,
        "tid": tid,
        "args": dict(sp.attrs) if sp.attrs else {},
    })
    for c in sp.children or ():
        _emit_span(events, c, tid, epoch_off_us, cat)


def to_perfetto(traces: List[CycleTrace],
                pinned: Optional[List[CycleTrace]] = None) -> Dict[str, Any]:
    """Serialize cycle traces to a trace-event JSON object. The export
    epoch is the earliest first-enqueue so queue-wait renders as real dead
    time before the first span."""
    all_traces = list(traces) + [t for t in (pinned or [])
                                 if t not in traces]
    events: List[dict] = []
    if not all_traces:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    epoch = min(min(t.first_enqueue, t.wall_start) for t in all_traces)
    tids: Dict[str, int] = {}
    for tr in all_traces:
        tid = tids.get(tr.pod_key)
        if tid is None:
            tid = tids[tr.pod_key] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M", "pid": PID,
                           "tid": tid, "args": {"name": tr.pod_key}})
        d = tr.to_dict()
        cycle_off_us = (tr.wall_start - epoch) * 1e6
        # one enclosing cycle span carrying the outcome + attribution
        total = d.get("total_s")
        if total is None:
            # still open (e.g. parked at Permit): span up to the last event
            total = max([t0 + (dur or 0.0)
                         for _, t0, dur, _ in tr._events] or [0.0])
        events.append({
            "name": f"cycle:{d['outcome']}",
            "cat": "cycle",
            "ph": "X",
            "ts": round(cycle_off_us, 3),
            "dur": round(total * 1e6, 3),
            "pid": PID,
            "tid": tid,
            "args": {k: d[k] for k in ("trace_id", "gang", "attempt",
                                       "outcome", "node", "plugin",
                                       "queue_wait_s") if d.get(k)},
        })
        if tr.queue_wait_s > 0:
            events.append({
                "name": "queue-wait", "cat": "queue", "ph": "X",
                "ts": round(cycle_off_us - tr.queue_wait_s * 1e6, 3),
                "dur": round(tr.queue_wait_s * 1e6, 3),
                "pid": PID, "tid": tid, "args": {},
            })
        for sp in tr.root_spans():
            _emit_span(events, sp, tid, cycle_off_us, "extension_point")
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_trace_events(doc: Any) -> List[str]:
    """Validate a document against the trace-event schema subset this
    exporter emits. Returns a list of problems (empty = valid) — the
    trace-smoke gate and the bench --trace-out assertion both run this."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents is not a list"]
    for i, ev in enumerate(evs):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M", "B", "E", "i", "I"):
            problems.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"{where}: missing name")
        for k in ("pid", "tid"):
            if not isinstance(ev.get(k), int):
                problems.append(f"{where}: missing int {k}")
        if ph == "X":
            for k in ("ts", "dur"):
                if not isinstance(ev.get(k), (int, float)):
                    problems.append(f"{where}: missing number {k}")
            if isinstance(ev.get("dur"), (int, float)) and ev["dur"] < 0:
                problems.append(f"{where}: negative dur")
        if "args" in ev and not isinstance(ev["args"], dict):
            problems.append(f"{where}: args not an object")
        if len(problems) >= 20:
            problems.append("... (truncated)")
            break
    return problems


def validate_span_tree(tr: CycleTrace) -> List[str]:
    """Structural well-formedness of one cycle trace's reconstructed span
    tree — every span has a non-negative duration, children fit inside
    their parent (small epsilon for clock reads straddling the close), the
    event log is end-ordered (the reconstruction invariant), and the trace
    carries an outcome. Used by the trace-smoke gate."""
    problems: List[str] = []
    eps = 5e-4

    def walk(sp: Span, path: str, lo: float, hi: float) -> None:
        p = f"{path}/{sp.name}"
        if sp.t0_off < lo - eps:
            problems.append(f"{p}: starts before parent")
        if sp.dur_s is None:
            problems.append(f"{p}: no duration recorded")
        else:
            if sp.dur_s < 0:
                problems.append(f"{p}: negative duration")
            if sp.t0_off + sp.dur_s > hi + eps:
                problems.append(f"{p}: ends after parent")
        for c in sp.children or ():
            walk(c, p, sp.t0_off,
                 sp.t0_off + sp.dur_s if sp.dur_s is not None else hi)

    events = list(tr._events)
    last_end = -eps
    for name, t0, dur, _ in events:
        end = t0 + (dur or 0.0)
        if end < last_end - eps:
            problems.append(
                f"{tr.trace_id}/{name}: event log not end-ordered")
        last_end = max(last_end, end)
    for sp in build_span_tree(events):
        walk(sp, tr.trace_id, 0.0, float("inf"))
    if tr.outcome == "scheduling":
        problems.append(f"{tr.trace_id}: no outcome recorded")
    return problems
