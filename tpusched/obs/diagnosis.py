"""Why-pending diagnosis engine.

The flight recorder (PR 2) answers "what happened in cycle X"; this engine
answers the operator's actual question — "why is my pod/gang STILL pending,
and what would unblock it" — by aggregating each pod's structured rejection
attribution ACROSS attempts into a bounded rolling diagnosis:

    per pod   last outcome + blocking plugin + (plugin, reason) rows with
              node counts ("178/256 nodes: TpuSlice shape-mismatch") and
              how many attempts each reason has blocked;
    per gang  the same rolled up across members (how many members each
              reason blocks, barrier population, blocking plugins);
    cluster   a top-blockers table: which (plugin, reason) keys block the
              most pods right now.

Fed by the scheduler at cycle resolution (works with tracing DISABLED —
the inputs are the Status + Filter diagnosis the cycle produced anyway,
not the trace ring).  Served at ``/debug/explain`` and by
``python -m tpusched.cmd.explain``.

Bounded like the flight recorder: entry cap + approximate byte cap on BOTH
the pod table and each pod's reason rows, LRU eviction, and immediate
eviction of RESOLVED pods (bound or deleted) so a healthy fleet holds a
near-empty table.  Write path is O(rows) per FAILED cycle under one lock —
the happy path (bound) pays one dict pop.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional, Tuple

from . import reasons as _reasons
from ..util.locking import GuardedLock, guarded_by

DEFAULT_MAX_PODS = 1024
DEFAULT_MAX_BYTES = 1 << 20          # ~1 MiB of diagnosis state
MAX_ROWS_PER_POD = 12
_POD_BASE_BYTES = 160
_ROW_BASE_BYTES = 96


class _Row:
    """One (plugin, normalized reason) aggregate for a pod."""

    __slots__ = ("plugin", "reason", "nodes", "cycles", "example")

    def __init__(self, plugin: str, reason: str):
        self.plugin = plugin
        self.reason = reason
        self.nodes = 0        # node count at the LAST attempt that saw it
        self.cycles = 0       # attempts in which this reason appeared
        self.example = ""     # one raw (un-normalized) instance, clipped

    def to_dict(self) -> Dict[str, Any]:
        d = {"plugin": self.plugin, "reason": self.reason,
             "nodes": self.nodes, "cycles": self.cycles}
        if self.example and self.example != self.reason:
            d["example"] = self.example
        return d


class _PodDiag:
    __slots__ = ("gang", "first_seen", "last_seen", "attempts",
                 "last_outcome", "last_plugin", "last_reason", "rows",
                 "bytes")

    def __init__(self, gang: Optional[str], now: float):
        self.gang = gang
        self.first_seen = now
        self.last_seen = now
        self.attempts = 0
        self.last_outcome = ""
        self.last_plugin = ""
        self.last_reason = ""
        self.rows: "collections.OrderedDict[Tuple[str, str], _Row]" = \
            collections.OrderedDict()
        self.bytes = _POD_BASE_BYTES

    def blocking_key(self) -> Optional[Tuple[str, str]]:
        if not self.last_plugin and not self.last_reason:
            return None
        return (self.last_plugin, self.last_reason)


@guarded_by("_lock", "_pods", "_bytes", "_gangs", "_blockers",
            "_fed", "_resolved", "_evicted")
class DiagnosisEngine:
    def __init__(self, max_pods: int = DEFAULT_MAX_PODS,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 max_rows_per_pod: int = MAX_ROWS_PER_POD,
                 clock=time.time):
        self.max_pods = max_pods
        self.max_bytes = max_bytes
        self.max_rows_per_pod = max_rows_per_pod
        self._clock = clock
        self._lock = GuardedLock("obs.DiagnosisEngine",
                                 reentrant=False)
        # pod key → diag, LRU order (OrderedDict, most-recent last)
        self._pods: "collections.OrderedDict[str, _PodDiag]" = \
            collections.OrderedDict()
        self._bytes = 0
        # gang full-name → set of member pod keys currently tracked
        self._gangs: Dict[str, set] = {}
        # cluster rollup: (plugin, norm reason) → pods currently blocked
        self._blockers: Dict[Tuple[str, str], int] = {}
        self._fed = 0
        self._resolved = 0
        self._evicted = 0

    # -- write path (scheduler feed) -----------------------------------------

    def on_attempt(self, pod_key: str, gang: Optional[str], outcome: str,
                   plugin: str, reason: str,
                   diagnosis_rows: Optional[List[Dict[str, Any]]] = None,
                   attempt: int = 0) -> None:
        """One resolved-unsuccessfully scheduling cycle.  ``diagnosis_rows``
        is the bounded (plugin, reason) → node-count summary of the Filter
        sweep (trace.summarize_diagnosis shape); ``plugin``/``reason`` are
        the merged Status attribution (the cycle's headline verdict)."""
        now = self._clock()
        norm_headline = _reasons.normalize(reason)
        with self._lock:
            self._fed += 1
            d = self._pods.get(pod_key)
            if d is None:
                d = _PodDiag(gang, now)
                self._pods[pod_key] = d
                if gang:
                    self._gangs.setdefault(gang, set()).add(pod_key)
                self._bytes += d.bytes
            else:
                self._pods.move_to_end(pod_key)
            old_key = d.blocking_key()
            d.last_seen = now
            d.attempts = max(d.attempts + 1, attempt)
            d.last_outcome = outcome
            d.last_plugin = plugin
            d.last_reason = norm_headline
            seen_this_attempt = set()
            merged: List[Tuple[str, str, int, str]] = []
            if plugin or reason:
                merged.append((plugin, norm_headline, 0, reason))
            for row in diagnosis_rows or ():
                merged.append((row.get("plugin", ""),
                               _reasons.normalize(row.get("reason", "")),
                               int(row.get("nodes", 0)),
                               row.get("reason", "")))
            for rplugin, rreason, nodes, raw in merged:
                key = (rplugin, rreason)
                row = d.rows.get(key)
                if row is None:
                    if len(d.rows) >= self.max_rows_per_pod:
                        continue           # bounded: keep the earliest keys
                    row = d.rows[key] = _Row(rplugin, rreason)
                    cost = (_ROW_BASE_BYTES + len(rreason)
                            + len(rplugin))
                    d.bytes += cost
                    self._bytes += cost
                if key not in seen_this_attempt:
                    row.cycles += 1
                    seen_this_attempt.add(key)
                if nodes:
                    row.nodes = nodes      # last attempt's count wins
                if not row.example:
                    row.example = raw[:160]
            self._reblock_locked(old_key, d.blocking_key())
            self._trim_locked()

    def on_resolved(self, pod_key: str, outcome: str = "bound") -> None:
        """The pod stopped being pending (bound, or deleted): its diagnosis
        is no longer a question anyone needs answered — evict."""
        with self._lock:
            d = self._pods.pop(pod_key, None)
            if d is None:
                return
            self._resolved += 1
            self._drop_locked(pod_key, d)

    # -- internals ------------------------------------------------------------

    def _drop_locked(self, pod_key: str, d: _PodDiag) -> None:
        self._bytes -= d.bytes
        self._reblock_locked(d.blocking_key(), None)
        if d.gang:
            members = self._gangs.get(d.gang)
            if members is not None:
                members.discard(pod_key)
                if not members:
                    del self._gangs[d.gang]

    def _reblock_locked(self, old: Optional[Tuple[str, str]],
                 new: Optional[Tuple[str, str]]) -> None:
        if old == new:
            return
        if old is not None:
            n = self._blockers.get(old, 0) - 1
            if n <= 0:
                self._blockers.pop(old, None)
            else:
                self._blockers[old] = n
        if new is not None:
            self._blockers[new] = self._blockers.get(new, 0) + 1

    def _trim_locked(self) -> None:
        while self._pods and (len(self._pods) > self.max_pods
                              or self._bytes > self.max_bytes):
            key, d = self._pods.popitem(last=False)   # LRU victim
            self._evicted += 1
            self._drop_locked(key, d)

    # -- read path (/debug/explain, the explain CLI) --------------------------

    def _find_pod_locked(self, query: str) -> Optional[str]:
        if query in self._pods:
            return query
        # substring convenience: `?pod=w-003` finds `default/w-003`
        hits = [k for k in self._pods if query in k]
        return hits[0] if len(hits) == 1 else None

    def explain_pod(self, query: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            key = self._find_pod_locked(query)
            if key is None:
                return None
            d = self._pods[key]
            rows = sorted(d.rows.values(),
                          key=lambda r: (-r.nodes, -r.cycles, r.plugin))
            out = {
                "pod": key,
                "gang": d.gang,
                "pending_for_s": round(self._clock() - d.first_seen, 3),
                "attempts": d.attempts,
                "last_outcome": d.last_outcome,
                "blocking_plugin": d.last_plugin,
                "blocking_reason": d.last_reason,
                "reasons": [r.to_dict() for r in rows],
            }
        out["suggestion"] = _reasons.suggest(out["blocking_plugin"],
                                             out["blocking_reason"])
        return out

    def explain_gang(self, query: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            full = query if query in self._gangs else None
            if full is None:
                hits = [g for g in self._gangs if query in g]
                full = hits[0] if len(hits) == 1 else None
            if full is None:
                return None
            members = sorted(self._gangs[full])
            outcomes: Dict[str, int] = {}
            plugins: Dict[str, int] = {}
            agg: Dict[Tuple[str, str], List[int]] = {}  # → [members, nodes]
            oldest = None
            attempts = 0
            for key in members:
                d = self._pods.get(key)
                if d is None:
                    continue
                outcomes[d.last_outcome] = outcomes.get(d.last_outcome, 0) + 1
                if d.last_plugin:
                    plugins[d.last_plugin] = plugins.get(d.last_plugin, 0) + 1
                attempts = max(attempts, d.attempts)
                if oldest is None or d.first_seen < oldest:
                    oldest = d.first_seen
                for (rplugin, rreason), row in d.rows.items():
                    ent = agg.setdefault((rplugin, rreason), [0, 0])
                    ent[0] += 1
                    ent[1] = max(ent[1], row.nodes)
            top = sorted(agg.items(), key=lambda kv: (-kv[1][0], -kv[1][1]))
            blocking = max(plugins.items(), key=lambda kv: kv[1])[0] \
                if plugins else ""
            out = {
                "gang": full,
                "members_pending": len(members),
                "outcomes": dict(sorted(outcomes.items())),
                "blocking_plugin": blocking,
                "max_attempts": attempts,
                "pending_for_s": (round(self._clock() - oldest, 3)
                                  if oldest is not None else 0.0),
                "top_reasons": [
                    {"plugin": p, "reason": r, "members": m, "nodes": n}
                    for (p, r), (m, n) in top[:10]],
            }
        # suggestion: prefer a ROOT-CAUSE reason over derivative ones —
        # members parked at the permit barrier are waiting FOR the blocked
        # members, and siblings bouncing off a denied-PG/denied-set window
        # echo one member's sweep failure; both dominate the member count
        # while explaining nothing the operator can act on directly
        lead = None
        for r in out["top_reasons"]:
            low = r["reason"].lower()
            if "denied" in low or "window" in low or "permit barrier" in low:
                continue
            lead = r
            break
        if lead is None and out["top_reasons"]:
            lead = out["top_reasons"][0]
        out["suggestion"] = _reasons.suggest(
            lead["plugin"] if lead else out["blocking_plugin"],
            lead["reason"] if lead else "")
        return out

    def top_blockers(self, n: int = 10) -> List[Dict[str, Any]]:
        with self._lock:
            top = sorted(self._blockers.items(),
                         key=lambda kv: (-kv[1], kv[0]))[:n]
        return [{"plugin": p, "reason": r, "pods": c,
                 "suggestion": _reasons.suggest(p, r)}
                for (p, r), c in top]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "pods": len(self._pods),
                "gangs": len(self._gangs),
                "approx_bytes": self._bytes,
                "max_pods": self.max_pods,
                "max_bytes": self.max_bytes,
                "fed_total": self._fed,
                "resolved_total": self._resolved,
                "evicted_total": self._evicted,
            }

    def dump(self) -> Dict[str, Any]:
        """The no-argument /debug/explain payload: cluster-wide rollup."""
        with self._lock:
            gangs = sorted(self._gangs)
        return {
            "stats": self.stats(),
            "top_blockers": self.top_blockers(),
            "pending_gangs": gangs[:64],
        }
