"""Fleet throughput telemetry: the sustained-rate counters and gauges the
arrival-storm bench (bench.py --storm) and the sharded scheduler core
(ROADMAP item 1) are judged against.

One ``ThroughputTelemetry`` per scheduler, fed from the three points that
define throughput:

- ``on_arrival``   a pending pod entered the scheduling queue
  (``sched/queue.SchedulingQueue.add``) — the arrival-rate gauge's source;
- ``on_cycle``     a scheduling cycle started (``scheduleOne``) —
  ``tpusched_scheduling_cycles_total``;
- ``on_bind``      a bind committed — ``tpusched_binds_total``.

Plus two scrape-time gauges registered per scheduler:
``tpusched_pod_arrivals_per_second`` (rolling-window arrival rate) and
``tpusched_bind_pool_backlog`` (binding tasks queued behind the
``_BindingPool`` workers — the first queue to grow when bind throughput,
not scheduling throughput, is the bottleneck).  Queue depths themselves
are already exposed as ``tpusched_pending_pods{queue=...}``.

Shadow isolation: a ``publish=False`` instance (what-if planner, defrag
trials) is a publish-inert shell — no counter children, no gauges, so a
trial run can never publish hypothetical binds/sec as fleet throughput.
Feed methods still bump two PRIVATE ints (``binds_observed``,
``cycles_observed``) that only the instance's own health timeline
reads.  The hot-path cost of a publishing instance is one counter
increment (arrivals also append one float to a bounded deque).
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Optional

from ..util.metrics import (REGISTRY, binds_total, escape_label_value,
                            scheduling_cycles_total)

__all__ = ["ThroughputTelemetry", "ARRIVAL_WINDOW_S"]

ARRIVAL_WINDOW_S = 60.0
_ARRIVAL_CAP = 65536        # bounded memory even under a 1k+/s storm


class ThroughputTelemetry:
    def __init__(self, scheduler_name: str = "", publish: bool = True,
                 clock=time.monotonic,
                 window_s: float = ARRIVAL_WINDOW_S):
        self.publish = publish
        self._clock = clock
        self._window_s = window_s
        # private tallies kept even when publish=False: the health
        # timeline (obs/timeline.py) derives its bind/cycle rate
        # families from these, and a SHADOW scheduler's private timeline
        # (virtual-time replay) needs real counts without touching the
        # global tpusched_binds_total family. Plain ints: += 1 is
        # GIL-atomic, and an approximate read is fine for a rate family.
        self.binds_observed = 0
        self.cycles_observed = 0
        # deque.append is atomic under the GIL; the rate reader copies.
        self._arrivals: "collections.deque[float]" = collections.deque(
            maxlen=_ARRIVAL_CAP)
        if not publish:
            # inert shell: no counter children, no gauges — the feed
            # methods check self.publish and return
            self._cycles = None
            self._binds = None
            return
        self._name = scheduler_name
        # per-shard children, created lazily as lanes first report ('' is
        # the classic single dispatch loop); the family total over all
        # shards keeps the pre-sharding meaning of binds/cycles per
        # scheduler
        self._cycles = {"": scheduling_cycles_total.with_labels(
            scheduler_name, "")}
        self._binds = {"": binds_total.with_labels(scheduler_name, "")}
        esc = escape_label_value(scheduler_name)
        self._labels = f'scheduler="{esc}"' if scheduler_name else ""
        ref = weakref.ref(self)

        def arrival_rate(ref=ref):
            live = ref()
            # None = dead provider: pruned at the next scrape instead of
            # a stale zero series (same discipline as the queue gauges)
            return live.arrival_rate() if live is not None else None
        REGISTRY.gauge_func(
            "tpusched_pod_arrivals_per_second", arrival_rate,
            "Pending-pod arrival rate over the rolling window, by "
            "scheduler profile.", labels=self._labels)

    def register_bind_backlog(self, backlog_fn) -> None:
        """Expose the binding pool's queued-task depth as
        ``tpusched_bind_pool_backlog``.  ``backlog_fn`` must already be
        weakref-safe (return None when its target died)."""
        if not self.publish:
            return
        REGISTRY.gauge_func(
            "tpusched_bind_pool_backlog", backlog_fn,
            "Binding tasks queued behind the bind-pool workers.",
            labels=self._labels)

    # -- feed points (hot path) ----------------------------------------------

    def on_arrival(self) -> None:
        if self.publish:
            self._arrivals.append(self._clock())

    def on_cycle(self, shard: str = "") -> None:
        self.cycles_observed += 1
        if self.publish:
            child = self._cycles.get(shard)
            if child is None:
                child = self._cycles[shard] = \
                    scheduling_cycles_total.with_labels(self._name, shard)
            child.inc()

    def on_bind(self, shard: str = "") -> None:
        self.binds_observed += 1
        if self.publish:
            child = self._binds.get(shard)
            if child is None:
                child = self._binds[shard] = \
                    binds_total.with_labels(self._name, shard)
            child.inc()

    # -- derived -------------------------------------------------------------

    def arrival_rate(self) -> float:
        """Arrivals per second over the rolling window.  For a window not
        yet ``window_s`` old the divisor is the observed span (a storm's
        first seconds read as their true rate, not diluted by the empty
        prefix)."""
        now = self._clock()
        horizon = now - self._window_s
        arrivals = list(self._arrivals)
        recent = [t for t in arrivals if t >= horizon]
        if not recent:
            return 0.0
        span = min(self._window_s, max(now - recent[0], 1e-3))
        return len(recent) / span
