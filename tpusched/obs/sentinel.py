"""Anomaly sentinel: the detection stage of the closed incident loop
(ISSUE 20) — a detector registry evaluated once per timeline tick, with
hysteresis and cooldown, each firing pinning a flight-recorder anomaly
and triggering a black-box incident capture (obs/incident.py).

Detectors are PURE functions over the committed timeline sample plus a
small trailing-baseline view — they hold no locks, touch no scheduler
state, and a raising detector is counted and skipped, never propagated
into the housekeeping thread.  Hysteresis (``enter_ticks`` consecutive
abnormal ticks before firing, ``clear_ticks`` normal ticks before
re-arming) keeps one noisy sample from paging anyone; cooldown bounds
bundle volume when a condition oscillates.

Shadow isolation: a ``publish=False`` sentinel evaluates identically
(virtual-time policy evaluation NEEDS the firings) but never bumps the
global ``tpusched_sentinel_firings_total`` family — firings pin into
whatever recorder it was wired with (the shadow's private one).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..util import klog
from ..util.metrics import sentinel_firings_total

__all__ = ["Detector", "AnomalySentinel", "default_detectors",
           "BaselineView"]

_FIRINGS_CAP = 256          # bounded firing log (newest kept)
_BASELINE_TICKS = 30        # trailing window the baselines average over
DEFAULT_ENTER_TICKS = 3
DEFAULT_CLEAR_TICKS = 5
DEFAULT_COOLDOWN_TICKS = 120


class BaselineView:
    """Trailing per-family view handed to detectors: ``mean(name)`` /
    ``prev(name)`` over the last ``_BASELINE_TICKS`` committed samples,
    EXCLUDING the sample under evaluation (a collapse must be judged
    against the healthy past, not against itself)."""

    def __init__(self) -> None:
        self._history: List[Dict[str, float]] = []

    def push(self, values: Dict[str, float]) -> None:
        self._history.append(values)
        if len(self._history) > _BASELINE_TICKS:
            self._history.pop(0)

    def ticks(self) -> int:
        return len(self._history)

    def prev(self, name: str) -> Optional[float]:
        for values in reversed(self._history):
            if name in values:
                return values[name]
        return None

    def mean(self, name: str) -> Optional[float]:
        xs = [v[name] for v in self._history if name in v]
        return (sum(xs) / len(xs)) if xs else None


class Detector:
    """One named anomaly check.  ``check(values, baseline)`` returns a
    detail dict while the condition holds, else None.  The sentinel
    applies hysteresis/cooldown around it."""

    def __init__(self, name: str,
                 check: Callable[[Dict[str, float], BaselineView],
                                 Optional[Dict[str, Any]]],
                 enter_ticks: int = DEFAULT_ENTER_TICKS,
                 clear_ticks: int = DEFAULT_CLEAR_TICKS,
                 cooldown_ticks: int = DEFAULT_COOLDOWN_TICKS):
        self.name = name
        self.check = check
        self.enter_ticks = max(1, int(enter_ticks))
        self.clear_ticks = max(1, int(clear_ticks))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        # hysteresis state (owned by the sentinel's tick thread)
        self.abnormal_streak = 0
        self.normal_streak = 0
        self.active = False
        self.cooldown_left = 0
        self.firings = 0

    def reset(self) -> None:
        self.abnormal_streak = self.normal_streak = 0
        self.active = False
        self.cooldown_left = 0


class AnomalySentinel:
    """Evaluates every registered detector against each timeline tick.

    Wire-up: ``sentinel.attach(timeline)`` registers the sentinel as a
    tick listener; ``on_firing`` (the incident manager's capture hook)
    and ``recorder`` (the scheduler's flight recorder, for pinned
    anomalies) are injected by the scheduler.
    """

    def __init__(self, detectors: Optional[List[Detector]] = None,
                 publish: bool = True, recorder=None,
                 on_firing: Optional[Callable[[Dict[str, Any]], None]]
                 = None):
        self.publish = publish
        self.recorder = recorder
        self.on_firing = on_firing
        self._lock = threading.Lock()
        self._detectors: Dict[str, Detector] = {}
        for d in (detectors if detectors is not None
                  else default_detectors()):
            self._detectors[d.name] = d
        self._baseline = BaselineView()
        self._firings: List[Dict[str, Any]] = []
        self._ticks_total = 0
        self._errors_total = 0
        self._attached_to = None

    # -- registry -------------------------------------------------------------

    def register(self, detector: Detector) -> None:
        """Add or REPLACE a detector (replace resets hysteresis)."""
        with self._lock:
            self._detectors[detector.name] = detector

    def detector(self, name: str) -> Optional[Detector]:
        with self._lock:
            return self._detectors.get(name)

    def detector_names(self) -> List[str]:
        with self._lock:
            return sorted(self._detectors)

    def attach(self, timeline) -> None:
        """Listen on ``timeline`` ticks (idempotent; re-attach moves)."""
        if self._attached_to is not None \
                and self._attached_to is not timeline:
            self._attached_to.remove_listener(self.on_sample)
        self._attached_to = timeline
        timeline.add_listener(self.on_sample)

    # -- evaluation -----------------------------------------------------------

    def on_sample(self, sample: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Evaluate every detector against one committed timeline sample.
        Returns the firings this tick produced (tests drive this
        directly with synthetic samples)."""
        values = sample.get("v", {})
        fired: List[Dict[str, Any]] = []
        with self._lock:
            self._ticks_total += 1
            detectors = list(self._detectors.values())
            baseline = self._baseline
            for d in detectors:
                if d.cooldown_left > 0:
                    d.cooldown_left -= 1
                try:
                    detail = d.check(values, baseline)
                # tpulint: disable=exception-taxonomy — a buggy detector
                # must not take the housekeeping thread down; counted in
                # errors_total and visible in stats()
                except Exception:  # noqa: BLE001
                    self._errors_total += 1
                    continue
                if detail is None:
                    d.abnormal_streak = 0
                    d.normal_streak += 1
                    if d.active and d.normal_streak >= d.clear_ticks:
                        d.active = False
                    continue
                d.normal_streak = 0
                d.abnormal_streak += 1
                if d.active or d.cooldown_left > 0:
                    continue
                if d.abnormal_streak < d.enter_ticks:
                    continue
                d.active = True
                d.cooldown_left = d.cooldown_ticks
                d.firings += 1
                firing = {"detector": d.name, "t": sample.get("t"),
                          "wall": sample.get("wall"), "detail": detail,
                          "values": dict(values)}
                self._firings.append(firing)
                if len(self._firings) > _FIRINGS_CAP:
                    self._firings.pop(0)
                fired.append(firing)
            # the evaluated sample joins the baseline AFTER evaluation:
            # a collapse is judged against the healthy past only
            baseline.push(values)
        for firing in fired:
            self._emit(firing)
        return fired

    def _emit(self, firing: Dict[str, Any]) -> None:
        name = firing["detector"]
        if self.publish:
            sentinel_firings_total.with_labels(name).inc()
        try:
            from ..trace import pin_event
            pin_event(f"sentinel_{name}", recorder=self.recorder,
                      **{k: v for k, v in firing["detail"].items()
                         if isinstance(v, (str, int, float, bool))})
        except Exception as e:  # noqa: BLE001 — pinning is advisory
            klog.V(4).info_s("sentinel pin failed", err=str(e))
        if self.on_firing is not None:
            try:
                self.on_firing(firing)
            except Exception as e:  # noqa: BLE001 — incident capture
                # failing must never take detection down with it
                klog.error_s(e, "incident capture hook failed",
                             detector=name)

    # -- reads ----------------------------------------------------------------

    def firings(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._firings)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "ticks_total": self._ticks_total,
                "errors_total": self._errors_total,
                "firings_total": sum(d.firings
                                     for d in self._detectors.values()),
                "detectors": {
                    d.name: {"firings": d.firings, "active": d.active,
                             "cooldown_left": d.cooldown_left}
                    for d in self._detectors.values()},
            }

    def census(self) -> Dict[str, int]:
        """{detector: firing count}, zero-suppressed — the deterministic
        replay/evaluation comparison view."""
        with self._lock:
            return {d.name: d.firings
                    for d in self._detectors.values() if d.firings}


# -- the default detector set -------------------------------------------------

def default_detectors(  # noqa: PLR0913 — the knobs ARE the spec
        collapse_ratio: float = 0.2,
        collapse_min_baseline: float = 0.5,
        collapse_min_pending: float = 8.0,
        burn_threshold: float = 2.0,
        straggler_rate: float = 1.0,
        escalation_rate: float = 5.0,
        quota_conflict_rate: float = 50.0,
        fanout_backlog: float = 4096.0) -> List[Detector]:
    """The eight standing detectors.  Thresholds are constructor knobs so
    tests and benches can tighten them; the defaults are deliberately
    conservative — a sentinel that cries wolf gets disabled in a week.
    Detectors returning None when their family is absent makes every one
    of them safe on shadow timelines (global-metric families are live
    schedulers only)."""

    def bind_rate_collapse(v, base):
        rate, pending = v.get("bind_rate"), v.get("pending_pods", 0.0)
        if rate is None or pending < collapse_min_pending:
            return None
        mean = base.mean("bind_rate")
        if mean is None or mean < collapse_min_baseline:
            return None
        if rate < collapse_ratio * mean:
            return {"bind_rate": rate, "baseline": mean,
                    "pending_pods": pending,
                    "reason": "bind rate collapsed vs trailing baseline "
                              "while pods stayed pending"}
        return None

    def slo_burn_spike(v, base):
        burn = v.get("slo_burn")
        if burn is not None and burn > burn_threshold:
            return {"burn_rate": burn, "threshold": burn_threshold,
                    "reason": "SLO burn rate above threshold"}
        return None

    def straggler_storm(v, base):
        rate = v.get("stragglers")
        if rate is not None and rate > straggler_rate:
            return {"straggler_edges_per_s": rate,
                    "reason": "gang straggler edges accruing fleet-wide"}
        return None

    def shard_starvation(v, base):
        rate = v.get("shard_escalations")
        if rate is not None and rate > escalation_rate:
            return {"escalations_per_s": rate,
                    "reason": "shard escalations hot — lanes starving "
                              "behind the global lane"}
        return None

    def quota_conflict_hot_loop(v, base):
        rate = v.get("quota_conflicts")
        if rate is not None and rate > quota_conflict_rate:
            return {"quota_conflicts_per_s": rate,
                    "reason": "quota compare-and-reserve conflicts "
                              "looping hot"}
        return None

    def degraded_mode_entry(v, base):
        cur = v.get("degraded", 0.0)
        prev = base.prev("degraded")
        if cur >= 1.0 and (prev is None or prev < 1.0):
            return {"reason": "scheduler entered degraded mode "
                              "(pop-dispatch paused after API retry "
                              "exhaustion)"}
        return None

    def native_differential_mismatch(v, base):
        rate = v.get("native_mismatches")
        if rate is not None and rate > 0.0:
            return {"mismatches_per_s": rate,
                    "reason": "native dispatch disagreed with the "
                              "pure-Python oracle"}
        return None

    def watch_fanout_backlog(v, base):
        depth = v.get("fanout_backlog")
        if depth is not None and depth > fanout_backlog:
            return {"queue_depth": depth,
                    "reason": "apiserver watch fan-out backlog growing"}
        return None

    return [
        Detector("bind_rate_collapse", bind_rate_collapse),
        Detector("slo_burn_spike", slo_burn_spike),
        Detector("straggler_storm", straggler_storm),
        Detector("shard_starvation", shard_starvation),
        Detector("quota_conflict_hot_loop", quota_conflict_hot_loop),
        # entry is an EDGE — one tick is the event
        Detector("degraded_mode_entry", degraded_mode_entry,
                 enter_ticks=1),
        Detector("native_differential_mismatch",
                 native_differential_mismatch, enter_ticks=1),
        Detector("watch_fanout_backlog", watch_fanout_backlog),
    ]
