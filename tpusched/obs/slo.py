"""Scheduling SLO layer: latency objectives + burn accounting.

Two built-in objectives, fed from clocks the scheduler already keeps:

- ``pod_e2e``     first-enqueue → bound per pod (the user-perceived
                  latency; fed by the binding thread at bind commit);
- ``gang_bound``  PodGroup-to-Bound per gang (the north-star interval the
                  gang stitcher and Coscheduling's post_bind already
                  compute: first member SEEN → quorum complete).

Per objective the tracker keeps cumulative event/breach counters
(``tpusched_slo_events_total`` / ``tpusched_slo_breaches_total``, labeled
``objective`` — PromQL burn rate is rate(breaches)/rate(events)), a
rolling-window burn-rate gauge (``tpusched_slo_burn_rate``), the objective
itself as a gauge (``tpusched_slo_objective_seconds`` — dashboards draw
the target line without config access), and a bounded sample window for
exact p50/p99 in ``summary()`` (the BENCH-json SLO block).

Objectives come from the scheduler profile (``slo_pod_e2e_s`` /
``slo_gang_bound_s``; 0 disables an objective).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, Optional

from ..util.metrics import REGISTRY

POD_E2E = "pod_e2e"
GANG_BOUND = "gang_bound"

DEFAULT_POD_E2E_S = 2.0       # matches the 2 s north-star budget
DEFAULT_GANG_BOUND_S = 2.0    # (BASELINE.md PodGroup-to-Bound)
_WINDOW = 1024                # rolling burn-rate / quantile window

slo_events = REGISTRY.counter_vec(
    "tpusched_slo_events_total", ("objective",),
    "SLO-governed completions observed, by objective.")
slo_breaches = REGISTRY.counter_vec(
    "tpusched_slo_breaches_total", ("objective",),
    "Completions that exceeded their latency objective.")
slo_burn_rate = REGISTRY.gauge_vec(
    "tpusched_slo_burn_rate", ("objective",),
    "Breach fraction over the rolling window (0 = within SLO).")
slo_objective_seconds = REGISTRY.gauge_vec(
    "tpusched_slo_objective_seconds", ("objective",),
    "The configured latency objective, as data.")


class _Objective:
    __slots__ = ("name", "target_s", "count", "breaches", "window",
                 "window_breaches", "samples", "first_stamp", "last_stamp")

    def __init__(self, name: str, target_s: float, window: int = _WINDOW):
        self.name = name
        self.target_s = target_s
        self.count = 0
        self.breaches = 0
        # first/last observation stamps on the TRACKER's clock: under a
        # virtual-time replay these delimit the replayed interval, so
        # summary() can say "this attainment describes N recorded hours"
        # rather than the wall seconds the replay took
        self.first_stamp: Optional[float] = None
        self.last_stamp: Optional[float] = None
        # rolling breach window (booleans, with a running count so the
        # per-bind burn computation is O(1), not an O(window) sum) +
        # bounded sample window for exact quantiles — an always-on
        # control plane must not grow
        self.window: "collections.deque[bool]" = collections.deque(
            maxlen=window)
        self.window_breaches = 0
        self.samples: "collections.deque[float]" = collections.deque(
            maxlen=window)

    def push(self, breached: bool, seconds: float) -> float:
        """Record one completion into the rolling windows; returns the
        current burn fraction."""
        if len(self.window) == self.window.maxlen and self.window[0]:
            self.window_breaches -= 1     # the value about to fall off
        self.window.append(breached)
        if breached:
            self.window_breaches += 1
        self.samples.append(seconds)
        return self.window_breaches / len(self.window)


class SLOTracker:
    def __init__(self, pod_e2e_s: float = DEFAULT_POD_E2E_S,
                 gang_bound_s: float = DEFAULT_GANG_BOUND_S,
                 publish: bool = True, window: int = _WINDOW,
                 clock=time.time):
        """``publish=False`` builds a PRIVATE tracker (shadow schedulers:
        what-if planner, defrag trials): observations accumulate in the
        internal windows for summary() but never touch the process-global
        ``tpusched_slo_*`` metric families — a trial bind's latency must
        not count into the production burn rate.  ``window`` sizes the
        rolling burn/quantile deques: bench installs one large enough to
        hold EVERY counted run's events so its summary quantiles and
        breach counts describe the same window.  ``clock`` stamps each
        observation (wall-flavored): a replay scheduler injects its
        virtual clock so the summary's observed span is REPLAY time."""
        self._lock = threading.Lock()
        self._publish = publish
        # wall-flavored by design: the stamps pair with the scheduler's
        # wall latency clock (and become virtual wall under replay)
        self._clock = clock
        # introspectable config (the scheduler re-installs the global
        # tracker only when its profile asks for DIFFERENT targets)
        self.targets = (pod_e2e_s, gang_bound_s)
        self._objectives: Dict[str, _Objective] = {}
        for name, target in ((POD_E2E, pod_e2e_s),
                             (GANG_BOUND, gang_bound_s)):
            if target and target > 0:
                self._objectives[name] = _Objective(name, target, window)
                if publish:
                    slo_objective_seconds.with_labels(name).set(target)

    def objective_names(self):
        return tuple(self._objectives)

    def observe(self, objective: str, seconds: float) -> Optional[bool]:
        """Record one completion; returns whether it breached (None when
        the objective is disabled/unknown)."""
        stamp = self._clock()
        with self._lock:
            obj = self._objectives.get(objective)
            if obj is None:
                return None
            breached = seconds > obj.target_s
            obj.count += 1
            if breached:
                obj.breaches += 1
            if obj.first_stamp is None:
                obj.first_stamp = stamp
            obj.last_stamp = stamp
            burn = obj.push(breached, seconds)
        if self._publish:
            slo_events.with_labels(objective).inc()
            if breached:
                slo_breaches.with_labels(objective).inc()
            slo_burn_rate.with_labels(objective).set(round(burn, 4))
        return breached

    def summary(self) -> Dict[str, Any]:
        """Per-objective digest (the BENCH-json SLO block and the
        /debug/explain footer): target vs observed p50/p99, totals, burn."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, obj in self._objectives.items():
                xs = sorted(obj.samples)

                def q(p):
                    if not xs:
                        return 0.0
                    return xs[min(len(xs) - 1,
                                  max(0, int(round(p * (len(xs) - 1)))))]
                out[name] = {
                    "objective_s": obj.target_s,
                    "events": obj.count,
                    "breaches": obj.breaches,
                    # the observed interval on the tracker's own clock —
                    # a virtual-time replay reports the REPLAYED span
                    # here, not the wall seconds it compressed into
                    "span_s": round(obj.last_stamp - obj.first_stamp, 3)
                    if obj.first_stamp is not None else 0.0,
                    "attainment": round(1.0 - (obj.breaches / obj.count), 4)
                    if obj.count else 1.0,
                    "burn_rate": round(
                        (obj.window_breaches / len(obj.window))
                        if obj.window else 0.0, 4),
                    "p50_s": round(q(0.50), 4),
                    "p99_s": round(q(0.99), 4),
                }
        return out
