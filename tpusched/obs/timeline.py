"""Fleet health timeline: the bounded in-process time-series ring every
other incident-plane piece stands on (ISSUE 20).

Every observability surface built so far answers "what is happening
NOW" — the flight recorder's last N cycles, /debug/explain's rolling
rejections, the profiler's rolling sample window.  None of them records
how fleet health EVOLVED, so by the time a human looks at a 3am wedge
the evidence has scrolled out of the bounded rings.  ``HealthTimeline``
closes that gap: one sample per tick over a curated family set (bind
rate, pending depth, queue-wait/pod-e2e quantiles, SLO burn,
fragmentation, shard/quota conflict rates, degraded-mode gauge,
native-dispatch fallback rate, lock wait, bind-pool backlog, watch
fan-out backlog), entry+byte budgeted, overflow counted never stored.

Clock discipline: the timeline ticks on the scheduler's injected
``Clock`` (util/clock.py).  Live, the housekeeping lane paces
``maybe_tick()`` once a second under WallClock.  Under VirtualClock
replay the timeline ARMS its next tick in the clock's deadline registry
(``arm_on``), so ``sim/replay.advance_until`` jumps to every tick
boundary and ``Scheduler.run_timers_once`` fires it — a recorded hour
replayed at 376x yields the full hour's timeline, deterministically.

Shadow isolation: a ``publish=False`` timeline samples into its own
ring (virtual-time replay needs the data) but never touches the global
``tpusched_timeline_*`` counters.  The /debug/timeline route resolves
the process-global instance (obs.default_timeline) at request time.
"""
from __future__ import annotations

import json
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..util import klog
from ..util.clock import WALL, Clock
from ..util.metrics import (timeline_overflow_total, timeline_samples_total)

__all__ = ["HealthTimeline", "register_scheduler_families",
           "DEFAULT_INTERVAL_S", "DEFAULT_MAX_SAMPLES", "DEFAULT_MAX_BYTES"]

DEFAULT_INTERVAL_S = 1.0
# ~68 min at 1 Hz; the byte budget is the binding bound under wide
# family sets (each sample is a flat {family: float} dict)
DEFAULT_MAX_SAMPLES = 4096
DEFAULT_MAX_BYTES = 1 << 20

_TICK_LABEL = "timeline-tick"
# fragmentation is the one non-O(1) family: recompute at most every
# N ticks AND only when the cache mutation cursor moved (capacity.py
# rate-limits its scrape-time twin the same way)
_FRAG_EVERY_TICKS = 15


class HealthTimeline:
    """Bounded time-series ring over registered health families.

    A FAMILY is ``(name, fn, kind)``: ``fn()`` returns the current value
    (float, or None for "no reading this tick").  ``kind="gauge"``
    samples the value as-is; ``kind="rate"`` treats the value as a
    cumulative counter and stores the per-second delta between ticks
    (first tick of a rate family stores 0.0 — no baseline yet).  Family
    functions must be cheap and must never block on scheduler locks held
    across I/O; exceptions are swallowed and counted (``errors_total``),
    never propagated into the dispatch/housekeeping thread.
    """

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 max_samples: int = DEFAULT_MAX_SAMPLES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 publish: bool = True,
                 clock: Optional[Clock] = None):
        self.interval_s = float(interval_s)
        self.max_samples = int(max_samples)
        self.max_bytes = int(max_bytes)
        self.publish = publish
        self._clock: Clock = clock if clock is not None else WALL
        self._lock = threading.Lock()
        self._families: Dict[str, Tuple[Callable[[], Any], str]] = {}
        self._rate_last: Dict[str, Tuple[float, float]] = {}  # name -> (t, raw)
        self._samples: List[Dict[str, Any]] = []
        self._bytes = 0
        self._listeners: List[Callable[[Dict[str, Any]], None]] = []
        self._last_tick = -1e18
        self._tick_token: Optional[int] = None
        self._armed = False
        # counters mirrored locally so a publish=False shadow still
        # reports its own census (replay determinism reads these)
        self._samples_total = 0
        self._overflow_total = 0
        self._errors_total = 0
        self._tick_seconds_total = 0.0

    # -- family registry ------------------------------------------------------

    def register_family(self, name: str, fn: Callable[[], Any],
                        kind: str = "gauge") -> None:
        """Register (or REPLACE — re-register-replaces, same semantics as
        gauge_func) a health family.  ``kind`` is ``gauge`` or ``rate``."""
        if kind not in ("gauge", "rate"):
            raise ValueError(f"unknown family kind {kind!r}")
        with self._lock:
            self._families[name] = (fn, kind)
            self._rate_last.pop(name, None)

    def unregister_family(self, name: str) -> None:
        with self._lock:
            self._families.pop(name, None)
            self._rate_last.pop(name, None)

    def family_names(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    def add_listener(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Listeners run after each tick, OUTSIDE the ring lock, with the
        committed sample (the sentinel hooks here).  A raising listener
        is counted as an error, never propagated."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    # -- ticking --------------------------------------------------------------

    def arm_on(self, clock: Clock) -> None:
        """Adopt ``clock`` as the tick clock and arm the next tick in its
        deadline registry.  Under WallClock ``arm`` is a no-op (the live
        housekeeping lane paces ``maybe_tick`` itself); under
        VirtualClock this is what makes ``advance_to_next_deadline``
        stop at every tick boundary, so a replayed hour accrues the full
        hour's samples."""
        with self._lock:
            self._clock = clock
            self._armed = True
            self._rearm_locked(self._clock.now())

    def _rearm_locked(self, now: float) -> None:
        if not self._armed:
            return
        if self._tick_token is not None:
            try:
                self._clock.cancel(self._tick_token)
            # tpulint: disable=exception-taxonomy — best-effort cancel of
            # a possibly already-fired (stale) deadline token; the rearm
            # below is the operation that matters
            except Exception:  # noqa: BLE001
                pass
        self._tick_token = self._clock.arm(_TICK_LABEL,
                                           now + self.interval_s)

    def disarm(self) -> None:
        """Stop arming tick deadlines (``maybe_tick`` still works).  The
        virtual-time replay driver calls this when the recorded span
        ends: a perpetually re-armed tick would keep the drain loop's
        "nothing armed → genuinely unplaceable" exit from ever firing,
        and post-span tick counts would become wall-bounded — i.e.
        nondeterministic across two replays of one trace."""
        with self._lock:
            self._armed = False
            if self._tick_token is not None:
                try:
                    self._clock.cancel(self._tick_token)
                # tpulint: disable=exception-taxonomy — best-effort cancel
                # of a possibly already-fired token during disarm teardown
                except Exception:  # noqa: BLE001
                    pass
                self._tick_token = None

    def maybe_tick(self, now: Optional[float] = None) -> bool:
        """Tick iff a full interval elapsed since the last tick.  Safe to
        call from any thread at any cadence — this is the live
        housekeeping pacing AND the replay driver's fire point."""
        if now is None:
            now = self._clock.now()
        with self._lock:
            if now - self._last_tick < self.interval_s:
                return False
        self.tick(now)
        return True

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Sample every family once and commit one ring entry."""
        if now is None:
            now = self._clock.now()
        t0 = self._clock.now()
        with self._lock:
            families = list(self._families.items())
            self._last_tick = now
        values: Dict[str, float] = {}
        errors = 0
        for name, (fn, kind) in families:
            try:
                raw = fn()
            # tpulint: disable=exception-taxonomy — a failing family must
            # not take the housekeeping/dispatch thread down; the failure
            # is counted (errors_total) and visible in stats()
            except Exception:  # noqa: BLE001
                errors += 1
                continue
            if raw is None:
                continue
            raw = float(raw)
            if kind == "rate":
                last = self._rate_last.get(name)
                self._rate_last[name] = (now, raw)
                if last is None:
                    values[name] = 0.0
                else:
                    dt = max(now - last[0], 1e-9)
                    values[name] = max(0.0, (raw - last[1]) / dt)
            else:
                values[name] = raw
        sample = {"t": now, "wall": self._clock.wall(), "v": values}
        # flat floats: ~16 bytes of overhead per family entry is a good
        # stable approximation without a json.dumps per tick
        approx = 32 + sum(len(k) + 16 for k in values)
        with self._lock:
            self._samples.append(sample)
            self._bytes += approx
            self._samples_total += 1
            self._errors_total += errors
            evicted = 0
            while self._samples and (
                    len(self._samples) > self.max_samples
                    or self._bytes > self.max_bytes):
                old = self._samples.pop(0)
                self._bytes -= 32 + sum(len(k) + 16 for k in old["v"])
                evicted += 1
            if not self._samples:
                self._bytes = 0
            self._overflow_total += evicted
            listeners = list(self._listeners)
            self._rearm_locked(now)
            self._tick_seconds_total += max(0.0, self._clock.now() - t0)
        if self.publish:
            timeline_samples_total.inc()
            if evicted:
                timeline_overflow_total.inc(evicted)
        for fn in listeners:
            try:
                fn(sample)
            except Exception as e:  # noqa: BLE001 — listener bugs are
                # observability bugs, not scheduling bugs
                with self._lock:
                    self._errors_total += 1
                klog.V(4).info_s("timeline listener failed", err=str(e))
        return sample

    # -- reads ----------------------------------------------------------------

    def samples(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._samples)

    def window(self, seconds: float,
               now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Samples with ``t >= now - seconds`` (oldest first)."""
        if now is None:
            now = self._clock.now()
        horizon = now - seconds
        with self._lock:
            return [s for s in self._samples if s["t"] >= horizon]

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._samples),
                "approx_bytes": self._bytes,
                "max_samples": self.max_samples,
                "max_bytes": self.max_bytes,
                "interval_s": self.interval_s,
                "families": sorted(self._families),
                "samples_total": self._samples_total,
                "overflow_total": self._overflow_total,
                "errors_total": self._errors_total,
                "tick_seconds_total": self._tick_seconds_total,
                "armed": self._armed,
            }

    def dump(self, window_s: Optional[float] = None) -> Dict[str, Any]:
        """The /debug/timeline document."""
        samples = (self.window(window_s) if window_s is not None
                   else self.samples())
        return {"stats": self.stats(), "samples": samples}

    def census(self) -> Dict[str, Any]:
        """The deterministic replay-comparison view: counts only, no
        wall stamps (two virtual replays of one trace must render this
        byte-identically)."""
        with self._lock:
            return {"samples_total": self._samples_total,
                    "overflow_total": self._overflow_total,
                    "entries": len(self._samples),
                    "families": sorted(self._families)}

    def census_json(self) -> str:
        return json.dumps(self.census(), sort_keys=True,
                          separators=(",", ":"))


# -- the curated scheduler family set -----------------------------------------

def register_scheduler_families(timeline: HealthTimeline, sched) -> None:
    """Register the curated family set for one scheduler.

    Every family closes over a WEAK reference — the (possibly global)
    timeline must not keep a stopped scheduler alive; a dead ref reads
    as None and the family simply stops producing values (same
    discipline as the registry's gauge_func pruning).  Re-registration
    replaces: in-process restarts (HA failover, bench arms) take the
    families over instead of sampling a corpse.

    Global-metric families (queue-wait/pod-e2e/lock-wait quantiles,
    native-dispatch and shard/quota conflict counters) are registered
    only for ``telemetry=True`` schedulers — a shadow reading global
    counters would fold live-fleet deltas into its private trial
    timeline.
    """
    ref = weakref.ref(sched)
    telemetry = bool(getattr(sched, "_telemetry", True))

    def _with(fn):
        def read():
            live = ref()
            return None if live is None else fn(live)
        return read

    timeline.register_family(
        "bind_rate", _with(lambda s: s._throughput.binds_observed), "rate")
    timeline.register_family(
        "cycle_rate", _with(lambda s: s.cycles_finished), "rate")
    timeline.register_family(
        "pending_pods",
        _with(lambda s: sum(s.queue.pending_counts().values())))
    timeline.register_family("pending_gangs", _with(_pending_gangs))
    timeline.register_family(
        "bind_backlog", _with(lambda s: s._bind_pool.backlog()))
    timeline.register_family(
        "degraded", _with(lambda s: 1.0 if s._degraded.active() else 0.0))
    timeline.register_family(
        "shard_escalations", _with(lambda s: s._router.escalations()),
        "rate")
    timeline.register_family(
        "fanout_backlog",
        _with(lambda s: float(
            s.recorder.health().get("fanout", {}).get("queue_depth", 0))))
    timeline.register_family(
        "stragglers",
        _with(lambda s: s._goodput.stats().get("straggler_edges_total", 0)),
        "rate")
    timeline.register_family("slo_burn", _with(_slo_burn))
    timeline.register_family("frag_largest_placeable",
                             _frag_family(ref, timeline))

    if not telemetry:
        return
    # global-metric families: live schedulers only (guard above)
    from ..util import metrics as m
    timeline.register_family(
        "queue_wait_p99", lambda: _vec_q99(m.queue_wait_seconds))
    timeline.register_family(
        "pod_e2e_p99", lambda: m.e2e_scheduling_seconds.quantile(0.99))
    timeline.register_family(
        "lock_wait_p99", lambda: _vec_q99(m.lock_wait_seconds))
    timeline.register_family(
        "shard_conflicts", m.shard_conflicts_total.value, "rate")
    timeline.register_family(
        "quota_conflicts", m.shard_quota_conflicts_total.value, "rate")
    timeline.register_family(
        "native_fallbacks", m.native_dispatch_fallbacks.value, "rate")
    timeline.register_family(
        "native_mismatches",
        m.native_dispatch_differential_mismatches.value, "rate")


def _pending_gangs(s) -> float:
    gangs = set()

    def visit(wp):
        g = getattr(wp, "gang", None) or getattr(wp, "gang_name", None)
        gangs.add(g if g else getattr(wp, "pod_key", id(wp)))
    try:
        s._fw.iterate_over_waiting_pods(visit)
    # tpulint: disable=exception-taxonomy — advisory census read off a
    # live queue; a racing mutation yields one missing sample, not an
    # error worth the housekeeping thread
    except Exception:  # noqa: BLE001
        return 0.0
    return float(len(gangs))


def _slo_burn(s) -> Optional[float]:
    # live schedulers hold _slo=None and resolve the process-global
    # tracker; shadows hold a private publish=False tracker
    if s._telemetry:
        from . import default_slo
        tracker = default_slo()
    else:
        tracker = s._slo
    if tracker is None:
        return None
    burns = [doc.get("burn_rate", 0.0)
             for doc in tracker.summary().values()]
    return max(burns) if burns else 0.0


def _vec_q99(vec) -> float:
    children = vec.children()
    if not children:
        return 0.0
    return max(c.quantile(0.99) for c in children.values())


def _frag_family(ref, timeline: HealthTimeline):
    """Largest placeable slice (chips) over all pools — the one
    non-O(1) family, so it is memoized on the cache mutation cursor and
    recomputed at most every ``_FRAG_EVERY_TICKS`` ticks (capacity.py
    rate-limits its scrape-time twin the same way; trend data, not a
    scheduling input)."""
    memo = {"cursor": None, "tick": -_FRAG_EVERY_TICKS, "value": None,
            "n": 0}

    def read():
        s = ref()
        if s is None:
            return None
        memo["n"] += 1
        if (memo["value"] is not None
                and memo["n"] - memo["tick"] < _FRAG_EVERY_TICKS):
            return memo["value"]
        try:
            cursor = s.cache.mutation_cursor()
            if cursor == memo["cursor"] and memo["value"] is not None:
                memo["tick"] = memo["n"]
                return memo["value"]
            from .capacity import HostGrid, largest_placeable_chips
            snapshot = s.cache.shared_snapshot()
            best = 0
            for topo in s.informer_factory.tputopologies().items():
                grid = HostGrid.from_spec(topo.spec)
                if grid is None:
                    continue
                placeable, _, _ = largest_placeable_chips(grid, snapshot)
                best = max(best, placeable)
            memo.update(cursor=cursor, tick=memo["n"], value=float(best))
            return memo["value"]
        # tpulint: disable=exception-taxonomy — advisory trend family:
        # on any failure serve the memoized last-good value rather than
        # poison the whole sample
        except Exception:  # noqa: BLE001
            return memo["value"]
    return read
