"""Rejection-reason normalization + unblock-signal hints.

Plugins attach human-oriented reason strings to their unschedulable
Statuses ("0/64 nodes are available: 48 insufficient resource
google.com/tpu", "Pod default/w-003 is rejected in PreFilter because
ElasticQuota research is more than Max").  The diagnosis engine aggregates
rejections ACROSS attempts and ACROSS gang members, so per-attempt
variance — node counts, pod keys, remaining-TTL seconds — must collapse to
one stable key or every retry mints a "new" reason and the bounded
per-pod table fills with noise.

``normalize()`` is that collapse: conservative, regex-based, and loses no
plugin identity (the engine keys on ``(plugin, normalized_reason)``).
``suggest()`` maps a blocking (plugin, reason) to the operator's next
action — the "what would unblock it" half of the why-pending contract.
"""
from __future__ import annotations

import re
from typing import Optional, Tuple

# Standalone integers/decimals (counts, quorums, TTLs) → N.  \b-delimited
# so resource/accelerator tokens survive: "tpu-v5p" and "4x4x4" contain no
# word-boundary-delimited number and normalize to themselves.
_NUM = re.compile(r"\b\d+(?:\.\d+)?\b")
# Object keys after the vocabulary the plugins actually use.  A blanket
# "ns/name" pattern would also eat resource names (google.com/tpu), so the
# preceding keyword anchors it.
_KEYED = re.compile(r"\b(Pod|pod|pgName|member|set)\s+\S+")
_WS = re.compile(r"\s+")


def normalize(reason: str) -> str:
    """Stable aggregation key for a rejection reason string."""
    if not reason:
        return "unknown"
    out = _KEYED.sub(lambda m: f"{m.group(1)} *", reason)
    out = _NUM.sub("N", out)
    return _WS.sub(" ", out).strip()[:160]


# (plugin-or-None, reason-substring) → hint, first match wins.  Substrings
# are matched against the NORMALIZED reason (lower-cased).  None plugin =
# any plugin.
_HINTS: Tuple[Tuple[Optional[str], str, str], ...] = (
    ("CapacityScheduling", "more than max",
     "queue quota exhausted: the namespace's ElasticQuota max is fully "
     "used — raise the quota, or wait for the team's running pods to "
     "finish (tpusched_quota_utilization{namespace=...})"),
    ("CapacityScheduling", "more than min",
     "no spare fleet capacity to borrow: every team is at or above its "
     "guaranteed min — add capacity or rebalance ElasticQuota mins"),
    ("TopologyMatch", "no feasible",
     "no contiguous torus window fits the slice shape: likely "
     "fragmentation — compare tpusched_pool_largest_placeable_chips "
     "against tpusched_pool_free_chips, then run the defrag advisor "
     "(python -m tpusched.cmd.whatif --suggest-migrations)"),
    ("TopologyMatch", "cannot map onto pool",
     "the requested slice shape can never fit this pool's torus "
     "geometry: fix tpu_slice_shape or target a different pool"),
    ("TopologyMatch", "no tputopology pool",
     "no TpuTopology CR matches the requested accelerator: publish the "
     "pool CR or fix tpu_accelerator on the PodGroup"),
    ("Coscheduling", "cannot find enough sibling",
     "fewer member pods exist than the PodGroup's minMember: create the "
     "missing gang members"),
    ("Coscheduling", "denied-podgroup expiration window",
     "the gang was recently mass-denied and is inside its backoff "
     "window: it retries automatically when the window lapses"),
    ("Coscheduling", "cluster-capacity dry-run",
     "the whole gang cannot fit the cluster's free capacity: add nodes "
     "or free capacity before the gang can admit"),
    ("MultiSlice", "incomplete",
     "the atomic multislice set is missing member PodGroups: submit the "
     "remaining slices (all-or-nothing admission)"),
    ("MultiSlice", "denied",
     "the multislice set was recently torn down and is inside its "
     "denied window: it retries automatically"),
    ("GangBindRollback", "",
     "a sibling's bind failed terminally and the gang rolled back "
     "coherently: check apiserver health "
     "(tpusched_api_retry_exhausted_total) — the gang requeues on its "
     "own once writes succeed"),
    (None, "notready",
     "unhealthy hardware: nodes are NotReady — repair or replace them "
     "(tpusched_nodes_not_ready; doc/ops.md 'Node and slice failures')"),
    (None, "not-ready taint",
     "unhealthy hardware: nodes carry the node.tpu.dev/not-ready taint — "
     "repair or replace them (doc/ops.md 'Node and slice failures')"),
    (None, "unschedulable",
     "nodes are cordoned (spec.unschedulable): uncordon them or add "
     "capacity"),
    (None, "untolerated taint",
     "nodes carry taints the pod does not tolerate: add tolerations or "
     "untaint the intended nodes"),
    (None, "insufficient",
     "insufficient free resources on every candidate node: add capacity, "
     "free pods, or (for slice gangs) run the defrag advisor"),
    (None, "no fit indexes",
     "chip-level fit failed on every candidate node (free chips exist "
     "but not in a usable arrangement): free whole chips or add hosts"),
    (None, "claimed by an in-flight slice preemption",
     "the hosts are reserved for a gang whose preemption is draining: "
     "wait for the drain window or target other hosts"),
    (None, "permit barrier",
     "gang quorum has not formed: the remaining members are blocked or "
     "missing — inspect the member rows (or /debug/gangs) for the "
     "member that is NOT waiting"),
)


def suggest(plugin: str, reason: str) -> str:
    """The operator's next action for a blocking (plugin, reason)."""
    low = (reason or "").lower()
    for want_plugin, needle, hint in _HINTS:
        if want_plugin is not None and want_plugin != plugin:
            continue
        if needle and needle not in low:
            continue
        return hint
    if plugin:
        return (f"blocked by plugin {plugin}: inspect the pod's cycle "
                "trace (/debug/trace?pod=...) for the full diagnosis")
    return ("no scheduling attempt recorded yet, or the reason is "
            "uncategorized: check /debug/flightrecorder")
