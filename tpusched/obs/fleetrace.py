"""Fleet trace capture: a durable, bounded, schema-versioned journal of
CLUSTER-level events — the workload record the live fleet actually saw.

PRs 2/5/7 made the running scheduler legible (flight recorder, why-pending,
profiler) but everything they hold dies with the process, and none of it
records the *workload*: which pods arrived when, with what specs and gang
membership, which nodes flapped, which quotas moved, and where each bind
landed.  ROADMAP items 3 (Gavel-style policy evaluation) and 4 (defrag
what-if) both need recorded fleet traces to replay, and on a box that
cannot resolve small wall-clock deltas by A/B (doc/performance.md) a
*deterministic* recorded workload is what turns perf comparisons into
cycle counts instead of noise.

Capture sits at the two boundaries that define cluster reality:

- the **watch boundary** (``APIServer.add_watch``): pod arrivals (full
  spec + gang membership), pod/node/PodGroup/ElasticQuota/TpuTopology
  adds/updates/deletes, node health transitions, PodGroup phase moves,
  and the authoritative bind commit (the ""→node transition);
- the **scheduler bind path**: ``record_bind_decision`` attaches decision
  attribution (profile, gang, scheduling e2e, attempt count) to each
  commit from ``Scheduler._finish_binding_traced``.

Every event is dual-stamped (``mono`` monotonic + ``wall`` epoch) and
spilled to crash-safe rotating JSONL segments under the journal
discipline of ``apiserver/persistence.Journal``: records are ENQUEUED
cheaply on the event thread (stored API objects are immutable after
publication, so encoding happens later), one named daemon writer thread
does all disk I/O, the queue has a hard budget (over it, events are
DROPPED and counted — capture sheds load, it never blocks the informer
boundary), and a torn tail line from a crash is tolerated on read while
a re-attached capture always resumes into a FRESH segment.  When the
segment count exceeds its budget the writer compacts exactly like the
WAL: the new segment opens with a fresh state snapshot and older
segments are deleted, so the directory stays bounded AND replayable from
its oldest retained byte.

Consumers: ``tpusched/sim/replay.py`` (deterministic replay +
differential placement/SLO reports), ``python -m tpusched.cmd.trace``
(capture/inspect/replay/diff), ``bench.py --replay`` (storm bench over a
recorded workload), ``/debug/fleetrace`` (live capture status).

Shadow isolation: live schedulers arm the process-global recorder via
``obs.ensure_fleetrace`` (environment-gated, ``TPUSCHED_FLEETRACE_DIR``);
shadow schedulers get a private DISARMED instance — a what-if trial's
simulated binds must never be recorded as fleet reality.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ..apiserver import server as srv
from ..apiserver.persistence import KIND_CLASSES, decode_object, encode_object
from ..util import klog
from ..util.metrics import (fleetrace_bytes_total, fleetrace_dropped_total,
                            fleetrace_events_total)

__all__ = [
    "SCHEMA_VERSION", "ENV_DIR", "FleetTraceRecorder", "FleetTrace",
    "load_trace", "read_records", "trace_summary", "workload_fingerprint",
    "WORKLOAD_EVENT_KINDS",
]

SCHEMA_VERSION = 1
ENV_DIR = "TPUSCHED_FLEETRACE_DIR"

SEGMENT_PREFIX = "fleet-"
SEGMENT_SUFFIX = ".jsonl"

DEFAULT_SEGMENT_BYTES = 8 * 1024 * 1024
DEFAULT_MAX_SEGMENTS = 8
DEFAULT_QUEUE_BUDGET = 8192

# Object kinds captured at the watch boundary and included in snapshots —
# everything a replayed scheduler consumes (the what-if shadow's kind set).
SNAPSHOT_KINDS = (srv.NODES, srv.PODS, srv.POD_GROUPS, srv.ELASTIC_QUOTAS,
                  srv.PRIORITY_CLASSES, srv.PDBS, srv.TPU_TOPOLOGIES)
_WATCH_KINDS = (srv.PODS, srv.NODES, srv.POD_GROUPS, srv.ELASTIC_QUOTAS,
                srv.TPU_TOPOLOGIES)

# Event kinds that ARE the workload (what a replay re-feeds). bind-commit /
# bind-decision are recorded REALITY — a replay makes its own decisions and
# diffs against them instead of re-applying them.
WORKLOAD_EVENT_KINDS = frozenset((
    "pod-arrival", "pod-update", "pod-delete",
    "node-add", "node-update", "node-health", "node-delete",
    "podgroup-add", "podgroup-update", "podgroup-phase", "podgroup-delete",
    "quota-add", "quota-update", "quota-delete",
    "topology-add", "topology-update", "topology-delete",
))

# sentinel payload: the writer thread expands it into snapshot records by
# calling the recorder's snapshot function — a 50k-pod fleet snapshot must
# not transit (and blow) the bounded event queue, and must not run its
# O(objects) encode on the watch thread
_SNAPSHOT_SENTINEL = "__snapshot__"


def _stamps() -> Tuple[float, float]:
    """(mono, wall) — every fleet-trace record is DUAL-stamped by design:
    mono orders and paces replay within one capture session, wall anchors
    the trace to fleet history across processes."""
    # tpulint: disable=monotonic-clock — the wall stamp is the point here:
    # post-hoc reconstruction needs epoch time next to the monotonic one
    return time.monotonic(), time.time()


def _segment_name(index: int) -> str:
    return f"{SEGMENT_PREFIX}{index:08d}{SEGMENT_SUFFIX}"


def _segment_paths(directory: str) -> List[Tuple[int, str]]:
    """(index, path) for every segment file in the directory, ascending."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(SEGMENT_PREFIX)
                and name.endswith(SEGMENT_SUFFIX)):
            continue
        stem = name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]
        try:
            out.append((int(stem), os.path.join(directory, name)))
        except ValueError:
            continue
    out.sort()
    return out


class _SegmentWriter:
    """Rotating JSONL segment writer on one named daemon thread.

    The journal discipline (apiserver/persistence.Journal): ``append`` only
    enqueues under a condition variable — stored API objects are never
    mutated after publication, so JSON encoding safely happens later on the
    writer thread, which does ALL disk I/O.  A full queue drops (and
    counts) instead of blocking: capture is observability, and the watch
    fan-out it rides must never stall on a slow disk."""

    def __init__(self, directory: str, segment_bytes: int, max_segments: int,
                 queue_budget: int,
                 snapshot_fn: Optional[Callable[[], Dict[str, list]]] = None):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self._segment_bytes = max(64 * 1024, segment_bytes)
        self._max_segments = max(2, max_segments)
        self._budget = max(16, queue_budget)
        self._snapshot_fn = snapshot_fn

        existing = _segment_paths(directory)
        # crash/restart contract: NEVER append to an existing segment — a
        # torn tail stays isolated in its own file and capture resumes
        # into a fresh one
        self._next_index = existing[-1][0] + 1 if existing else 1
        self._on_disk = [i for i, _ in existing]
        # a big snapshot can itself span several segments (each rotation
        # re-enters _ensure_segment): re-compacting before max_segments
        # FRESH segments accumulated would write snapshots back to back,
        # and re-compacting while a snapshot is being WRITTEN would recurse
        self._last_compact = 0
        self._in_compact = False

        self._cv = threading.Condition()
        self._queue: List[tuple] = []
        self._enqueued = 0
        self._processed = 0
        self._closed = False

        self._file = None
        self._file_bytes = 0
        self._stats_lock = threading.Lock()
        self._bytes_written = 0
        self._events_written = 0
        self._dropped = 0
        self._write_errors = 0

        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpusched-fleetrace")
        self._thread.start()

    # -- producer side (watch/bind threads) -----------------------------------

    def append(self, kind: str, mono: float, wall: float,
               payload: Optional[dict], obj: Any,
               objkind: Optional[str]) -> Optional[bool]:
        """Enqueue one record.  True = accepted, False = dropped at the
        queue budget, None = writer already closed (not an event loss:
        capture was detached)."""
        with self._cv:
            if self._closed:
                return None
            if len(self._queue) >= self._budget:
                self._dropped += 1
                fleetrace_dropped_total.inc()
                return False
            self._queue.append((kind, mono, wall, payload, obj, objkind))
            self._enqueued += 1
            self._cv.notify()
        return True

    # -- writer thread ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(0.5)
                batch, self._queue = self._queue, []
                closing = self._closed
            if batch:
                try:
                    self._write_batch(batch)
                except Exception as e:  # capture is best-effort: a disk
                    # failure must never take the control plane's watch
                    # fan-out down with it
                    klog.error_s(e, "fleetrace segment write failed",
                                 directory=self.dir)
                    with self._stats_lock:
                        self._write_errors += 1
                with self._cv:
                    self._processed += len(batch)
                    self._cv.notify_all()
            if closing and not batch:
                self._close_file()
                return

    def _write_batch(self, batch) -> None:
        for kind, mono, wall, payload, obj, objkind in batch:
            if kind == _SNAPSHOT_SENTINEL:
                self._ensure_segment()
                self._write_snapshot(mono, wall)
                continue
            rec: Dict[str, Any] = {"kind": kind, "mono": mono, "wall": wall}
            if payload:
                rec.update(payload)
            if obj is not None:
                rec["objkind"] = objkind
                rec["object"] = encode_object(obj)
            self._ensure_segment()
            self._write_record(rec)
        # per-batch flush (persistence.Journal discipline): a process that
        # exits without detach() loses at most the in-flight batch, not the
        # whole Python-buffered tail of the open segment
        if self._file is not None:
            self._file.flush()

    def _write_record(self, rec: dict) -> None:
        if self._file is None:
            # a rotation mid-batch (or mid-snapshot) closed the segment:
            # open the next one. _ensure_segment sets _file BEFORE writing
            # its header record, so the reentry terminates.
            self._ensure_segment()
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        self._file.write(line)
        n = len(line.encode("utf-8"))
        self._file_bytes += n
        with self._stats_lock:
            self._bytes_written += n
            self._events_written += 1
        fleetrace_bytes_total.inc(n)
        if self._file_bytes >= self._segment_bytes:
            self._close_file()          # next record opens a fresh segment

    def _ensure_segment(self) -> None:
        if self._file is not None:
            return
        index = self._next_index
        self._next_index += 1
        path = os.path.join(self.dir, _segment_name(index))
        self._file = open(path, "w", encoding="utf-8")
        self._file_bytes = 0
        self._on_disk.append(index)
        now_m, now_w = _stamps()
        self._write_record({"kind": "segment-header",
                            "schema_version": SCHEMA_VERSION,
                            "segment": index, "mono": now_m, "wall": now_w})
        if len(self._on_disk) > self._max_segments \
                and index - self._last_compact > self._max_segments \
                and not self._in_compact:
            self._last_compact = index
            self._in_compact = True
            try:
                self._compact(index, now_m, now_w)
            finally:
                self._in_compact = False

    def _compact(self, keep_from: int, mono: float, wall: float) -> None:
        """WAL-style compaction: the freshly opened segment gets a full
        state snapshot, then every OLDER segment is deleted — the directory
        stays bounded and remains replayable from its oldest retained
        byte (readers start at the last snapshot)."""
        if self._snapshot_fn is not None:
            self._write_snapshot(mono, wall)
        kept = []
        for idx in self._on_disk:
            if idx >= keep_from:
                kept.append(idx)
                continue
            try:
                os.unlink(os.path.join(self.dir, _segment_name(idx)))
            except OSError as e:
                klog.error_s(e, "fleetrace segment delete failed",
                             segment=idx)
                kept.append(idx)
        self._on_disk = kept

    def _write_snapshot(self, mono: float, wall: float) -> None:
        if self._snapshot_fn is None:
            return
        dump = self._snapshot_fn()
        counts = {k: len(v) for k, v in dump.items() if v}
        self._write_record({"kind": "snapshot-start", "mono": mono,
                            "wall": wall, "counts": counts})
        for objkind, objs in dump.items():
            for obj in objs:
                self._write_record({"kind": "snapshot-object",
                                    "mono": mono, "wall": wall,
                                    "objkind": objkind,
                                    "object": encode_object(obj)})
        self._write_record({"kind": "snapshot-end", "mono": mono,
                            "wall": wall})

    def _close_file(self) -> None:
        if self._file is None:
            return
        try:
            self._file.flush()
            self._file.close()
        except OSError as e:
            klog.error_s(e, "fleetrace segment close failed")
        self._file = None
        self._file_bytes = 0

    # -- control ---------------------------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every record enqueued so far hit the writer (written
        or counted as a write error)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            target = self._enqueued
            while self._processed < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=10)

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            out = {"bytes_written": self._bytes_written,
                   "events_written": self._events_written,
                   "dropped": self._dropped,
                   "write_errors": self._write_errors}
        with self._cv:
            out["queue_depth"] = len(self._queue)
        out["segments"] = len(self._on_disk)
        return out


class FleetTraceRecorder:
    """The capture front-end: watch-boundary hooks + the scheduler's
    bind-decision feed, multiplexed into one ``_SegmentWriter``.

    Disarmed (the default, and always for shadow schedulers) every feed
    method is a nearly-free no-op; ``attach`` arms it against ONE
    APIServer.  All feed paths are thread-safe: the writer reference is
    swapped atomically and a closed writer refuses appends."""

    def __init__(self):
        self._lock = threading.Lock()
        self._writer: Optional[_SegmentWriter] = None
        self._api: Optional[srv.APIServer] = None
        self._handlers: List[Tuple[str, Callable]] = []
        self._status_sink: Optional[Callable] = None
        self._events_by_kind: Dict[str, int] = {}
        self._started_wall = 0.0
        self._started_mono = 0.0
        # recorded-span bookkeeping (ISSUE 15 small fix): first/last event
        # stamps of the CURRENT capture, so /debug/fleetrace states how
        # much fleet time the trace spans — the number a virtual-time
        # replay's compression ratio is quoted against
        self._first_event_mono: Optional[float] = None
        self._last_event_mono: Optional[float] = None

    # -- lifecycle -------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._writer is not None

    def attach(self, api: srv.APIServer, directory: str, *,
               segment_bytes: int = DEFAULT_SEGMENT_BYTES,
               max_segments: int = DEFAULT_MAX_SEGMENTS,
               queue_budget: int = DEFAULT_QUEUE_BUDGET) -> None:
        """Arm capture against ``api``, spilling into ``directory``.  The
        first records are a ``capture-start`` marker and a full state
        snapshot, so the trace is replayable without any external state.
        Idempotent against the same directory; re-attaching elsewhere
        detaches first."""
        old = None
        with self._lock:
            if self._writer is not None:
                if self._api is api and self._writer.dir == directory:
                    return
                # the old writer's drain (flush + thread join, seconds)
                # happens AFTER the lock is released: _enqueue's
                # bookkeeping takes this lock on the watch fan-out path,
                # and APIServer dispatch is synchronous — holding it
                # across the drain would stall every store write behind
                # a re-arm
                old = self._swap_out_locked()

            def snapshot() -> Dict[str, list]:
                dump, _rv = api.dump_for_snapshot(SNAPSHOT_KINDS)
                return dump

            writer = _SegmentWriter(directory, segment_bytes, max_segments,
                                    queue_budget, snapshot_fn=snapshot)
            self._writer = writer
            self._api = api
            mono, wall = _stamps()
            self._started_wall = wall
            self._started_mono = mono
            self._events_by_kind = {}
            self._first_event_mono = None
            self._last_event_mono = None
            # direct appends (not _enqueue): attach holds self._lock and
            # _enqueue's bookkeeping takes it too
            writer.append("capture-start", mono, wall,
                          {"schema_version": SCHEMA_VERSION}, None, None)
            # watch hooks BEFORE the snapshot sentinel: the writer thread
            # dumps the store when it dequeues the sentinel, so an object
            # written after the dump but before a later registration would
            # be in neither the snapshot nor the stream. Registered first,
            # a pre-sentinel event is merely discarded by load_trace (its
            # effect is already in the store the dump will read) and a
            # snapshot-ahead duplicate is upserted by replay's apply_event.
            handlers = []
            for kind in _WATCH_KINDS:
                def handler(ev, kind=kind):
                    self._on_watch_event(kind, ev)
                api.add_watch(kind, handler, replay=False)
                handlers.append((kind, handler))
            self._handlers = handlers
            # in-band gang runtime status reports (ISSUE 10): captured as
            # goodput-report events so a recorded trace carries the
            # workload×generation throughput matrix (goodput.
            # matrix_from_trace rebuilds it offline) for replay/policy
            # evaluation — same sink fan-out the goodput aggregator rides
            self._status_sink = self._on_status_reports
            api.add_status_sink(self._status_sink)
            writer.append(_SNAPSHOT_SENTINEL, mono, wall, None, None, None)
        self._drain_writer(old)
        klog.info_s("fleet trace capture armed", directory=directory)

    def detach(self, flush_timeout: float = 5.0) -> None:
        with self._lock:
            writer = self._swap_out_locked()
        self._drain_writer(writer, flush_timeout)

    def _swap_out_locked(self):
        """Under self._lock: deregister the watch hooks and surrender the
        writer.  The blocking drain is the CALLER's job, outside the lock."""
        writer, self._writer = self._writer, None
        if writer is None:
            return None
        for kind, handler in self._handlers:
            # tpulint: disable=naked-api-calls — the capture IS a watch-
            # boundary component (informer-sibling): it registers raw
            # watch handlers and must deregister the same way
            self._api.remove_watch(kind, handler)
        self._handlers = []
        if self._status_sink is not None:
            # tpulint: disable=naked-api-calls — sink deregistration is
            # the same watch-boundary contract as remove_watch above
            self._api.remove_status_sink(self._status_sink)
            self._status_sink = None
        self._api = None
        return writer

    @staticmethod
    def _drain_writer(writer, flush_timeout: float = 5.0) -> None:
        """Stamp capture-stop, drain the queue, stop the writer thread.
        Blocks up to flush_timeout + the thread join: never call under
        self._lock (watch fan-out takes it per event)."""
        if writer is None:
            return
        writer.append("capture-stop", *_stamps(), None, None, None)
        writer.flush(flush_timeout)
        writer.close()

    def flush(self, timeout: float = 10.0) -> bool:
        writer = self._writer
        return writer.flush(timeout) if writer is not None else True

    # -- feed points -----------------------------------------------------------

    def _enqueue(self, kind: str, obj=None, objkind: Optional[str] = None,
                 payload: Optional[dict] = None) -> None:
        writer = self._writer
        if writer is None:
            return
        mono, wall = _stamps()
        ok = writer.append(kind, mono, wall, payload, obj, objkind)
        if ok:
            fleetrace_events_total.with_labels(kind).inc()
            with self._lock:
                self._events_by_kind[kind] = \
                    self._events_by_kind.get(kind, 0) + 1
                if self._first_event_mono is None:
                    self._first_event_mono = mono
                self._last_event_mono = mono
        # ok is False → dropped (counted by the writer); None → detached
        # mid-flight (not a loss)

    def record_bind_decision(self, pod_key: str, node: str, *,
                             scheduler: str = "", gang: Optional[str] = None,
                             e2e_s: float = 0.0, attempts: int = 0) -> None:
        """Decision attribution for a bind commit, fed from the scheduler's
        bind path right after ``cache.finish_binding``.  The watch-derived
        ``bind-commit`` event is the authoritative placement record (it
        fires inside the API commit); this adds WHO decided and at what
        cost.  No-op while disarmed — shadow schedulers hold a private
        disarmed recorder, so trial binds can never masquerade as fleet
        reality."""
        if self._writer is None:
            return
        self._enqueue("bind-decision",
                      payload={"pod": pod_key, "node": node,
                               "scheduler": scheduler, "gang": gang or "",
                               "e2e_s": round(e2e_s, 6),
                               "attempts": attempts})

    def _on_status_reports(self, reports) -> None:
        """In-band ``GangMemberStatus`` fan-out (``APIServer.
        report_status``): one ``goodput-report`` event per report.  The
        report's own wall timestamp rides in the payload (the emitter's
        window end); the record's ``wall``/``mono`` stamps are capture
        time, like every other event."""
        for r in reports:
            try:
                self._enqueue("goodput-report", payload={
                    "pod": r.pod_key, "gang": r.gang, "step": r.step,
                    "step_time_s": round(r.step_time_s, 6),
                    "throughput": round(r.throughput, 3), "unit": r.unit,
                    "ttft_s": round(r.ttft_s, 6),
                    "stall_s": round(r.stall_s, 6),
                    "reported_wall": r.timestamp})
            except Exception as e:  # a malformed report must not kill the
                # heartbeat path that carried it
                klog.error_s(e, "fleetrace goodput-report capture failed")

    # -- watch boundary --------------------------------------------------------

    def _on_watch_event(self, kind: str, ev: srv.WatchEvent) -> None:
        try:
            if kind == srv.PODS:
                self._on_pod(ev)
            elif kind == srv.NODES:
                self._on_node(ev)
            elif kind == srv.POD_GROUPS:
                self._on_podgroup(ev)
            elif kind == srv.ELASTIC_QUOTAS:
                self._on_simple(ev, srv.ELASTIC_QUOTAS, "quota")
            elif kind == srv.TPU_TOPOLOGIES:
                self._on_simple(ev, srv.TPU_TOPOLOGIES, "topology")
        except Exception as e:  # the capture must never break watch fan-out
            klog.error_s(e, "fleetrace watch hook panicked", kind=kind)

    def _on_pod(self, ev: srv.WatchEvent) -> None:
        pod = ev.object
        if ev.type == srv.ADDED:
            self._enqueue("pod-arrival", obj=pod, objkind=srv.PODS,
                          payload={"pod": pod.meta.key,
                                   "gang": _gang_of(pod)})
        elif ev.type == srv.MODIFIED:
            old = ev.old_object
            was = bool(old is not None and old.spec.node_name)
            now = bool(pod.spec.node_name)
            if now and not was:
                # the authoritative commit: fires inside the API server's
                # bind patch, so commit order here IS store-mutation order
                self._enqueue("bind-commit",
                              payload={"pod": pod.meta.key,
                                       "node": pod.spec.node_name,
                                       "gang": _gang_of(pod)})
            elif not now:
                self._enqueue("pod-update", obj=pod, objkind=srv.PODS,
                              payload={"pod": pod.meta.key})
            # bound-pod status churn (phase flips, conditions) carries no
            # scheduling signal — deliberately not recorded
        elif ev.type == srv.DELETED:
            self._enqueue("pod-delete",
                          payload={"pod": pod.meta.key,
                                   "node": pod.spec.node_name,
                                   "gang": _gang_of(pod)})

    def _on_node(self, ev: srv.WatchEvent) -> None:
        from ..api.core import heartbeat_only_update, node_health_error
        node = ev.object
        if ev.type == srv.ADDED:
            self._enqueue("node-add", obj=node, objkind=srv.NODES,
                          payload={"node": node.meta.name})
        elif ev.type == srv.MODIFIED:
            old = ev.old_object
            # heartbeat-only stamps would be the dominant event kind while
            # carrying zero scheduling information — same predicate the
            # scheduler's informer path drops them by
            if old is not None and heartbeat_only_update(old, node):
                return
            err_old = node_health_error(old) if old is not None else None
            err_new = node_health_error(node)
            if err_old != err_new:
                self._enqueue("node-health", obj=node, objkind=srv.NODES,
                              payload={"node": node.meta.name,
                                       "health_from": err_old or "",
                                       "health_to": err_new or ""})
            else:
                self._enqueue("node-update", obj=node, objkind=srv.NODES,
                              payload={"node": node.meta.name})
        elif ev.type == srv.DELETED:
            self._enqueue("node-delete", payload={"node": node.meta.name})

    def _on_podgroup(self, ev: srv.WatchEvent) -> None:
        pg = ev.object
        if ev.type == srv.ADDED:
            self._enqueue("podgroup-add", obj=pg, objkind=srv.POD_GROUPS,
                          payload={"gang": pg.meta.key})
        elif ev.type == srv.MODIFIED:
            old = ev.old_object
            from_phase = old.status.phase if old is not None else ""
            if pg.status.phase != from_phase:
                self._enqueue("podgroup-phase", obj=pg,
                              objkind=srv.POD_GROUPS,
                              payload={"gang": pg.meta.key,
                                       "from": from_phase,
                                       "to": pg.status.phase})
            else:
                self._enqueue("podgroup-update", obj=pg,
                              objkind=srv.POD_GROUPS,
                              payload={"gang": pg.meta.key})
        elif ev.type == srv.DELETED:
            self._enqueue("podgroup-delete", payload={"gang": pg.meta.key})

    def _on_simple(self, ev: srv.WatchEvent, kind: str, stem: str) -> None:
        obj = ev.object
        if ev.type == srv.ADDED:
            self._enqueue(f"{stem}-add", obj=obj, objkind=kind,
                          payload={"name": obj.meta.key})
        elif ev.type == srv.MODIFIED:
            self._enqueue(f"{stem}-update", obj=obj, objkind=kind,
                          payload={"name": obj.meta.key})
        elif ev.type == srv.DELETED:
            self._enqueue(f"{stem}-delete", payload={"name": obj.meta.key})

    # -- introspection ---------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The /debug/fleetrace payload."""
        writer = self._writer
        out: Dict[str, Any] = {"enabled": writer is not None,
                               "schema_version": SCHEMA_VERSION}
        if writer is None:
            return out
        out["directory"] = writer.dir
        out.update(writer.stats())
        with self._lock:
            out["events_by_kind"] = dict(self._events_by_kind)
            out["started_wall"] = self._started_wall
            out["attached_for_s"] = round(
                time.monotonic() - self._started_mono, 3)
            # the virtual↔wall mapping stamp: how much FLEET time the
            # capture spans so far — the denominator an operator (or a
            # replay report) quotes trace-compression ratios against,
            # and the at-a-glance tell between a live capture and a
            # compressed evaluation of one
            out["recorded_span_s"] = round(
                self._last_event_mono - self._first_event_mono, 3) \
                if self._first_event_mono is not None else 0.0
        return out


# -- reading ------------------------------------------------------------------

def read_records(directory: str) -> Iterator[dict]:
    """Every decodable record in the directory, segment order.  A torn tail
    (crash mid-append) ends THAT segment's stream — everything before the
    tear is yielded, and later segments (a resumed capture) still read."""
    records, _torn = read_all(directory)
    return iter(records)


def read_all(directory: str) -> Tuple[List[dict], int]:
    """(records, torn_segment_count) — the tear-aware bulk reader behind
    ``read_records``/``load_trace``."""
    records: List[dict] = []
    torn = 0
    for index, path in _segment_paths(directory):
        try:
            f = open(path, encoding="utf-8")
        except OSError as e:
            klog.error_s(e, "fleetrace segment unreadable", segment=index)
            torn += 1
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    klog.warning_s("fleetrace segment tail truncated; "
                                   "stopping at the tear", segment=index)
                    torn += 1
                    break
    return records, torn


@dataclasses.dataclass
class FleetTrace:
    """A loaded trace: initial state (from the LAST snapshot — compaction
    may have rolled earlier ones away) + every event after it, in capture
    order."""
    directory: str
    schema_version: int
    objects: Dict[str, List[Any]]       # objkind → decoded API objects
    events: List[dict]
    segments: int
    torn: bool                          # any segment ended at a tear

    def recorded_binds(self) -> List[Tuple[str, str]]:
        """(pod key, node) per bind-commit, in store-mutation order — the
        recorded reality replays diff against."""
        return [(e["pod"], e["node"]) for e in self.events
                if e.get("kind") == "bind-commit"]

    def bind_decisions(self) -> Dict[str, dict]:
        return {e["pod"]: e for e in self.events
                if e.get("kind") == "bind-decision"}

    def arrivals(self) -> List[dict]:
        return [e for e in self.events if e.get("kind") == "pod-arrival"]

    def events_by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events:
            k = e.get("kind", "?")
            out[k] = out.get(k, 0) + 1
        return out

    def window_s(self) -> float:
        monos = [e["mono"] for e in self.events if "mono" in e]
        return max(monos) - min(monos) if len(monos) > 1 else 0.0

    def summary(self) -> Dict[str, Any]:
        by_kind = self.events_by_kind()
        return {
            "directory": self.directory,
            "schema_version": self.schema_version,
            "segments": self.segments,
            "torn": self.torn,
            "snapshot_objects": {k: len(v) for k, v in self.objects.items()
                                 if v},
            "events": sum(by_kind.values()),
            "events_by_kind": by_kind,
            "arrivals": by_kind.get("pod-arrival", 0),
            "binds": by_kind.get("bind-commit", 0),
            "gangs": len({e.get("gang") for e in self.events
                          if e.get("kind") == "pod-arrival"
                          and e.get("gang")}),
            "window_s": round(self.window_s(), 3),
            "workload_fingerprint": workload_fingerprint(self.events),
        }


def load_trace(directory: str) -> FleetTrace:
    """Parse a trace directory into initial state + post-snapshot events.
    Restart from the LAST complete-or-running snapshot: a re-attached
    capture (or a compaction) always writes a fresh one, so the newest
    snapshot governs everything after it."""
    segments = len(_segment_paths(directory))
    if segments == 0:
        raise FileNotFoundError(f"no fleet-trace segments under {directory}")
    records, torn_count = read_all(directory)
    schema = SCHEMA_VERSION
    for rec in records:
        if rec.get("kind") == "segment-header":
            schema = rec.get("schema_version", SCHEMA_VERSION)
    last_snap = -1
    for i, rec in enumerate(records):
        if rec.get("kind") == "snapshot-start":
            last_snap = i
    objects: Dict[str, List[Any]] = {k: [] for k in SNAPSHOT_KINDS}
    events: List[dict] = []
    in_snapshot = False
    for i, rec in enumerate(records):
        kind = rec.get("kind")
        if i == last_snap:
            in_snapshot = True
            continue
        if in_snapshot:
            if kind == "snapshot-object":
                cls = KIND_CLASSES.get(rec.get("objkind"))
                if cls is not None:
                    objects.setdefault(rec["objkind"], []).append(
                        decode_object(cls, rec["object"]))
            elif kind == "snapshot-end":
                in_snapshot = False
            # a torn snapshot (no snapshot-end) swallows the segment tail,
            # which read_records already ended at the tear
            continue
        if i < last_snap:
            continue                    # pre-snapshot history: compacted away
        if kind in ("segment-header", "capture-start", "capture-stop",
                    "snapshot-start", "snapshot-object", "snapshot-end"):
            continue
        events.append(rec)
    return FleetTrace(directory=directory, schema_version=schema,
                      objects=objects, events=events, segments=segments,
                      torn=bool(torn_count))


def trace_summary(directory: str) -> Dict[str, Any]:
    return load_trace(directory).summary()


# record fields that are capture framing / stamps, not workload identity
_FP_SKIP_FIELDS = frozenset(("kind", "mono", "wall", "objkind", "object"))


def workload_fingerprint(events: List[dict]) -> str:
    """Stable hash of the WORKLOAD an event stream carries (arrivals with
    their specs, deletes, node/quota/gang changes — not the recorded
    placements): two traces with the same fingerprint pose the scheduler
    the same problem, so their replay reports are comparable."""
    h = hashlib.sha256()
    for e in events:
        kind = e.get("kind")
        if kind not in WORKLOAD_EVENT_KINDS:
            continue
        h.update(kind.encode())
        # every payload field is workload (health_from/health_to on a
        # node-health transition is as much the problem statement as the
        # node's name), EXCEPT stamps/framing and a pod event's node —
        # that is where the RECORDED scheduler put the pod (bind-commit
        # reality leaking through pod-delete), and hashing it would give
        # the same workload captured under two scoring policies different
        # fingerprints
        for field in sorted(e):
            if field in _FP_SKIP_FIELDS:
                continue
            if field == "node" and kind.startswith("pod-"):
                continue
            v = e.get(field)
            if v:
                h.update(field.encode() + b"=" + str(v).encode())
        obj = e.get("object")
        if obj is not None:
            h.update(json.dumps(obj.get("spec", obj), sort_keys=True,
                                separators=(",", ":")).encode())
            # node size is workload even though it lives in status;
            # heartbeat times and conditions are capture noise and stay out
            status = obj.get("status") or {}
            sizing = {k: status[k] for k in ("capacity", "allocatable")
                      if status.get(k)}
            if sizing:
                h.update(json.dumps(sizing, sort_keys=True,
                                    separators=(",", ":")).encode())
        h.update(b"|")
    return h.hexdigest()[:16]


def _gang_of(pod) -> str:
    from ..api.scheduling import pod_group_full_name
    return pod_group_full_name(pod) or ""
