"""tpusched.obs — operator-grade observability on top of trace/metrics.

Three pillars (ISSUE 5):

- ``diagnosis.DiagnosisEngine`` — the why-pending engine: bounded rolling
  per-pod / per-gang rejection aggregation + a cluster top-blockers table,
  served at ``/debug/explain`` and by ``python -m tpusched.cmd.explain``;
- ``capacity.CapacityTelemetry`` — per-pool free/placeable chip gauges
  (torus fragmentation index), ElasticQuota utilization, queue depth;
- ``slo.SLOTracker`` — pod-e2e and PodGroup-to-Bound latency objectives
  with burn-rate accounting (``tpusched_slo_*``).

Plus the performance pillar (ISSUE 7):

- ``profiler.HotPathProfiler`` — the always-on sampling profiler:
  collapsed stacks at ``/debug/profile``, extension-point/plugin/lock
  attribution in ``/debug/flightrecorder``'s health section;
- ``throughput.ThroughputTelemetry`` — binds/sec, cycles/sec, arrival
  rate and bind-pool backlog, per scheduler profile.

Like the flight recorder, the engine and the SLO tracker have process-
global defaults: the scheduler feeds whichever instances it was built
with (default: the globals), and the /debug HTTP surface resolves the
globals at request time — so a bench/test that installs fresh instances
is picked up without rebuilding servers, and plugin code (Coscheduling's
gang-bound clock) can feed the SLO layer without a handle threaded
through the framework.  The profiler follows the same pattern
(``default_profiler``/``install_profiler``); live schedulers start it via
``ensure_profiler`` and SHADOW schedulers never touch it — the
shadow-isolation lint rule pins the whole accessor set.
"""
from __future__ import annotations

from .diagnosis import DiagnosisEngine
from .slo import (GANG_BOUND, POD_E2E, SLOTracker, DEFAULT_GANG_BOUND_S,
                  DEFAULT_POD_E2E_S)
from .capacity import (CapacityTelemetry, largest_placeable_chips,
                       largest_window_chips, pool_occupancy)
from .profiler import (HotPathProfiler, profiling_enabled,
                       set_profiling_enabled)
from .throughput import ThroughputTelemetry
from .fleetrace import FleetTraceRecorder
from .goodput import (GoodputAggregator, GoodputMatrix, load_matrix,
                      matrix_from_trace, workload_fingerprint_of)
from .timeline import HealthTimeline, register_scheduler_families
from .sentinel import AnomalySentinel, Detector, default_detectors
from .incident import (IncidentManager, config_fingerprint,
                       validate_bundle, wire_incident_plane)
from . import reasons  # noqa: F401  (re-export)

__all__ = [
    "DiagnosisEngine", "SLOTracker", "CapacityTelemetry",
    "HotPathProfiler", "ThroughputTelemetry", "FleetTraceRecorder",
    "profiling_enabled", "set_profiling_enabled",
    "largest_placeable_chips", "largest_window_chips", "pool_occupancy",
    "POD_E2E", "GANG_BOUND",
    "DEFAULT_POD_E2E_S", "DEFAULT_GANG_BOUND_S", "reasons",
    "default_engine", "install_engine", "default_slo", "install_slo",
    "default_profiler", "install_profiler", "ensure_profiler",
    "default_fleetrecorder", "install_fleetrecorder", "ensure_fleetrace",
    "observe_gang_bound",
    "GoodputAggregator", "GoodputMatrix", "load_matrix", "matrix_from_trace",
    "workload_fingerprint_of",
    "default_goodput", "install_goodput", "ensure_goodput",
    "HealthTimeline", "AnomalySentinel", "Detector", "default_detectors",
    "IncidentManager", "register_scheduler_families", "wire_incident_plane",
    "config_fingerprint", "validate_bundle",
    "default_timeline", "install_timeline",
    "default_sentinel", "install_sentinel",
    "default_incidents", "install_incidents", "ensure_incidents",
]

_engine = DiagnosisEngine()
_slo = SLOTracker()
_profiler = HotPathProfiler()
_fleet = FleetTraceRecorder()
_goodput = GoodputAggregator()
_timeline = HealthTimeline()
_sentinel = AnomalySentinel()
_incidents = IncidentManager()


def default_engine() -> DiagnosisEngine:
    return _engine


def install_engine(engine: DiagnosisEngine) -> DiagnosisEngine:
    """Swap the process-global diagnosis engine (bench/test isolation).
    Schedulers built earlier keep feeding the instance they captured; the
    /debug/explain route resolves the global at request time."""
    global _engine
    _engine = engine
    return engine


def default_slo() -> SLOTracker:
    return _slo


def install_slo(tracker: SLOTracker) -> SLOTracker:
    """Swap the process-global SLO tracker.  Objectives the NEW tracker
    does not carry (disabled via a 0 target) have their objective/burn
    gauge children removed — a retired objective must stop being exposed,
    not freeze at its last value."""
    global _slo
    from .slo import slo_burn_rate, slo_objective_seconds
    for name in set(_slo.objective_names()) - set(tracker.objective_names()):
        slo_objective_seconds.remove(name)
        slo_burn_rate.remove(name)
    _slo = tracker
    return tracker


def observe_gang_bound(seconds: float) -> None:
    """Feed the gang-bound objective from wherever the PodGroup-to-Bound
    clock is read (Coscheduling's post_bind quorum completion)."""
    _slo.observe(GANG_BOUND, seconds)


def default_profiler() -> HotPathProfiler:
    return _profiler


def install_profiler(profiler: HotPathProfiler) -> HotPathProfiler:
    """Swap the process-global profiler (bench/test isolation — prof-smoke
    runs each arm against a fresh instance).  The replaced sampler is
    stopped: two samplers would double every attribution share."""
    global _profiler
    if _profiler is not profiler:
        _profiler.stop()
    _profiler = profiler
    return profiler


def ensure_profiler() -> HotPathProfiler:
    """Start the process-global profiler if enabled and not yet running
    (idempotent — live schedulers call this at construction; shadows must
    not)."""
    _profiler.ensure_started()
    return _profiler


def default_fleetrecorder() -> FleetTraceRecorder:
    return _fleet


def install_fleetrecorder(rec: FleetTraceRecorder) -> FleetTraceRecorder:
    """Swap the process-global fleet trace recorder (bench/test isolation).
    The replaced recorder is detached: two armed recorders on one API
    server would double every captured event."""
    global _fleet
    if _fleet is not rec:
        _fleet.detach()
    _fleet = rec
    return rec


def default_goodput() -> GoodputAggregator:
    return _goodput


def install_goodput(agg: GoodputAggregator) -> GoodputAggregator:
    """Swap the process-global goodput aggregator (bench/test isolation).
    The replaced aggregator is detached from its API server's status
    fan-out: two attached aggregators would double-count every report,
    and the stale one's per-gang gauge children would fight the fresh
    one's over the shared metric families."""
    global _goodput
    if _goodput is not agg:
        _goodput.detach()
    _goodput = agg
    return agg


def ensure_goodput(api) -> GoodputAggregator:
    """Arm the process-global goodput aggregator against ``api``'s
    in-band status-report fan-out, idempotently — live schedulers call
    this at construction so heartbeat-piggybacked ``GangMemberStatus``
    reports flow the moment the first gang binds.  Shadow schedulers hold
    a private ``GoodputAggregator(publish=False)`` and must never reach
    this accessor (shadow-isolation lint rule): a what-if trial's
    synthetic members must not publish as fleet runtime telemetry."""
    _goodput.attach(api)
    return _goodput


def default_timeline() -> HealthTimeline:
    return _timeline


def install_timeline(timeline: HealthTimeline) -> HealthTimeline:
    """Swap the process-global health timeline (bench/test isolation).
    Schedulers wired earlier keep feeding the instance they registered
    families on; the /debug/timeline route resolves the global at
    request time."""
    global _timeline
    _timeline = timeline
    return timeline


def default_sentinel() -> AnomalySentinel:
    return _sentinel


def install_sentinel(sentinel: AnomalySentinel) -> AnomalySentinel:
    """Swap the process-global anomaly sentinel.  The replaced sentinel
    is detached from whatever timeline it listened on — two sentinels on
    one tick stream would double every firing (and every bundle)."""
    global _sentinel
    if _sentinel is not sentinel and _sentinel._attached_to is not None:
        _sentinel._attached_to.remove_listener(_sentinel.on_sample)
    _sentinel = sentinel
    return sentinel


def default_incidents() -> IncidentManager:
    return _incidents


def install_incidents(mgr: IncidentManager) -> IncidentManager:
    """Swap the process-global incident manager (bench/test isolation)."""
    global _incidents
    _incidents = mgr
    return mgr


def ensure_incidents() -> IncidentManager:
    """Arm the process-global incident manager from the environment
    (``TPUSCHED_INCIDENT_DIR``), idempotently — live schedulers call
    this at construction; shadows hold a private in-memory
    ``IncidentManager(publish=False)`` and must never reach this
    accessor (shadow-isolation lint rule)."""
    import os as _os
    from .incident import ENV_DIR
    directory = _os.environ.get(ENV_DIR, "")
    if directory and not _incidents.directory:
        try:
            _incidents.arm_directory(directory)
        except Exception as e:  # noqa: BLE001 — capture is
            # observability: an unwritable bundle dir must not keep the
            # scheduler down
            from ..util import klog
            klog.error_s(e, "incident bundle dir arm failed",
                         directory=directory)
    return _incidents


def ensure_fleetrace(api) -> FleetTraceRecorder:
    """Arm the process-global fleet trace capture from the environment
    (``TPUSCHED_FLEETRACE_DIR``), idempotently — live schedulers call this
    at construction; shadows get a private disarmed recorder instead and
    must never reach this accessor (shadow-isolation lint rule)."""
    import os as _os
    from .fleetrace import ENV_DIR
    directory = _os.environ.get(ENV_DIR, "")
    if directory and not _fleet.enabled:
        try:
            _fleet.attach(api, directory)
        except Exception as e:  # noqa: BLE001 — capture is observability:
            # an unwritable trace dir must not keep the scheduler down
            from ..util import klog
            klog.error_s(e, "fleet trace capture arm failed",
                         directory=directory)
    return _fleet
