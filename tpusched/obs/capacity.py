"""Capacity & fragmentation telemetry.

Fleet-level gauges computed at scrape time (a registry collector — no
background thread) from the scheduler's own informers and cache snapshot:

- per-pool chip capacity/free gauges;
- a torus FRAGMENTATION index per pool: the largest slice (in chips) that
  is placeable RIGHT NOW as one contiguous window, against the pool's free
  chips.  ``free=512, largest_placeable=64`` is the number that explains a
  "no feasible slice placement" rejection — plenty of chips, no window.
  Placement semantics mirror the scheduler exactly (same HostGrid, same
  rotation/wraparound rules via topology.torus, same health gating);
- ElasticQuota utilization per queue (namespace), in whole TPU chips —
  the fleet currency quota min/max are written in;
- queue-depth gauges already exist (``tpusched_pending_pods{queue=}``);
  this adds ``tpusched_pending_gangs`` (distinct gangs with pending
  members) so "how many JOBS are waiting" needs no label math.

Cost discipline: the fragmentation search is memoized on (cache mutation
cursor, TpuTopology resourceVersions) and rate-limited; an idle fleet
re-serves the cached answer for free.  The search itself prunes shapes
larger than the free host count and is capped, so a scrape can never walk
an unbounded placement space.
"""
from __future__ import annotations

import itertools
import math
import time
import weakref
from typing import Dict, FrozenSet, Optional, Tuple

from ..api.core import node_health_error
from ..api.resources import TPU
from ..api.scheduling import POD_GROUP_LABEL
from ..plugins.tpuslice.chip_node import pod_tpu_limits
from ..topology.torus import HOST_EXTENT, HostGrid, iter_placements
from ..util.metrics import REGISTRY

pool_capacity_chips = REGISTRY.gauge_vec(
    "tpusched_pool_capacity_chips", ("pool",),
    "Allocatable TPU chips per topology pool.")
pool_free_chips = REGISTRY.gauge_vec(
    "tpusched_pool_free_chips", ("pool",),
    "TPU chips not claimed by any pod, per pool.")
pool_largest_placeable_chips = REGISTRY.gauge_vec(
    "tpusched_pool_largest_placeable_chips", ("pool",),
    "Largest slice (chips) placeable right now as one contiguous torus "
    "window on healthy free hosts.")
pool_fragmentation_ratio = REGISTRY.gauge_vec(
    "tpusched_pool_fragmentation_ratio", ("pool",),
    "1 - largest_placeable/free chips per pool (0 = every free chip is "
    "reachable by one window; 1 = free capacity unusable for slices).")
quota_min_chips = REGISTRY.gauge_vec(
    "tpusched_quota_min_chips", ("namespace",),
    "ElasticQuota guaranteed min, in whole TPU chips, per queue.")
quota_used_chips = REGISTRY.gauge_vec(
    "tpusched_quota_used_chips", ("namespace",),
    "Whole TPU chips in use (bound + assumed pods), per quota queue.")
quota_utilization = REGISTRY.gauge_vec(
    "tpusched_quota_utilization", ("namespace",),
    "used/min chip ratio per quota queue (>1 = borrowing beyond min).")
# scheduler-labeled like the pending_pods gauges beside it: one process
# can host several profiles/replicas, and an unlabeled gauge would flap
# between their queues (and freeze at a stopped scheduler's last value)
pending_gangs = REGISTRY.gauge_vec(
    "tpusched_pending_gangs", ("scheduler",),
    "Distinct gangs (PodGroups) with pending members, per scheduler queue.")

_MAX_SHAPES_TRIED = 128


def _node_chip_usage(info) -> Tuple[int, bool]:
    """(whole chips requested, any TPU usage at all) for one node.
    Computed directly, NOT via NodeInfo.derived(): this runs on the
    /metrics scrape thread against snapshot NodeInfos the scheduling loop
    shares across incremental snapshots, and a foreign-thread write into
    ``derived_cache`` could race the loop's ``clone()`` dict copy.
    Reading ``info.pods`` is safe (snapshot infos are read-only by
    contract); only the memo write would be the hazard."""
    chips = 0
    any_usage = False
    for p in info.pods:
        c, chips_set, _, mem_set = pod_tpu_limits(p)
        chips += c
        if chips_set or mem_set:
            any_usage = True
    return chips, any_usage


def _any_placement_fits(grid: HostGrid, chip_shape: Tuple[int, ...],
                        free: FrozenSet) -> bool:
    """Streaming existence check: does ANY placement of ``chip_shape``
    land entirely on ``free`` hosts?  Iterates the scheduler's own lazy
    placement generator (torus.iter_placements — ONE implementation of
    the rotation/wraparound rules) and returns on the first fit instead
    of materializing the full placement list."""
    return any(p <= free for p in iter_placements(grid, chip_shape))


def pool_occupancy(grid: HostGrid, snapshot) -> Tuple[FrozenSet, int, int]:
    """(window-eligible free host coords, free chips, capacity chips).

    A host is window-eligible when it is healthy and carries zero TPU
    usage — the same definition TopologyMatch's occupancy sweep uses for
    ``free``, so these gauges and the scheduler can never disagree about
    what is placeable."""
    free_coords: set = set()
    free_chips = 0
    capacity = 0
    for node, coord in grid.coord_of.items():
        info = snapshot.get(node)
        if info is None:
            continue
        alloc = info.allocatable.get(TPU, 0)
        capacity += alloc
        used, any_usage = _node_chip_usage(info)
        free_chips += max(0, alloc - used)
        # window-eligible requires chips to actually exist on the host: a
        # healthy empty node whose device plugin has not advertised chips
        # yet (alloc 0, post-repair churn) must not count as placeable —
        # it would float largest_placeable above free_chips
        if alloc > 0 and not any_usage \
                and node_health_error(info.node) is None:
            free_coords.add(coord)
    return frozenset(free_coords), free_chips, capacity


def largest_window_chips(grid: HostGrid, free: FrozenSet) -> int:
    """Largest slice (chips) placeable as one contiguous window on the
    given free hosts.  Bounded (_MAX_SHAPES_TRIED) but can never
    under-report below one host block: a single free healthy host always
    places the extent shape."""
    if not free:
        return 0
    extent = HOST_EXTENT[grid.acc.name]
    # candidate chip shapes: host-block multiples of the accelerator's
    # host extent, deduplicated up to rotation (the fit check tries
    # rotations itself), largest chip count first.  Floor: one host block
    # (the extent shape) trivially fits any free host, so the bounded
    # search can only ever refine the answer UP from there.
    best = math.prod(extent)
    axes = [[e * h for h in range(1, hd + 1)]
            for e, hd in zip(extent, grid.dims)]
    shapes: Dict[Tuple[int, ...], Tuple[int, ...]] = {}
    for s in itertools.product(*axes):
        shapes.setdefault(tuple(sorted(s)), s)
    ordered = sorted(shapes.values(), key=lambda s: -math.prod(s))
    tried = 0
    for shape in ordered:
        chips = math.prod(shape)
        if chips <= best:
            break                         # descending order: done
        hosts_needed = math.prod(s // e for s, e in zip(shape, extent))
        if hosts_needed > len(free):
            continue
        tried += 1
        if tried > _MAX_SHAPES_TRIED:
            break                         # bounded: report the floor/best
        if _any_placement_fits(grid, shape, free):
            best = chips
            break                         # nothing bigger left to try
    return best


def largest_placeable_chips(grid: HostGrid, snapshot) -> Tuple[int, int, int]:
    """(largest placeable chips, free chips, capacity chips) for a pool —
    the one-call convenience over pool_occupancy + largest_window_chips."""
    free, free_chips, capacity = pool_occupancy(grid, snapshot)
    return largest_window_chips(grid, free), free_chips, capacity


class CapacityTelemetry:
    """Scrape-time collector bound to one scheduler (weakly: a stopped
    scheduler's collector removes its own series and unregisters)."""

    def __init__(self, scheduler, min_refresh_s: float = 1.0,
                 frag_refresh_s: float = 15.0, clock=time.monotonic):
        self._ref = weakref.ref(scheduler)
        # kept by value: close() may run after the scheduler is garbage
        self._scheduler_name = scheduler.profile.scheduler_name
        self._min_refresh_s = min_refresh_s
        # the fragmentation search is the one non-O(nodes) computation
        # here: on an ACTIVE cluster the mutation cursor moves between
        # every pair of scrapes, so cursor-memoization alone would re-run
        # it per scrape — this interval additionally rate-limits it (the
        # gauge may lag reality by up to frag_refresh_s; capacity trend
        # data, not a scheduling input)
        self._frag_refresh_s = frag_refresh_s
        self._clock = clock
        self._last_refresh = -1e9
        # fragmentation memo: {pool: [cursor, topo_rv, computed_at, result]}
        self._frag_memo: Dict[str, list] = {}
        self._grid_cache: Dict[Tuple[str, int], Optional[HostGrid]] = {}
        self._pool_labels: set = set()
        self._ns_labels: set = set()
        # tpulint: disable=shadow-isolation — CapacityTelemetry is
        # only constructed for telemetry=True schedulers (the guard
        # is the `if telemetry` at the single construction site in
        # sched/scheduler.py); shadows never instantiate it
        REGISTRY.register_collector(self.collect)

    def close(self) -> None:
        REGISTRY.unregister_collector(self.collect)
        pending_gangs.remove(self._scheduler_name)
        for pool in self._pool_labels:
            for vec in (pool_capacity_chips, pool_free_chips,
                        pool_largest_placeable_chips,
                        pool_fragmentation_ratio):
                vec.remove(pool)
        for ns in self._ns_labels:
            for vec in (quota_min_chips, quota_used_chips,
                        quota_utilization):
                vec.remove(ns)
        self._pool_labels.clear()
        self._ns_labels.clear()

    # -- the collector --------------------------------------------------------

    def collect(self) -> None:
        sched = self._ref()
        if sched is None:
            self.close()
            return
        now = self._clock()
        if now - self._last_refresh < self._min_refresh_s:
            return
        self._last_refresh = now
        # READ-ONLY snapshot access: this runs on the /metrics scrape
        # thread.  shared_snapshot() serves the cache's PERSISTENT
        # composed view — always fresh at O(Δ) cost (per-pool sub-maps
        # rebuilt only for mutated pools), and unlike cache.snapshot() it
        # never advances the loop's snapshot bookkeeping, so it cannot
        # launder a concurrent foreign mutation past the equivalence
        # cache's arming guard.  This is what retired the scheduler's
        # housekeeping-tick full snapshot refresh (ISSUE 14).
        snapshot = sched.cache.shared_snapshot()
        self._refresh_queue(sched)
        cursor = sched.cache.mutation_cursor()
        self._refresh_pools(sched, snapshot, cursor)
        self._refresh_quotas(sched, snapshot)

    def _grid(self, topo) -> Optional[HostGrid]:
        key = (topo.key, topo.meta.resource_version)
        if key not in self._grid_cache:
            if len(self._grid_cache) > 16:
                self._grid_cache.clear()
            self._grid_cache[key] = HostGrid.from_spec(topo.spec)
        return self._grid_cache[key]

    def _refresh_pools(self, sched, snapshot, cursor: int) -> None:
        seen = set()
        index = getattr(sched, "window_index", None)
        for topo in sched.informer_factory.tputopologies().items():
            grid = self._grid(topo)
            if grid is None:
                continue
            pool = topo.spec.pool
            seen.add(pool)
            # Window-index fast path (ISSUE 13): planes + totals are
            # maintained incrementally, so the per-scrape O(pool hosts ×
            # pods) occupancy walk disappears, and the largest-window
            # search is memoized on the pool's OWN plane version instead
            # of the fleet-global cursor (an idle pool answers for free
            # while the rest of the fleet churns).  The collector's
            # rate limit stays on top: a hot pool re-runs the bounded
            # ladder at most once per frag_refresh_s.
            view = index.capacity_view(topo) if index is not None else None
            if view is not None:
                free_set, free, capacity, version = view
                memo_key = ("idx", version)
            else:
                # free/capacity: cheap O(pool hosts) walk, always fresh
                free_set, free, capacity = pool_occupancy(grid, snapshot)
                memo_key = cursor
            # largest-window search: memoized on its arm's change witness
            # (plane version / fleet cursor) + topo rv, AND rate-limited —
            # an active cluster moves the witness between every pair of
            # scrapes, so the memo alone would re-run the search per scrape
            now = self._clock()
            memo = self._frag_memo.get(pool)
            rv = topo.meta.resource_version
            fresh = memo is not None and (
                (memo[0] == memo_key and memo[1] == rv)
                or now - memo[2] < self._frag_refresh_s)
            if fresh:
                largest = memo[3]
            else:
                lp = index.largest_placeable(topo) \
                    if view is not None else None
                largest = lp[0] if lp is not None \
                    else largest_window_chips(grid, free_set)
                self._frag_memo[pool] = [memo_key, rv, now, largest]
            pool_capacity_chips.with_labels(pool).set(capacity)
            pool_free_chips.with_labels(pool).set(free)
            pool_largest_placeable_chips.with_labels(pool).set(largest)
            # clamped: a one-cycle-stale snapshot or sub-host free chips
            # can put largest marginally above free; the ratio is defined
            # on [0, 1]
            pool_fragmentation_ratio.with_labels(pool).set(
                max(0.0, round(1.0 - (largest / free), 4)) if free else 0.0)
        for stale in self._pool_labels - seen:
            self._frag_memo.pop(stale, None)
            for vec in (pool_capacity_chips, pool_free_chips,
                        pool_largest_placeable_chips,
                        pool_fragmentation_ratio):
                vec.remove(stale)
        self._pool_labels = seen

    def _refresh_quotas(self, sched, snapshot) -> None:
        quotas = list(sched.informer_factory.elasticquotas().items())
        if not quotas and not self._ns_labels:
            return
        # quota ledger fast path (ISSUE 14): the cache maintains per-quota
        # used resources incrementally, so the per-scrape O(pods) fleet
        # walk collapses to O(quotas).  Fallback to the walk only when the
        # ledger tracks none of the informer's quotas yet (registration
        # races the first scrape).
        from ..api.resources import TPU as _TPU
        ledger = sched.cache.quota_used_snapshot() \
            if hasattr(sched.cache, "quota_used_snapshot") else {}
        used: Dict[str, int] = {ns: int(res.get(_TPU, 0))
                                for ns, res in ledger.items()}
        if not ledger:
            for info in snapshot.list():
                for p in info.pods:
                    chips, chips_set, _, _ = pod_tpu_limits(p)
                    if chips_set:
                        used[p.meta.namespace] = \
                            used.get(p.meta.namespace, 0) + chips
        seen = set()
        for eq in quotas:
            ns = eq.meta.namespace
            seen.add(ns)
            mn = eq.spec.min.get(TPU, 0)
            u = used.get(ns, 0)
            quota_min_chips.with_labels(ns).set(mn)
            quota_used_chips.with_labels(ns).set(u)
            quota_utilization.with_labels(ns).set(
                round(u / mn, 4) if mn else 0.0)
        for stale in self._ns_labels - seen:
            for vec in (quota_min_chips, quota_used_chips,
                        quota_utilization):
                vec.remove(stale)
        self._ns_labels = seen

    @staticmethod
    def _refresh_queue(sched) -> None:
        gangs = set()
        for p in sched.queue.pending_pods():
            name = p.meta.labels.get(POD_GROUP_LABEL)
            if name:
                gangs.add(f"{p.meta.namespace}/{name}")
        pending_gangs.with_labels(
            sched.profile.scheduler_name).set(len(gangs))
