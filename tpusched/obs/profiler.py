"""Hot-path sampling profiler: always-on, low-overhead, attribution-aware.

Every bench so far clocks one gang at a time; the sharded-core work
(ROADMAP item 1) needs to know where a *sustained* cycle's wall time goes —
"Filter spends 41% of the cycle under the cache lock" — without attaching
an external profiler to a production scheduler.  This module is that
substrate:

- a named daemon sampler thread periodically snapshots the stacks of all
  scheduler-owned threads (``sys._current_frames()`` — no stop-the-world,
  no tracing hooks on the hot path);
- each sample is attributed through the cross-thread context the hot path
  already publishes into ``util/tracectx`` (active extension point, plugin
  body, contended lock — the latter fed by ``GuardedLock`` telemetry mode),
  so a stack is not just frames but "PreFilter / TpuSlice / blocked on
  sched.Cache";
- samples aggregate into a BOUNDED hot-path table (entry + byte budgets —
  an always-on control plane must hold its memory ceiling through any
  workload; overflow stacks are counted, never stored);
- the aggregate serves collapsed-stack (flamegraph-collapsed, one
  ``frame;frame;frame count`` line per distinct stack) output at
  ``/debug/profile`` — ``?seconds=N`` collects a fresh bounded window so an
  operator can profile "now", no argument returns the rolling aggregate —
  and a top-N attribution table into ``/debug/flightrecorder``'s health
  section.

The sampler accounts for its own cost (``self_seconds`` in stats): the
prof-smoke gate's direct-attribution fallback divides that by the run's
wall time when the A/B cannot resolve its 3% budget on a noisy box.

Overhead design: the HOT PATH pays only the tracectx attribution stores
(one thread-local getattr + a list store per extension point / cold plugin
call — sites that already pay two perf_counter reads for the duration
metrics); everything else runs on the sampler thread at ``interval_s``
resolution.  At the default 100 Hz with a dozen scheduler threads a sweep
is ~100 µs of work — well under the 3% budget ``make prof-smoke`` pins.

Known sampling bias (inherent to a pure-Python sampler): the sampler can
only preempt a CPU-bound pure-Python burst through the forced GIL handoff,
which fires after ``sys.getswitchinterval()`` (5 ms default) — a busy
burst SHORTER than that is sampled only at its voluntary GIL releases, so
sub-switch-interval bursts are attributed to the wait states around them.
Durations at that scale belong to the duration histograms
(``tpusched_framework_extension_point_duration_seconds`` and friends);
the profiler's regime is the aggregate shape of where whole cycles go.
"""
from __future__ import annotations

import os
import re
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..util import klog, tracectx
from ..util.metrics import profiler_samples_total

__all__ = ["HotPathProfiler", "profiling_enabled", "set_profiling_enabled",
           "DEFAULT_INTERVAL_S"]

DEFAULT_INTERVAL_S = 0.01          # 100 Hz: resolves ms-scale cycle phases
DEFAULT_MAX_STACKS = 512
DEFAULT_MAX_BYTES = 1 << 20        # ~1 MiB of collapsed-stack keys
DEFAULT_MAX_FRAMES = 48            # innermost frames kept per stack
_MAX_ATTR_ROWS = 256
_MAX_CAPTURES = 4                  # concurrent ?seconds=N windows
_THREAD_PREFIX = "tpusched-"
_NUM_SUFFIX = re.compile(r"-\d+$")

_enabled = os.environ.get("TPUSCHED_PROFILE", "1") not in ("0", "false",
                                                           "off")


def profiling_enabled() -> bool:
    return _enabled


def set_profiling_enabled(v: bool) -> bool:
    """Kill switch (and the profiler-off arm of the prof-smoke A/B):
    ``ensure_started`` becomes a no-op and a running sampler parks at the
    next tick.  Returns the previous value (restore in finally)."""
    global _enabled
    prev, _enabled = _enabled, bool(v)
    return prev


class _Aggregate:
    """One bounded collapsed-stack table + attribution table.  Not
    self-locking: the owning profiler serializes access (sampler feeds and
    scrapers read under the profiler's lock)."""

    __slots__ = ("max_stacks", "max_bytes", "stacks", "attrs", "bytes",
                 "samples", "dropped", "dropped_attrs", "started_at")

    def __init__(self, max_stacks: int, max_bytes: int):
        self.max_stacks = max_stacks
        self.max_bytes = max_bytes
        # (thread_label, (point, plugin, lock), frames) → sample count
        self.stacks: Dict[Tuple[str, Tuple[str, str, str],
                                Tuple[str, ...]], int] = {}
        # (thread_label, point, plugin, lock) → sample count
        self.attrs: Dict[Tuple[str, str, str, str], int] = {}
        self.bytes = 0
        self.samples = 0
        self.dropped = 0
        self.dropped_attrs = 0
        self.started_at = time.monotonic()

    def feed(self, label: str, attr: Tuple[str, str, str],
             frames: Tuple[str, ...]) -> None:
        self.samples += 1
        akey = (label,) + attr
        if akey in self.attrs or len(self.attrs) < _MAX_ATTR_ROWS:
            self.attrs[akey] = self.attrs.get(akey, 0) + 1
        else:
            self.dropped_attrs += 1    # same contract as stacks: overflow
        skey = (label, attr, frames)   # is counted, never silent
        n = self.stacks.get(skey)
        if n is not None:
            self.stacks[skey] = n + 1
            return
        est = len(label) + sum(len(f) + 1 for f in frames) + 24
        if len(self.stacks) >= self.max_stacks \
                or self.bytes + est > self.max_bytes:
            self.dropped += 1          # counted, never stored: the budget
            return                     # holds through any stack diversity
        self.stacks[skey] = 1
        self.bytes += est

    # -- views ---------------------------------------------------------------

    def collapsed(self) -> str:
        """Flamegraph-collapsed text: ``thread;point:X;plugin:Y;lock:Z;
        frame;...;frame N`` per distinct stack, hottest first.  Attribution
        segments are emitted only when present, as synthetic frames — a
        flamegraph then groups the scheduler's time by extension point and
        plugin before any Python frame."""
        lines = []
        for (label, attr, frames), n in sorted(
                self.stacks.items(), key=lambda kv: -kv[1]):
            point, plugin, lock = attr
            segs = [label]
            if point:
                segs.append(f"point:{point}")
            if plugin:
                segs.append(f"plugin:{plugin}")
            if lock:
                segs.append(f"lock:{lock}")
            segs.extend(frames)
            lines.append(f"{';'.join(segs)} {n}")
        return "\n".join(lines) + ("\n" if lines else "")

    def top_attribution(self, n: int = 10) -> List[Dict[str, Any]]:
        total = self.samples or 1
        rows = sorted(self.attrs.items(), key=lambda kv: -kv[1])[:n]
        return [{"thread": k[0], "extension_point": k[1], "plugin": k[2],
                 "lock": k[3], "samples": v,
                 "share": round(v / total, 4)}
                for k, v in rows]

    def stats(self) -> Dict[str, Any]:
        return {"samples": self.samples, "stacks": len(self.stacks),
                "approx_bytes": self.bytes, "dropped_stacks": self.dropped,
                "dropped_attr_rows": self.dropped_attrs,
                "max_stacks": self.max_stacks, "max_bytes": self.max_bytes,
                "window_s": round(time.monotonic() - self.started_at, 3)}


class HotPathProfiler:
    """The always-on sampler.  One instance per process is the intended
    shape (``obs.default_profiler()``); shadow schedulers get none — a
    what-if trial must never publish live hot-path samples
    (tpulint's shadow-isolation rule pins the accessor set)."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 max_frames: int = DEFAULT_MAX_FRAMES,
                 thread_prefix: str = _THREAD_PREFIX):
        self.interval_s = max(0.001, interval_s)
        self.max_frames = max_frames
        self.thread_prefix = thread_prefix
        # raw Lock on purpose: the profiler must never feed itself (a
        # GuardedLock in telemetry mode would observe its own contention
        # from inside the sampler loop)
        self._mu = threading.Lock()
        self._agg = _Aggregate(max_stacks, max_bytes)
        self._captures: List[_Aggregate] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sweeps = 0
        self._sweep_errors = 0
        self._self_s = 0.0             # sampler's own cost (direct
        self._prune_countdown = 0      # attribution for prof-smoke)

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive() \
            and not self._stop.is_set()

    def ensure_started(self) -> bool:
        """Idempotent start (the scheduler calls this once per live
        construction).  Respects the module kill switch."""
        if not _enabled:
            return False
        with self._mu:
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="tpusched-profiler-sampler",
                daemon=True)
            self._thread.start()
            return True

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)

    # -- sampling ------------------------------------------------------------

    def _run(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            if not _enabled:
                continue               # parked by the kill switch
            t0 = time.perf_counter()
            try:
                self._sweep(me)
            except Exception as e:  # noqa: BLE001 — an always-on sampler
                # must survive one bad sweep (exotic frame, racing capture
                # state): losing the thread would silently end profiling
                # for the life of the process
                self._sweep_errors += 1
                if self._sweep_errors <= 3:
                    klog.error_s(e, "profiler sweep failed")
            self._self_s += time.perf_counter() - t0

    def _sweep(self, self_ident: int) -> None:
        frames = sys._current_frames()
        try:
            names = {t.ident: t.name for t in threading.enumerate()}
            fed = 0
            with self._mu:
                captures = list(self._captures)
                for ident, frame in frames.items():
                    if ident == self_ident:
                        continue
                    name = names.get(ident, "")
                    if not name.startswith(self.thread_prefix):
                        continue
                    label = _NUM_SUFFIX.sub("", name)
                    attr = tracectx.attribution(ident)
                    stack = self._extract(frame)
                    self._agg.feed(label, attr, stack)
                    for cap in captures:
                        cap.feed(label, attr, stack)
                    fed += 1
            if fed:
                profiler_samples_total.inc(fed)
            self._sweeps += 1
            # housekeeping every ~256 sweeps: drop attribution slots of
            # dead threads (bind-pool workers are long-lived, but tests
            # construct and stop schedulers constantly)
            self._prune_countdown -= 1
            if self._prune_countdown <= 0:
                self._prune_countdown = 256
                tracectx.prune_attributions(set(frames))
        finally:
            del frames                 # break frame → local ref cycles

    def _extract(self, frame) -> Tuple[str, ...]:
        out: List[str] = []
        f = frame
        while f is not None and len(out) < self.max_frames:
            code = f.f_code
            out.append(f"{f.f_globals.get('__name__', '?')}."
                       f"{code.co_name}")
            f = f.f_back
        out.reverse()                  # root first, leaf last (collapsed
        return tuple(out)              # stack convention)

    # -- views ---------------------------------------------------------------

    def collapsed(self) -> str:
        with self._mu:
            return self._agg.collapsed()

    def top_attribution(self, n: int = 10) -> List[Dict[str, Any]]:
        with self._mu:
            return self._agg.top_attribution(n)

    def capture(self, seconds: float) -> Optional[_Aggregate]:
        """Collect a FRESH bounded window for ``seconds`` (the
        ``/debug/profile?seconds=N`` path) and return its aggregate.
        Blocking — intended for request-handler threads.  Concurrent
        captures are capped to bound sampler work; past the cap this
        returns None and the caller must say so (a silent fall-back to
        the since-start rolling aggregate LOOKS like a fresh window but
        may be dominated by hours of idle frames)."""
        cap = _Aggregate(self._agg.max_stacks, self._agg.max_bytes)
        with self._mu:
            if len(self._captures) >= _MAX_CAPTURES:
                return None
            self._captures.append(cap)
        try:
            self._stop.wait(max(0.05, seconds))
        finally:
            with self._mu:
                if cap in self._captures:
                    self._captures.remove(cap)
        return cap

    def _snapshot_agg(self) -> _Aggregate:
        snap = _Aggregate(self._agg.max_stacks, self._agg.max_bytes)
        with self._mu:
            snap.stacks = dict(self._agg.stacks)
            snap.attrs = dict(self._agg.attrs)
            snap.bytes = self._agg.bytes
            snap.samples = self._agg.samples
            snap.dropped = self._agg.dropped
            snap.started_at = self._agg.started_at
        return snap

    def reset(self) -> None:
        """Drop the rolling aggregate (bench isolation between arms)."""
        with self._mu:
            self._agg = _Aggregate(self._agg.max_stacks,
                                   self._agg.max_bytes)

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            st = self._agg.stats()
        st.update({"running": self.running, "interval_s": self.interval_s,
                   "sweeps": self._sweeps,
                   "sweep_errors": self._sweep_errors,
                   "self_seconds": round(self._self_s, 6),
                   "active_captures": len(self._captures)})
        return st

    def health(self, n: int = 10) -> Dict[str, Any]:
        """The /debug/flightrecorder health-section payload: top-N
        attribution rows + the sampler's own vitals."""
        with self._mu:
            top = self._agg.top_attribution(n)
            samples = self._agg.samples
        return {"running": self.running, "interval_s": self.interval_s,
                "samples": samples, "self_seconds": round(self._self_s, 6),
                "top": top}
