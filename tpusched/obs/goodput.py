"""Gang runtime goodput telemetry: the observability plane for gangs AFTER
they bind.

Every layer so far (flight recorder, why-pending, profiler, fleet trace)
watches the *scheduler*; the moment a gang binds the system goes blind —
yet realized JCT on a TPU fleet is dominated by what happens next:
stragglers, slice-generation throughput spread, checkpoint/restore stalls
(the TPU-fleet retrospective's core lesson, PAPERS.md #2).  This module
aggregates the in-band ``GangMemberStatus`` reports gang members piggyback
on the node heartbeat (``api/core.GangMemberStatus``,
``APIServer.report_status``) into:

- **per-gang runtime health** — rolling goodput (unit/s and per-chip),
  per-member step skew, and straggler detection (member p99 step-time vs
  the gang's median-of-medians, with hysteresis so a single slow step
  cannot flap the verdict).  Detections are pinned as ``gang_straggler``
  flight-recorder anomalies and served through ``/debug/goodput`` and the
  ``/debug/explain`` gang view, so "my gang is slow" is as diagnosable as
  "my pod is pending";
- **the workload × slice-type throughput matrix** — EWMA goodput-per-chip
  keyed by workload fingerprint × pool generation (the measured matrix
  ROADMAP item 3's Gavel-style Score plugin and ``sim/whatif.py``
  consume), exportable as a schema-versioned JSON artifact with a ``peek``
  API and reconstructible offline from a recorded fleet trace
  (``matrix_from_trace`` — fleetrace captures every report as a
  ``goodput-report`` event).

Bounded like every other obs surface: entry + byte budgets on gangs,
members and matrix cells; over budget the aggregator SHEDS (counted,
``tpusched_goodput_reports_shed_total``) instead of growing; resolved
(deleted) members are evicted immediately.  Ingest is O(members of the
reporting gang) under one lock; the happy path for a solo report is a few
dict operations.

Shadow isolation: live schedulers attach the process-global aggregator to
their API server via ``obs.ensure_goodput``; shadow schedulers construct a
private ``GoodputAggregator(publish=False)`` — inert metrics, no anomaly
pinning — so a what-if trial can never publish hypothetical runtime
telemetry (the shadow-isolation lint rule pins the accessor set).
"""
from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..api.resources import TPU
from ..util import klog
from ..util.locking import GuardedLock, guarded_by
from ..util.metrics import (gang_goodput_per_chip, gang_goodput_units,
                            gang_step_skew, gang_straggler_events,
                            gang_stragglers, goodput_reports_shed,
                            goodput_reports_total, workload_goodput_per_chip)

__all__ = [
    "MATRIX_SCHEMA_VERSION", "LABEL_WORKLOAD", "GoodputAggregator",
    "GoodputMatrix", "load_matrix", "matrix_from_trace",
    "workload_fingerprint_of",
]

MATRIX_SCHEMA_VERSION = 1

# Pod/PodGroup label naming the workload class for the throughput matrix.
# Absent the label, the fingerprint is derived from the gang's shape — two
# jobs asking the same slice geometry are the same scheduling problem, and
# a coarse fingerprint that groups them beats an unbounded per-job key.
LABEL_WORKLOAD = "tpu.dev/workload"

DEFAULT_MAX_GANGS = 256
DEFAULT_MAX_MEMBERS = 4096
DEFAULT_MAX_BYTES = 1 << 20          # ~1 MiB of runtime-health state
DEFAULT_MAX_MATRIX_CELLS = 512
MEMBER_WINDOW = 32                   # rolling step-time samples per member
EWMA_ALPHA = 0.25                    # matrix cell smoothing

# Straggler hysteresis: ENTER when the member's rolling p99 step time
# exceeds enter_ratio × the gang's median-of-member-medians; CLEAR only
# when it falls back under clear_ratio × the median (or the member is torn
# down). The gap between the two ratios is what keeps one noisy step from
# flapping the verdict.
STRAGGLER_ENTER_RATIO = 1.5
STRAGGLER_CLEAR_RATIO = 1.2
STRAGGLER_MIN_REPORTS = 4            # per member, before it can be judged
STRAGGLER_MIN_MEMBERS = 2            # a gang of one has no skew

_MEMBER_BASE_BYTES = 160 + 8 * MEMBER_WINDOW
_GANG_BASE_BYTES = 128


def _median(xs: List[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _p99(sorted_xs: List[float]) -> float:
    if not sorted_xs:
        return 0.0
    return sorted_xs[min(len(sorted_xs) - 1,
                         max(0, round(0.99 * (len(sorted_xs) - 1))))]


def workload_fingerprint_of(pod, pg=None) -> str:
    """The matrix's workload key for a pod: the ``tpu.dev/workload`` label
    when the job names itself (pod label wins, then its PodGroup's), else
    a shape-derived class — gangs asking the same slice geometry pose the
    same throughput question, and a bounded fingerprint space is what
    keeps the matrix a matrix instead of a per-job log."""
    name = pod.meta.labels.get(LABEL_WORKLOAD, "")
    if not name and pg is not None:
        name = pg.meta.labels.get(LABEL_WORKLOAD, "")
    if name:
        return name
    shape = ""
    if pg is not None and getattr(pg.spec, "tpu_slice_shape", ""):
        shape = pg.spec.tpu_slice_shape
    return f"{shape or 'any'}/{pod_chips(pod)}chip"


# -- the persistent matrix artifact -------------------------------------------

@dataclasses.dataclass
class _MatrixCell:
    goodput_per_chip: float = 0.0    # EWMA, unit/s/chip
    unit: str = "tokens"
    reports: int = 0
    updated_wall: float = 0.0

    def fold(self, per_chip: float, unit: str, wall: float,
             alpha: float = EWMA_ALPHA) -> None:
        if self.reports == 0:
            self.goodput_per_chip = per_chip
        else:
            self.goodput_per_chip = (alpha * per_chip
                                     + (1 - alpha) * self.goodput_per_chip)
        self.unit = unit
        self.reports += 1
        self.updated_wall = wall


@dataclasses.dataclass
class GoodputMatrix:
    """The workload × pool-generation throughput matrix: measured EWMA
    goodput-per-chip per (workload fingerprint, generation) cell.  This is
    the persistent artifact ROADMAP item 3's goodput-aware Score plugin
    and ``sim/whatif.py`` consume — schema-versioned JSON so a snapshot
    survives process restarts and rides in recorded fleet traces."""
    schema_version: int = MATRIX_SCHEMA_VERSION
    generated_wall: float = 0.0
    # workload → generation → cell
    cells: Dict[str, Dict[str, _MatrixCell]] = dataclasses.field(
        default_factory=dict)

    def peek(self, workload: str, generation: str) -> Optional[float]:
        """Measured goodput-per-chip for a cell, or None when unmeasured —
        callers (the what-if planner, a Score plugin) must treat None as
        "no data", never as zero throughput."""
        cell = self.cells.get(workload, {}).get(generation)
        return cell.goodput_per_chip if cell is not None else None

    def cell(self, workload: str, generation: str) -> Optional[_MatrixCell]:
        return self.cells.get(workload, {}).get(generation)

    def fold(self, workload: str, generation: str, per_chip: float,
             unit: str, wall: float) -> None:
        row = self.cells.setdefault(workload, {})
        cell = row.get(generation)
        if cell is None:
            cell = row[generation] = _MatrixCell()
        cell.fold(per_chip, unit, wall)

    def size(self) -> int:
        return sum(len(row) for row in self.cells.values())

    def best_generation(self, workload: str) -> Optional[str]:
        """The generation with the highest measured goodput-per-chip for a
        workload (the Gavel placement question), or None when unmeasured."""
        row = self.cells.get(workload)
        if not row:
            return None
        return max(row, key=lambda g: row[g].goodput_per_chip)

    def to_dict(self) -> dict:
        return {
            "schema_version": self.schema_version,
            "generated_wall": self.generated_wall,
            "cells": {w: {g: dataclasses.asdict(c) for g, c in row.items()}
                      for w, row in self.cells.items()},
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "GoodputMatrix":
        version = doc.get("schema_version")
        if version != MATRIX_SCHEMA_VERSION:
            raise ValueError(
                f"goodput matrix schema_version {version!r} unsupported "
                f"(want {MATRIX_SCHEMA_VERSION})")
        cells_in = doc.get("cells")
        if not isinstance(cells_in, dict):
            raise ValueError("goodput matrix: 'cells' missing or not an "
                             "object")
        cells: Dict[str, Dict[str, _MatrixCell]] = {}
        for w, row in cells_in.items():
            if not isinstance(row, dict):
                raise ValueError(f"goodput matrix: workload {w!r} row is "
                                 "not an object")
            out_row: Dict[str, _MatrixCell] = {}
            for g, c in row.items():
                try:
                    out_row[g] = _MatrixCell(
                        goodput_per_chip=float(c["goodput_per_chip"]),
                        unit=str(c.get("unit", "tokens")),
                        reports=int(c.get("reports", 0)),
                        updated_wall=float(c.get("updated_wall", 0.0)))
                except (KeyError, TypeError, ValueError) as e:
                    raise ValueError(
                        f"goodput matrix: malformed cell {w!r}×{g!r}: {e}")
            cells[w] = out_row
        return cls(schema_version=version,
                   generated_wall=float(doc.get("generated_wall", 0.0)),
                   cells=cells)

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
            f.write("\n")

    def summary(self) -> dict:
        return {
            "schema_version": self.schema_version,
            "workloads": len(self.cells),
            "cells": self.size(),
            "rows": {w: {g: {"goodput_per_chip":
                             round(c.goodput_per_chip, 4),
                             "unit": c.unit, "reports": c.reports}
                         for g, c in row.items()}
                     for w, row in self.cells.items()},
        }


def load_matrix(path: str) -> GoodputMatrix:
    with open(path, encoding="utf-8") as f:
        return GoodputMatrix.from_dict(json.load(f))


# -- aggregator state ----------------------------------------------------------

class _Member:
    __slots__ = ("node", "workload", "generation", "chips", "unit",
                 "steps", "last_step", "throughput", "ttft_s", "stall_s",
                 "reports", "median", "p99", "straggler", "last_wall")

    def __init__(self, node: str, workload: str, generation: str,
                 chips: int):
        self.node = node
        self.workload = workload
        self.generation = generation
        self.chips = chips
        self.unit = "tokens"
        self.steps: "collections.deque[float]" = collections.deque(
            maxlen=MEMBER_WINDOW)
        self.last_step = 0
        self.throughput = 0.0
        self.ttft_s = 0.0
        self.stall_s = 0.0
        self.reports = 0
        self.median = 0.0        # rolling median step time (cached)
        self.p99 = 0.0           # rolling p99 step time (cached)
        self.straggler = False
        self.last_wall = 0.0

    def fold(self, r) -> None:
        if r.step_time_s > 0:
            self.steps.append(r.step_time_s)
            xs = sorted(self.steps)
            self.median = _median(xs)
            self.p99 = _p99(xs)
        self.last_step = max(self.last_step, r.step)
        self.throughput = r.throughput
        self.unit = r.unit or self.unit
        if r.ttft_s > 0:
            self.ttft_s = r.ttft_s
        self.stall_s += max(0.0, r.stall_s)
        self.reports += 1
        self.last_wall = r.timestamp

    def to_dict(self, pod_key: str) -> dict:
        return {
            "pod": pod_key, "node": self.node,
            "generation": self.generation, "chips": self.chips,
            "step": self.last_step,
            "step_time_p50_s": round(self.median, 4),
            "step_time_p99_s": round(self.p99, 4),
            "throughput": round(self.throughput, 3),
            "unit": self.unit,
            "ttft_s": round(self.ttft_s, 4),
            "stall_s": round(self.stall_s, 3),
            "reports": self.reports,
            "straggler": self.straggler,
        }


class _Gang:
    __slots__ = ("members", "workload", "units", "stragglers", "skew",
                 "last_wall", "bytes")

    def __init__(self, workload: str):
        self.members: Dict[str, _Member] = {}
        self.workload = workload
        self.units: set = set()          # metric children to remove on drop
        self.stragglers = 0
        self.skew = 1.0
        self.last_wall = 0.0
        self.bytes = _GANG_BASE_BYTES


@guarded_by("_lock", "_gangs", "_solo", "_pod_to_gang", "_members",
            "_bytes", "_matrix", "_accepted", "_shed", "_straggler_edges",
            "_evictions", "_reporters")
class GoodputAggregator:
    """The runtime-telemetry back end: member registration from the
    scheduler's bind path, report ingest from the apiserver's status
    fan-out, straggler diagnosis + matrix folding on the way through."""

    def __init__(self, max_gangs: int = DEFAULT_MAX_GANGS,
                 max_members: int = DEFAULT_MAX_MEMBERS,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 max_matrix_cells: int = DEFAULT_MAX_MATRIX_CELLS,
                 enter_ratio: float = STRAGGLER_ENTER_RATIO,
                 clear_ratio: float = STRAGGLER_CLEAR_RATIO,
                 min_reports: int = STRAGGLER_MIN_REPORTS,
                 publish: bool = True, clock=time.time):
        """``publish=False`` builds the SHADOW shell: observations
        accumulate for ``dump()`` but no process-global metric family is
        touched and no anomaly is pinned — a what-if trial's synthetic
        members must never read as fleet runtime telemetry."""
        self.max_gangs = max_gangs
        self.max_members = max_members
        self.max_bytes = max_bytes
        self.max_matrix_cells = max_matrix_cells
        self.enter_ratio = enter_ratio
        self.clear_ratio = clear_ratio
        self.min_reports = min_reports
        self._publish = publish
        self._clock = clock
        self._lock = GuardedLock("obs.GoodputAggregator", reentrant=False)
        # gang full-name → _Gang, LRU order (most-recent report last)
        self._gangs: "collections.OrderedDict[str, _Gang]" = \
            collections.OrderedDict()
        self._solo = _Gang("")           # gangless members, never evicted
        self._pod_to_gang: Dict[str, str] = {}
        self._members = 0
        self._bytes = 0
        self._matrix = GoodputMatrix()
        self._accepted = 0
        self._shed = 0
        self._evictions = 0
        self._reporters = 0      # distinct members ever heard from
        self._straggler_edges = 0
        self._api = None

    # -- lifecycle (apiserver attachment) -------------------------------------

    def attach(self, api) -> None:
        """Arm ingest against ``api``'s status-report fan-out. Idempotent;
        re-attaching elsewhere detaches first."""
        if self._api is api:
            return
        self.detach()
        api.add_status_sink(self.ingest)
        self._api = api

    def detach(self) -> None:
        if self._api is not None:
            # tpulint: disable=naked-api-calls — the aggregator IS a
            # status-fan-out component (informer-sibling): it registers a
            # raw report sink and must deregister the same way
            self._api.remove_status_sink(self.ingest)
            self._api = None

    @property
    def attached(self) -> bool:
        return self._api is not None

    # -- registration (scheduler bind path) -----------------------------------

    def register_member(self, pod_key: str, gang: Optional[str], node: str,
                        workload: str = "", generation: str = "",
                        chips: int = 0) -> None:
        """Bind→running registration, fed from the scheduler's bind commit:
        names the member's node, pool generation and chip count so later
        reports can be folded into the per-chip matrix without another
        lookup.  Sheds (counted) at the member/byte budgets; at the gang
        budget the LRU gang is evicted (counted) instead."""
        with self._lock:
            # member budget FIRST when the gang doesn't exist yet: a
            # registration that would be shed anyway must not create an
            # empty gang shell (or LRU-evict a live gang to make room)
            g = self._gang_locked(gang, workload, create=False)
            if g is None and self._members >= self.max_members:
                self._shed += 1
                if self._publish:
                    goodput_reports_shed.inc()
                return
            if g is None:
                g = self._gang_locked(gang, workload, create=True)
            if pod_key not in g.members:
                if self._members >= self.max_members:
                    self._shed += 1
                    if self._publish:
                        goodput_reports_shed.inc()
                    return
                g.members[pod_key] = _Member(node, workload or g.workload,
                                             generation, max(0, chips))
                g.bytes += _MEMBER_BASE_BYTES
                self._bytes += _MEMBER_BASE_BYTES
                self._members += 1
                self._pod_to_gang[pod_key] = gang or ""
            else:
                m = g.members[pod_key]
                m.node, m.generation = node, generation or m.generation
                if chips:
                    m.chips = chips
                if workload:
                    m.workload = workload
            if workload and not g.workload:
                g.workload = workload
            self._trim_locked()

    def on_pod_delete(self, pod_key: str) -> None:
        """Teardown clears the member — including any standing straggler
        verdict (the hysteresis exit every straggler eventually takes:
        slow hardware gets drained, not argued with)."""
        edges: List[Tuple[str, float]] = []
        with self._lock:
            gang_name = self._pod_to_gang.pop(pod_key, None)
            if gang_name is None:
                return
            g = self._solo if not gang_name else self._gangs.get(gang_name)
            if g is None:
                return
            m = g.members.pop(pod_key, None)
            if m is None:
                return
            self._members -= 1
            g.bytes -= _MEMBER_BASE_BYTES
            self._bytes -= _MEMBER_BASE_BYTES
            if m.straggler:
                g.stragglers -= 1
            if not g.members and gang_name:
                self._drop_gang_locked(gang_name, g)
            elif gang_name:
                # a deletion can shift the gang median enough to cross a
                # survivor over the enter threshold — those ENTER edges
                # pin anomalies exactly like ingest-triggered ones
                edges = self._reevaluate_locked(gang_name, g)
        if self._publish:
            for surviving_pod, skew in edges:
                self._pin_straggler(gang_name, surviving_pod, skew)

    # -- ingest (apiserver status fan-out) ------------------------------------

    def ingest(self, reports) -> None:
        """Fold a batch of ``GangMemberStatus`` reports. Reports for
        unregistered members are REGISTERED on the fly (synthetic emitters
        and out-of-order heartbeats must not be lost) with unknown
        node/generation until the scheduler's registration fills them in;
        budgets shed as usual.

        Batched on purpose: ONE lock round trip, and straggler
        re-evaluation + gauge publication run once per TOUCHED GANG per
        batch instead of once per report — a 32-member gang's heartbeat
        batch costs one re-evaluation, not 32 (this is the storm-bench
        ingest overhead budget, ``make goodput-smoke``)."""
        accepted = 0
        shed = 0
        edge_pins: List[Tuple[str, str, float]] = []
        with self._lock:
            touched: Dict[str, _Gang] = {}
            for r in reports:
                gang_name = r.gang or ""
                # as in register_member: don't create (or evict for) a
                # gang whose only member would be shed at the budget
                g = self._gang_locked(r.gang, "", create=False)
                if g is None and self._members >= self.max_members:
                    shed += 1
                    continue
                if g is None:
                    g = self._gang_locked(r.gang, "", create=True)
                m = g.members.get(r.pod_key)
                if m is None:
                    if self._members >= self.max_members:
                        shed += 1
                        continue
                    m = g.members[r.pod_key] = _Member("", g.workload, "", 0)
                    g.bytes += _MEMBER_BASE_BYTES
                    self._bytes += _MEMBER_BASE_BYTES
                    self._members += 1
                    self._pod_to_gang[r.pod_key] = gang_name
                if m.reports == 0:
                    self._reporters += 1
                m.fold(r)
                g.last_wall = r.timestamp
                accepted += 1
                if gang_name:
                    self._gangs.move_to_end(gang_name)
                    touched[gang_name] = g
                self._fold_matrix_locked(m, r)
            for gang_name, g in touched.items():
                # skip gangs LRU-evicted later in this same batch:
                # re-evaluating would re-create their gauge children
                # with nothing left to remove them
                if self._gangs.get(gang_name) is not g:
                    continue
                for pod_key, skew in self._reevaluate_locked(gang_name, g):
                    edge_pins.append((gang_name, pod_key, skew))
            self._accepted += accepted
            self._shed += shed
            self._trim_locked()
        if self._publish:
            if accepted:
                goodput_reports_total.inc(accepted)
            if shed:
                goodput_reports_shed.inc(shed)
            for gang_name, pod_key, skew in edge_pins:
                self._pin_straggler(gang_name, pod_key, skew)

    # -- internals -------------------------------------------------------------

    def _gang_locked(self, gang: Optional[str], workload: str,
                     create: bool) -> Optional[_Gang]:
        if not gang:
            return self._solo
        g = self._gangs.get(gang)
        if g is None and create:
            if len(self._gangs) >= self.max_gangs:
                # evict the LRU gang to admit the new one — the newest
                # reporter is the one an operator is likely debugging
                old_name, old = self._gangs.popitem(last=False)
                self._drop_gang_locked(old_name, old, popped=True)
            g = self._gangs[gang] = _Gang(workload)
            self._bytes += g.bytes
        return g

    def _drop_gang_locked(self, name: str, g: _Gang,
                          popped: bool = False) -> None:
        if not popped:
            self._gangs.pop(name, None)
        else:
            self._evictions += 1          # budget eviction, not teardown
        for pod_key in g.members:
            self._pod_to_gang.pop(pod_key, None)
        self._members -= len(g.members)
        self._bytes -= g.bytes
        if self._publish:
            # a torn-down/evicted gang must stop being exposed, not freeze
            # at its last values — same discipline as install_slo
            for unit in g.units:
                gang_goodput_units.remove(name, unit)
                gang_goodput_per_chip.remove(name, unit)
            gang_step_skew.remove(name)
            gang_stragglers.remove(name)
            gang_straggler_events.remove(name)

    def _trim_locked(self) -> None:
        while len(self._gangs) > self.max_gangs:
            name, g = self._gangs.popitem(last=False)
            self._drop_gang_locked(name, g, popped=True)
        # byte budget: evict from whichever side holds the bulk — a flood
        # of gangless reporters must not permanently evict every gang
        # (the gang plane is the point), nor vice versa
        while self._bytes > self.max_bytes and (self._gangs
                                                or self._solo.members):
            if self._solo.members and (not self._gangs
                                       or self._solo.bytes
                                       > self.max_bytes // 2):
                pod_key = next(iter(self._solo.members))  # oldest first
                del self._solo.members[pod_key]
                self._pod_to_gang.pop(pod_key, None)
                self._members -= 1
                self._solo.bytes -= _MEMBER_BASE_BYTES
                self._bytes -= _MEMBER_BASE_BYTES
                self._evictions += 1
            else:
                name, g = self._gangs.popitem(last=False)
                self._drop_gang_locked(name, g, popped=True)

    def _fold_matrix_locked(self, m: _Member, r) -> None:
        if r.throughput <= 0 or m.chips <= 0 or not m.generation:
            return     # unattributable: no chips or unknown generation
        workload = m.workload or "unlabeled"
        if (self._matrix.cell(workload, m.generation) is None
                and self._matrix.size() >= self.max_matrix_cells):
            return     # bounded: new cells shed once the matrix is full
            # (cell-exists first: the common case skips the row scan)
        per_chip = r.throughput / m.chips
        self._matrix.fold(workload, m.generation, per_chip, m.unit,
                          r.timestamp)
        self._matrix.generated_wall = r.timestamp
        if self._publish:
            cell = self._matrix.cell(workload, m.generation)
            workload_goodput_per_chip.with_labels(
                workload, m.generation).set(round(cell.goodput_per_chip, 4))

    def _reevaluate_locked(self, gang_name: str, g: _Gang
                           ) -> List[Tuple[str, float]]:
        """Recompute gang skew + straggler verdicts after a report. Returns
        the ENTER edges (pod, skew) so the caller can pin anomalies outside
        the lock."""
        judged = {k: m for k, m in g.members.items()
                  if m.reports >= self.min_reports and m.median > 0}
        edges: List[Tuple[str, float]] = []
        gang_median = (_median([m.median for m in judged.values()])
                       if len(judged) >= STRAGGLER_MIN_MEMBERS else 0.0)
        stragglers = 0
        if gang_median <= 0:
            # too few judgeable members: a gang of one has no skew — and
            # no standing verdicts either (a straggler whose last peer
            # left must clear, not freeze), so fall through and republish
            g.skew = 1.0
            for m in g.members.values():
                m.straggler = False
        else:
            worst = max(m.p99 for m in judged.values())
            g.skew = worst / gang_median
            for pod_key, m in judged.items():
                ratio = m.p99 / gang_median
                if not m.straggler and ratio > self.enter_ratio:
                    m.straggler = True
                    self._straggler_edges += 1
                    edges.append((pod_key, ratio))
                elif m.straggler and ratio < self.clear_ratio:
                    m.straggler = False
                if m.straggler:
                    stragglers += 1
        g.stragglers = stragglers
        if self._publish:
            throughput: Dict[str, float] = {}
            per_chip_num: Dict[str, float] = {}
            chips = 0
            for m in g.members.values():
                throughput[m.unit] = throughput.get(m.unit, 0.0) \
                    + m.throughput
                chips += m.chips
            g.units |= set(throughput)
            for unit, total in throughput.items():
                gang_goodput_units.with_labels(gang_name, unit).set(
                    round(total, 3))
                if chips > 0:
                    gang_goodput_per_chip.with_labels(gang_name, unit).set(
                        round(total / chips, 4))
            gang_step_skew.with_labels(gang_name).set(round(g.skew, 4))
            gang_stragglers.with_labels(gang_name).set(stragglers)
            for _ in edges:
                gang_straggler_events.with_labels(gang_name).inc()
        return edges

    def _pin_straggler(self, gang_name: str, pod_key: str,
                       skew: float) -> None:
        """ENTER edge: pin the detection as a flight-recorder anomaly so
        the standard anomaly surfaces (/debug/flightrecorder, the anomaly
        counter) carry it — fully attributed: gang, member, skew."""
        from .. import trace
        m = None
        with self._lock:
            g = self._gangs.get(gang_name)
            if g is not None:
                m = g.members.get(pod_key)
        trace.pin_event("gang_straggler", subject=pod_key, gang=gang_name,
                        member=pod_key, node=m.node if m else "",
                        skew=round(skew, 3),
                        step_time_p99_s=round(m.p99, 4) if m else 0.0)
        klog.warning_s("gang straggler detected", gang=gang_name,
                       member=pod_key, skew=round(skew, 3))

    # -- read path (/debug/goodput, /debug/explain, whatif, bench) ------------

    def gang_health(self, query: str) -> Optional[Dict[str, Any]]:
        """Runtime health for one gang (full name or unique substring), or
        None when the gang has never reported — the RUNNING-phase answer
        the explain surface falls back to when no pending diagnosis
        exists."""
        with self._lock:
            full = query if query in self._gangs else None
            if full is None:
                hits = [gname for gname in self._gangs if query in gname]
                full = hits[0] if len(hits) == 1 else None
            if full is None:
                return None
            return self._gang_health_locked(full, self._gangs[full])

    def _gang_health_locked(self, name: str, g: _Gang) -> Dict[str, Any]:
        members = [m.to_dict(k) for k, m in sorted(g.members.items())]
        throughput: Dict[str, float] = {}
        chips = 0
        for m in g.members.values():
            throughput[m.unit] = throughput.get(m.unit, 0.0) + m.throughput
            chips += m.chips
        medians = [m.median for m in g.members.values() if m.median > 0]
        gang_median = _median(medians)
        stragglers = [
            {"pod": k, "node": m.node,
             "skew": round(m.p99 / gang_median, 3) if gang_median else 0.0,
             "step_time_p99_s": round(m.p99, 4),
             "gang_step_time_p50_s": round(gang_median, 4)}
            for k, m in sorted(g.members.items()) if m.straggler]
        return {
            "gang": name,
            "phase": "Running",
            "workload": g.workload,
            "members": members,
            "members_reporting": len(g.members),
            "chips": chips,
            "goodput": {u: round(v, 3) for u, v in throughput.items()},
            "goodput_per_chip": {u: round(v / chips, 4)
                                 for u, v in throughput.items()
                                 if chips > 0},
            "step_time_p50_s": round(gang_median, 4),
            "step_skew": round(g.skew, 4),
            "stragglers": stragglers,
            "last_report_wall": g.last_wall,
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "gangs": len(self._gangs),
                "members": self._members,
                "solo_members": len(self._solo.members),
                "approx_bytes": self._bytes,
                "max_gangs": self.max_gangs,
                "max_members": self.max_members,
                "max_bytes": self.max_bytes,
                "accepted_total": self._accepted,
                "shed_total": self._shed,
                "gang_evictions_total": self._evictions,
                "reporters_total": self._reporters,
                "straggler_edges_total": self._straggler_edges,
                "matrix_cells": self._matrix.size(),
                "attached": self._api is not None,
            }

    def dump(self) -> Dict[str, Any]:
        """The /debug/goodput payload: stats + the live fleet census +
        per-gang runtime health + the matrix summary, one document."""
        with self._lock:
            gangs = [self._gang_health_locked(name, g)
                     for name, g in list(self._gangs.items())[-64:]]
            matrix = self._matrix.summary()
        return {"stats": self.stats(), "fleet": self.fleet_summary(),
                "gangs": gangs, "matrix": matrix}

    def fleet_summary(self) -> Dict[str, Any]:
        """The LIVE fleet census (rides in ``dump()``/``/debug/goodput``):
        total reported throughput by unit, mean per-chip goodput and the
        straggler count over currently-live members.  A census of what is
        reporting right now — for cumulative whole-run accounting (the
        bench stamp) use ``stats()``, whose counters survive teardown."""
        with self._lock:
            throughput: Dict[str, float] = {}
            per_chip: List[float] = []
            stragglers = 0
            all_gangs = list(self._gangs.values()) + [self._solo]
            for g in all_gangs:
                stragglers += g.stragglers
                for m in g.members.values():
                    throughput[m.unit] = throughput.get(m.unit, 0.0) \
                        + m.throughput
                    if m.chips > 0 and m.throughput > 0:
                        per_chip.append(m.throughput / m.chips)
            return {
                "units_per_s": {u: round(v, 3)
                                for u, v in throughput.items()},
                "goodput_per_chip_mean": round(
                    sum(per_chip) / len(per_chip), 4) if per_chip else 0.0,
                "reporting_members": len(per_chip),
                "stragglers": stragglers,
                "reports": self._accepted,
                "shed": self._shed,
            }

    def matrix_snapshot(self) -> GoodputMatrix:
        """A deep snapshot of the current matrix (safe to mutate/save)."""
        with self._lock:
            return GoodputMatrix.from_dict(self._matrix.to_dict())

    def peek(self, workload: str, generation: str) -> Optional[float]:
        with self._lock:
            return self._matrix.peek(workload, generation)

    def save_matrix(self, path: str) -> None:
        self.matrix_snapshot().save(path)


# -- offline reconstruction from a recorded fleet trace ------------------------

def matrix_from_trace(trace) -> GoodputMatrix:
    """Rebuild the throughput matrix from a recorded fleet trace
    (``obs.fleetrace.FleetTrace``): join each ``goodput-report`` event with
    the trace's own record of where that pod ran (bind-commits), what
    hardware that was (node objects → generation label), and what the pod
    asked for (arrival specs → chips + workload fingerprint).  This is what
    makes recorded traces carry the matrix for replay/policy evaluation —
    no live aggregator state needed."""
    from ..api.scheduling import pod_group_full_name
    from ..api.topology import LABEL_ACCELERATOR
    from ..apiserver import server as srv
    from ..apiserver.persistence import KIND_CLASSES, decode_object

    node_gen: Dict[str, str] = {}
    for node in trace.objects.get(srv.NODES, ()):
        node_gen[node.meta.name] = node.meta.labels.get(LABEL_ACCELERATOR,
                                                        "")
    pods: Dict[str, Any] = {}                   # key → decoded Pod
    for pod in trace.objects.get(srv.PODS, ()):
        pods[pod.meta.key] = pod
    groups: Dict[str, Any] = {}                 # full name → PodGroup
    for pg in trace.objects.get(srv.POD_GROUPS, ()):
        groups[pg.meta.key] = pg
    pod_node: Dict[str, str] = {
        pod.meta.key: pod.spec.node_name
        for pod in trace.objects.get(srv.PODS, ())
        if pod.spec.node_name}

    matrix = GoodputMatrix()
    for e in trace.events:
        kind = e.get("kind")
        if kind in ("node-add", "node-update", "node-health") \
                and e.get("object") is not None:
            node = decode_object(KIND_CLASSES[srv.NODES], e["object"])
            node_gen[node.meta.name] = node.meta.labels.get(
                LABEL_ACCELERATOR, "")
        elif kind == "pod-arrival" and e.get("object") is not None:
            pod = decode_object(KIND_CLASSES[srv.PODS], e["object"])
            pods[pod.meta.key] = pod
        elif kind in ("podgroup-add", "podgroup-update") \
                and e.get("object") is not None:
            pg = decode_object(KIND_CLASSES[srv.POD_GROUPS], e["object"])
            groups[pg.meta.key] = pg
        elif kind == "bind-commit":
            pod_node[e.get("pod", "")] = e.get("node", "")
        elif kind == "goodput-report":
            pod = pods.get(e.get("pod", ""))
            throughput = float(e.get("throughput", 0.0))
            chips = pod_chips(pod) if pod is not None else 0
            generation = node_gen.get(pod_node.get(e.get("pod", ""), ""),
                                      "")
            if pod is None or throughput <= 0 or chips <= 0 \
                    or not generation:
                continue
            # the same fingerprint the LIVE path computes: the pod joined
            # with its PodGroup (slice shape), so offline and online
            # matrices key identically
            pg = groups.get(pod_group_full_name(pod) or "")
            workload = workload_fingerprint_of(pod, pg)
            matrix.fold(workload or "unlabeled", generation,
                        throughput / chips, e.get("unit", "tokens"),
                        e.get("wall", 0.0))
            matrix.generated_wall = e.get("wall", 0.0)
    return matrix


def pod_chips(pod) -> int:
    """TPU chips a pod asks for — the one chip-counting rule shared by
    the scheduler's bind-time registration and the matrix fingerprint."""
    return sum(int(c.limits.get(TPU, 0)) for c in pod.spec.containers)
