"""Black-box incident bundles: the capture stage of the closed incident
loop (ISSUE 20).

When a sentinel detector fires, everything a postmortem needs is still
in memory — the timeline window around the trigger, the pinned anomaly
traces, /debug/explain documents for the top blocked gangs, the
profiler's hot-path attribution, the fleetrace capture cursor, every
flight-recorder health section, the config fingerprint.  Ten minutes
later it has scrolled out of the bounded rings.  ``IncidentManager``
freezes it NOW, into one atomic, crash-safe, disk-bounded JSON bundle —
the scheduler's flight data recorder, written at the moment of impact.

Crash safety follows apiserver/persistence.Journal discipline: bundles
are written to ``<id>.json.tmp``, flushed, fsynced, then ``os.replace``d
into place — a crash mid-write leaves a ``.tmp`` (removed on reopen),
never a torn ``.json``.  A ``.json`` that fails to parse on reopen
(torn by an older writer, truncated disk) is quarantined to
``.corrupt``, counted, and never served.

Shadow isolation: a ``publish=False`` manager keeps bundles in a
bounded in-memory ring (directory=None) on the shadow's clock — the
virtual-time policy-evaluation plane reads them; nothing touches disk
or the global ``tpusched_incident_bundles_*`` counters.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional

from ..util import klog
from ..util.clock import WALL, Clock
from ..util.metrics import (incident_bundles_dropped_total,
                            incident_bundles_written_total)

__all__ = ["IncidentManager", "validate_bundle", "config_fingerprint",
           "wire_incident_plane", "SCHEMA_VERSION", "ENV_DIR"]

SCHEMA_VERSION = 1
ENV_DIR = "TPUSCHED_INCIDENT_DIR"

DEFAULT_MAX_BUNDLES = 32
DEFAULT_MAX_BYTES = 32 << 20
DEFAULT_COOLDOWN_S = 60.0
# timeline seconds frozen around the trigger: enough to see the healthy
# baseline BEFORE the collapse, bounded so a bundle stays readable
INCIDENT_WINDOW_S = 180.0
_EXPLAIN_GANGS = 5
_PROFILER_CAPTURE_S = 0.75

_REQUIRED_KEYS = ("schema_version", "id", "captured_wall", "trigger",
                  "sections")


class IncidentManager:
    """Bounded store of black-box bundles, disk- or memory-backed."""

    def __init__(self, directory: Optional[str] = None,
                 max_bundles: int = DEFAULT_MAX_BUNDLES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 publish: bool = True,
                 clock: Optional[Clock] = None):
        self.directory = directory or None
        self.max_bundles = int(max_bundles)
        self.max_bytes = int(max_bytes)
        self.cooldown_s = float(cooldown_s)
        self.publish = publish
        self._clock: Clock = clock if clock is not None else WALL
        self._lock = threading.Lock()
        self._memory: List[Dict[str, Any]] = []   # directory=None mode
        self._seq = 0
        self._last_capture: Dict[str, float] = {}  # detector -> wall
        self._written_total = 0
        self._dropped_total = 0
        self._recovered_tmp = 0
        self._quarantined = 0
        if self.directory:
            self._recover()

    # -- crash recovery -------------------------------------------------------

    def _recover(self) -> None:
        """Reopen discipline: a ``.tmp`` is an interrupted write (atomic
        replace never happened — remove it); a ``.json`` that fails to
        parse is quarantined to ``.corrupt`` so it is counted once and
        never served or deleted by the budget sweep."""
        os.makedirs(self.directory, exist_ok=True)
        for name in sorted(os.listdir(self.directory)):
            path = os.path.join(self.directory, name)
            if name.endswith(".tmp"):
                try:
                    os.remove(path)
                    self._recovered_tmp += 1
                except OSError:
                    pass
                continue
            if not name.endswith(".json"):
                continue
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
                if validate_bundle(doc):
                    raise ValueError("schema")
            except (OSError, ValueError):
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    pass
                self._quarantined += 1

    # -- capture --------------------------------------------------------------

    def arm_directory(self, directory: str) -> None:
        """Switch from memory to disk mode (``ensure_incidents`` path)."""
        with self._lock:
            if self.directory == directory:
                return
            self.directory = directory
            self._recover()

    def capture(self, trigger: Dict[str, Any],
                sources: Dict[str, Callable[[], Any]]) -> Optional[str]:
        """Freeze one bundle.  ``trigger`` is the sentinel firing;
        ``sources`` maps section name -> zero-arg callable.  A raising
        source becomes an error section — partial evidence beats no
        bundle.  Returns the bundle id, or None when suppressed
        (cooldown) or dropped (budget/write failure)."""
        detector = str(trigger.get("detector", "unknown"))
        wall = self._clock.wall()
        with self._lock:
            last = self._last_capture.get(detector)
            if last is not None and wall - last < self.cooldown_s:
                return None
            self._last_capture[detector] = wall
            self._seq += 1
            seq = self._seq
        bundle_id = f"inc-{int(wall * 1000):013d}-{seq:04d}-{detector}"
        sections: Dict[str, Dict[str, Any]] = {}
        for name, fn in sorted(sources.items()):
            try:
                sections[name] = {"ok": True, "data": fn()}
            except Exception as e:  # noqa: BLE001 — partial evidence
                # beats no bundle; the error IS the section's evidence
                sections[name] = {"ok": False, "error": str(e)}
        doc = {"schema_version": SCHEMA_VERSION, "id": bundle_id,
               "captured_wall": wall, "trigger": trigger,
               "sections": sections}
        if self._store(doc):
            if self.publish:
                incident_bundles_written_total.inc()
            return bundle_id
        if self.publish:
            incident_bundles_dropped_total.inc()
        return None

    def _store(self, doc: Dict[str, Any]) -> bool:
        if not self.directory:
            with self._lock:
                self._memory.append(doc)
                while len(self._memory) > self.max_bundles:
                    self._memory.pop(0)
                    self._dropped_total += 1
                self._written_total += 1
            return True
        try:
            payload = json.dumps(doc, sort_keys=True, default=str)
        except (TypeError, ValueError) as e:
            klog.error_s(e, "incident bundle not serializable",
                         id=doc["id"])
            with self._lock:
                self._dropped_total += 1
            return False
        if len(payload) > self.max_bytes:
            with self._lock:
                self._dropped_total += 1
            return False
        path = os.path.join(self.directory, doc["id"] + ".json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            klog.error_s(e, "incident bundle write failed", id=doc["id"])
            try:
                os.remove(tmp)
            except OSError:
                pass
            with self._lock:
                self._dropped_total += 1
            return False
        with self._lock:
            self._written_total += 1
        self._enforce_budget()
        return True

    def _enforce_budget(self) -> None:
        """Oldest-first deletion past either budget (ids sort by capture
        wall time, so lexicographic order IS age order)."""
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.endswith(".json"))
            sizes = {}
            for n in names:
                try:
                    sizes[n] = os.path.getsize(
                        os.path.join(self.directory, n))
                except OSError:
                    sizes[n] = 0
            dropped = 0
            while names and (len(names) > self.max_bundles
                             or sum(sizes[n] for n in names)
                             > self.max_bytes):
                victim = names.pop(0)
                try:
                    os.remove(os.path.join(self.directory, victim))
                    dropped += 1
                except OSError:
                    pass
            if dropped:
                with self._lock:
                    self._dropped_total += dropped
                if self.publish:
                    incident_bundles_dropped_total.inc(dropped)
        except OSError as e:
            klog.V(4).info_s("incident budget sweep failed", err=str(e))

    # -- reads ----------------------------------------------------------------

    def list(self) -> List[Dict[str, Any]]:
        """Newest-first index: id, detector, captured_wall, sections."""
        docs: List[Dict[str, Any]] = []
        if not self.directory:
            with self._lock:
                mem = list(self._memory)
            docs = mem
        else:
            try:
                names = sorted((n for n in os.listdir(self.directory)
                                if n.endswith(".json")), reverse=True)
            except OSError:
                names = []
            for n in names:
                doc = self._read(os.path.join(self.directory, n))
                if doc is not None:
                    docs.append(doc)
        index = [{"id": d["id"],
                  "detector": d.get("trigger", {}).get("detector"),
                  "captured_wall": d.get("captured_wall"),
                  "sections": sorted(d.get("sections", {}))}
                 for d in docs]
        index.sort(key=lambda e: str(e["id"]), reverse=True)
        return index

    def get(self, bundle_id: str) -> Optional[Dict[str, Any]]:
        if not self.directory:
            with self._lock:
                for doc in reversed(self._memory):
                    if doc["id"] == bundle_id:
                        return doc
            return None
        # ids are filenames minus .json; refuse path traversal
        if "/" in bundle_id or bundle_id.startswith("."):
            return None
        return self._read(os.path.join(self.directory,
                                       bundle_id + ".json"))

    @staticmethod
    def _read(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def diff(self, id_a: str, id_b: str) -> Optional[Dict[str, Any]]:
        """Section-level structural diff between two bundles — the
        'what changed between the 3am incident and the 4am one' view."""
        a, b = self.get(id_a), self.get(id_b)
        if a is None or b is None:
            return None
        sa, sb = a.get("sections", {}), b.get("sections", {})
        common = sorted(set(sa) & set(sb))
        changed = {}
        for name in common:
            da, db = sa[name].get("data"), sb[name].get("data")
            if da == db:
                continue
            if isinstance(da, dict) and isinstance(db, dict):
                keys = sorted(set(da) | set(db))
                changed[name] = [k for k in keys
                                 if da.get(k) != db.get(k)]
            else:
                changed[name] = ["<value>"]
        return {"a": id_a, "b": id_b,
                "trigger_a": a.get("trigger", {}).get("detector"),
                "trigger_b": b.get("trigger", {}).get("detector"),
                "only_in_a": sorted(set(sa) - set(sb)),
                "only_in_b": sorted(set(sb) - set(sa)),
                "changed": changed}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"directory": self.directory or "",
                    "max_bundles": self.max_bundles,
                    "max_bytes": self.max_bytes,
                    "cooldown_s": self.cooldown_s,
                    "written_total": self._written_total,
                    "dropped_total": self._dropped_total,
                    "recovered_tmp": self._recovered_tmp,
                    "quarantined": self._quarantined}

    def census(self) -> Dict[str, Any]:
        """Deterministic comparison view: per-detector bundle counts
        (derived from ids — stable across two virtual replays of one
        trace) plus the written/dropped totals."""
        by_detector: Dict[str, int] = {}
        for entry in self.list():
            d = str(entry.get("detector"))
            by_detector[d] = by_detector.get(d, 0) + 1
        with self._lock:
            return {"written_total": self._written_total,
                    "dropped_total": self._dropped_total,
                    "by_detector": dict(sorted(by_detector.items()))}


# -- schema -------------------------------------------------------------------

def validate_bundle(doc: Any) -> List[str]:
    """Schema-v1 validation: a list of problems, [] when valid."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["bundle is not an object"]
    for key in _REQUIRED_KEYS:
        if key not in doc:
            problems.append(f"missing key: {key}")
    if doc.get("schema_version") != SCHEMA_VERSION:
        problems.append(
            f"schema_version {doc.get('schema_version')!r}, "
            f"want {SCHEMA_VERSION}")
    if not isinstance(doc.get("id"), str) or not doc.get("id"):
        problems.append("id must be a non-empty string")
    if not isinstance(doc.get("captured_wall"), (int, float)):
        problems.append("captured_wall must be a number")
    trigger = doc.get("trigger")
    if not isinstance(trigger, dict):
        problems.append("trigger must be an object")
    elif not trigger.get("detector"):
        problems.append("trigger.detector missing")
    sections = doc.get("sections")
    if not isinstance(sections, dict):
        problems.append("sections must be an object")
    else:
        for name, sec in sections.items():
            if not isinstance(sec, dict) or "ok" not in sec:
                problems.append(f"section {name}: missing ok flag")
            elif sec["ok"] and "data" not in sec:
                problems.append(f"section {name}: ok without data")
            elif not sec["ok"] and "error" not in sec:
                problems.append(f"section {name}: failed without error")
    return problems


def config_fingerprint(profile) -> Dict[str, Any]:
    """Stable digest of the effective scheduler profile — two bundles
    with different fingerprints were captured under different configs,
    which is usually the whole diagnosis."""
    try:
        import dataclasses
        if dataclasses.is_dataclass(profile):
            snap = dataclasses.asdict(profile)
        else:
            snap = dict(getattr(profile, "__dict__", {}))
    # tpulint: disable=exception-taxonomy — a fingerprint must never fail
    # a capture; an unconvertible profile degrades to its repr
    except Exception:  # noqa: BLE001
        snap = {"repr": repr(profile)}
    snap = {k: v for k, v in snap.items()
            if isinstance(v, (str, int, float, bool, type(None)))}
    blob = json.dumps(snap, sort_keys=True, separators=(",", ":"))
    return {"sha256": hashlib.sha256(blob.encode("utf-8")).hexdigest(),
            "profile": snap}


# -- scheduler wiring ---------------------------------------------------------

def wire_incident_plane(sched, timeline, sentinel,
                        incidents: IncidentManager) -> None:
    """Close the loop for one scheduler: curated families onto the
    timeline, sentinel listening on ticks and pinning into the
    scheduler's recorder, firings freezing bundles whose sources read
    the scheduler's own surfaces.  Everything closes over a WEAK ref —
    the (possibly process-global) plane must not keep a stopped
    scheduler alive."""
    from .timeline import register_scheduler_families
    register_scheduler_families(timeline, sched)
    sentinel.recorder = sched.recorder
    sentinel.attach(timeline)
    ref = weakref.ref(sched)
    telemetry = bool(getattr(sched, "_telemetry", True))

    def on_firing(firing: Dict[str, Any]) -> None:
        s = ref()
        if s is None:
            return
        incidents.capture(firing, _bundle_sources(s, timeline, sentinel,
                                                  telemetry))

    sentinel.on_firing = on_firing
    timeline.arm_on(sched.clock_handle)


def _bundle_sources(s, timeline, sentinel,
                    telemetry: bool) -> Dict[str, Callable[[], Any]]:
    """The section callables for one capture — each reads a surface the
    operator would otherwise have had to curl mid-incident."""

    def explain() -> Dict[str, Any]:
        doc = s.obs_engine.dump()
        gangs = {}
        for name in doc.get("pending_gangs", [])[:_EXPLAIN_GANGS]:
            gangs[name] = s.obs_engine.explain_gang(name)
        doc["gangs"] = gangs
        return doc

    def profiler() -> Dict[str, Any]:
        # live schedulers only: a fresh bounded capture window, falling
        # back to the rolling attribution when concurrent captures are
        # saturated.  Shadows never register this source — a trial must
        # not read (or block on) the live sampler.
        if not telemetry:
            return {"fresh": False, "skipped": "shadow"}
        from . import default_profiler
        prof = default_profiler()
        cap = prof.capture(_PROFILER_CAPTURE_S) if prof.running else None
        if cap is not None:
            return {"fresh": True, "stats": cap.stats(),
                    "top": cap.top_attribution(10)}
        return {"fresh": False, "health": prof.health()}

    sources: Dict[str, Callable[[], Any]] = {
        "timeline": lambda: timeline.window(INCIDENT_WINDOW_S),
        "timeline_stats": timeline.stats,
        "anomalies": s.recorder.pinned_dump,
        "explain": explain,
        "fleetrace": s._fleet.status,
        "health": s.recorder.health,
        "sentinel": sentinel.stats,
        "queues": lambda: dict(s.queue.pending_counts()),
        "config": lambda: config_fingerprint(s.profile),
    }
    if telemetry:
        sources["profiler"] = profiler
    return sources
