"""Typed, versioned, defaulted plugin-args config API.

Analog of /root/reference/apis/config (internal types + v1beta2/v1beta3
versioned decode + defaults, registered into the scheduler scheme so YAML
pluginConfig decodes to typed args — types.go:28-160, scheme/scheme.go:30-47).
Here: dataclass args types, a name→type scheme, hand-written defaults
(defaults.go analogs), and a YAML/dict decoder with strict unknown-field
checking.
"""
from .types import (TpuSliceArgs, CoschedulingArgs, ElasticQuotaArgs,
                    TopologyMatchArgs, MultiSliceArgs,
                    NodeResourcesAllocatableArgs, TargetLoadPackingArgs,
                    LoadVariationRiskBalancingArgs, PreemptionTolerationArgs)
from .scheme import decode_plugin_args, decode_profile, ARGS_SCHEME

__all__ = [n for n in dir() if not n.startswith("_")]
