"""Canonical scheduler profiles — the analog of the reference's per-plugin
deployment YAML (manifests/*/scheduler-config.yaml). These are the wirings a
deployment would select; tests compose their own narrower ones."""
from __future__ import annotations

from ..fwk.runtime import PluginProfile
from .types import CoschedulingArgs


def tpu_gang_profile(permit_wait_s: int = 60, denied_s: int = 20,
                     scheduler_name: str = "tpusched") -> PluginProfile:
    """The flagship profile: gang admission + TPU chip placement.
    Mirrors the coscheduling config (queueSort/preFilter/postFilter/permit/
    reserve/postBind, manifests/coscheduling/scheduler-config.yaml:10-34)
    combined with the flexgpu chart's custom-bind wiring
    (manifests/flexgpu/templates/configmap.yaml:14-28)."""
    return PluginProfile(
        scheduler_name=scheduler_name,
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling", "TopologyMatch", "MultiSlice"],
        # TopologyMatch first: its per-node check is one set lookup against
        # the PreFilter stash and it is the most selective filter for slice
        # gangs (a 16-pool fleet rejects ~15/16 of hosts here) — running it
        # early skips the rest of the chain for every rejected host.
        # Filters are conjunctive, so order changes cost, not outcome.
        filter=["TopologyMatch", "MultiSlice", "NodeUnschedulable", "NodeName",
                "NodeSelector", "TaintToleration", "NodeResourcesFit",
                "TpuSlice"],
        # MultiSlice after Coscheduling: its set teardown relies on
        # Coscheduling having already judged (and possibly graced) the
        # failing member gang
        post_filter=["Coscheduling", "MultiSlice"],
        pre_score=["MultiSlice"],
        score=[("TpuSlice", 1), ("TopologyMatch", 2), ("MultiSlice", 3)],
        reserve=["TpuSlice", "TopologyMatch", "Coscheduling", "MultiSlice"],
        # Coscheduling first: a pod clears its gang quorum check before the
        # set barrier decides whether the whole set may proceed
        permit=["Coscheduling", "MultiSlice"],
        bind=["TpuSlice"],
        post_bind=["Coscheduling"],
        plugin_args={"Coscheduling": CoschedulingArgs(
            permit_waiting_time_seconds=permit_wait_s,
            denied_pg_expiration_time_seconds=denied_s)},
    )


def full_stack_profile(permit_wait_s: int = 60, denied_s: int = 20,
                       scheduler_name: str = "tpusched") -> PluginProfile:
    """Everything composed: gang admission under team ElasticQuotas with
    quota-aware preemption, ICI-torus slice fitting, chip placement, and
    DCN-aware multi-slice scoring — the production wiring a multi-team TPU
    fleet runs (the reference composes its plugins the same way: all are
    framework plugins in one scheduler, SURVEY §1)."""
    from .types import TopologyMatchArgs
    prof = tpu_gang_profile(permit_wait_s=permit_wait_s, denied_s=denied_s,
                            scheduler_name=scheduler_name)
    prof.pre_filter = prof.pre_filter + ["CapacityScheduling"]
    # TopologyMatch's slice preemption first: window-wise eviction for
    # slice-shaped gangs (single-node preemption cannot free a torus block);
    # CapacityScheduling's evaluator handles the non-slice pods after it
    prof.post_filter = (["TopologyMatch"] + prof.post_filter
                        + ["CapacityScheduling"])
    prof.reserve = prof.reserve + ["CapacityScheduling"]
    prof.plugin_args["TopologyMatch"] = TopologyMatchArgs(
        enable_slice_preemption=True)
    return prof


def capacity_profile(scheduler_name: str = "tpusched") -> PluginProfile:
    """ElasticQuota capacity sharing + quota-aware preemption over TPU
    placement (mirrors manifests/capacityscheduling/scheduler-config wiring:
    preFilter/postFilter/reserve)."""
    return PluginProfile(
        scheduler_name=scheduler_name,
        queue_sort="PrioritySort",
        pre_filter=["CapacityScheduling"],
        filter=["NodeUnschedulable", "NodeName", "NodeSelector",
                "TaintToleration", "NodeResourcesFit", "TpuSlice"],
        post_filter=["CapacityScheduling"],
        score=[("TpuSlice", 1)],
        reserve=["TpuSlice", "CapacityScheduling"],
        bind=["TpuSlice"],
    )


def tpuslice_profile(scheduler_name: str = "tpusched") -> PluginProfile:
    """TpuSlice-only wiring (the flexgpu Helm chart analog)."""
    return PluginProfile(
        scheduler_name=scheduler_name,
        queue_sort="PrioritySort",
        filter=["NodeUnschedulable", "NodeName", "NodeSelector",
                "TaintToleration", "NodeResourcesFit", "TpuSlice"],
        score=[("TpuSlice", 1)],
        reserve=["TpuSlice"],
        bind=["TpuSlice"],
    )


def load_aware_profile(watcher_address: str = "",
                       target_utilization: "int | None" = None,
                       scheduler_name: str = "tpusched") -> PluginProfile:
    """Trimaran load-aware scoring (mirrors manifests/trimaran/
    scheduler-config wiring: TargetLoadPacking as the sole scorer fed by a
    load-watcher endpoint, targetloadpacking.go:82-96)."""
    from .types import TargetLoadPackingArgs
    args = TargetLoadPackingArgs(watcher_address=watcher_address)
    if target_utilization is not None:
        args.target_utilization = target_utilization
    return PluginProfile(
        scheduler_name=scheduler_name,
        queue_sort="PrioritySort",
        filter=["NodeUnschedulable", "NodeName", "NodeSelector",
                "TaintToleration", "NodeResourcesFit"],
        score=[("TargetLoadPacking", 1)],
        bind=["DefaultBinder"],
        plugin_args={"TargetLoadPacking": args},
    )
