"""Scheme: plugin name → args type, plus strict profile decoding.

Analog of apis/config/register.go + scheme/scheme.go (strict codecs: unknown
fields are errors, scheme.go:35) and the profile wiring of
KubeSchedulerConfiguration YAML (manifests/*/scheduler-config.yaml).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

from ..fwk.runtime import PluginProfile
from . import types as t

# Plugin name → args dataclass ("<PluginName>Args" convention).
ARGS_SCHEME: Dict[str, type] = {
    "TpuSlice": t.TpuSliceArgs,
    "Coscheduling": t.CoschedulingArgs,
    "CapacityScheduling": t.ElasticQuotaArgs,
    "TopologyMatch": t.TopologyMatchArgs,
    "MultiSlice": t.MultiSliceArgs,
    "NodeResourcesAllocatable": t.NodeResourcesAllocatableArgs,
    "TargetLoadPacking": t.TargetLoadPackingArgs,
    "LoadVariationRiskBalancing": t.LoadVariationRiskBalancingArgs,
    "PreemptionToleration": t.PreemptionTolerationArgs,
}


class ConfigError(ValueError):
    pass


def decode_plugin_args(plugin_name: str, raw: Dict[str, Any]):
    """Decode a raw dict into the plugin's typed args with defaulting; strict
    on unknown fields (the reference uses strict codecs)."""
    cls = ARGS_SCHEME.get(plugin_name)
    if cls is None:
        raise ConfigError(f"no args type registered for plugin {plugin_name!r}")
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in (raw or {}).items():
        norm = _camel_to_snake(k)
        if norm not in fields:
            raise ConfigError(f"unknown field {k!r} in {plugin_name}Args")
        kwargs[norm] = v
    args = cls(**kwargs)
    # args types may define validate() raising ValueError — surfaced here so
    # --validate-only catches range errors, not a silent clamp at score time
    validate = getattr(args, "validate", None)
    if validate is not None:
        try:
            validate()
        except ValueError as e:
            raise ConfigError(f"{plugin_name}Args: {e}") from e
    return args


def _camel_to_snake(name: str) -> str:
    out = []
    for i, c in enumerate(name):
        if c.isupper() and i > 0:
            # boundary at lower→Upper and at the end of an acronym run
            # (Upper followed by lower), so "deniedPGExpirationTimeSeconds"
            # maps to denied_pg_expiration_time_seconds.
            prev_upper = name[i - 1].isupper()
            next_lower = i + 1 < len(name) and name[i + 1].islower()
            if not prev_upper or next_lower:
                out.append("_")
        out.append(c.lower())
    return "".join(out)


_EXTENSION_POINTS = ("preFilter", "filter", "postFilter", "preScore", "score",
                     "reserve", "permit", "preBind", "bind", "postBind")
_POINT_ATTR = {
    "preFilter": "pre_filter", "filter": "filter", "postFilter": "post_filter",
    "preScore": "pre_score", "reserve": "reserve", "permit": "permit",
    "preBind": "pre_bind", "bind": "bind", "postBind": "post_bind",
}


def decode_profile(raw: Dict[str, Any]) -> PluginProfile:
    """Decode a profile dict (YAML-shaped, mirroring KubeSchedulerConfiguration):

    schedulerName: tpusched
    plugins:
      queueSort: {enabled: [{name: Coscheduling}]}
      filter: {enabled: [{name: TpuSlice}], disabled: [{name: "*"}]}
      score: {enabled: [{name: TpuSlice, weight: 2}]}
    pluginConfig:
      - name: Coscheduling
        args: {permitWaitingTimeSeconds: 10}
    """
    pct = int(raw.get("percentageOfNodesToScore", 0) or 0)
    if not 0 <= pct <= 100:
        raise ConfigError(
            f"percentageOfNodesToScore must be 0-100, got {pct}")
    profile = PluginProfile(
        scheduler_name=raw.get("schedulerName", "tpusched"),
        percentage_of_nodes_to_score=pct)
    # sharded dispatch core (sched/shards.py): lane count and bind-pool
    # width. dispatchShards: 1 = classic single loop, 0 = auto;
    # bindPoolWorkers: 0 = auto (sized relative to the shard count)
    for yaml_key, attr, lo in (("dispatchShards", "dispatch_shards", 0),
                               ("bindPoolWorkers", "bind_pool_workers", 0)):
        if yaml_key in raw:
            try:
                v = int(raw[yaml_key])
            except (TypeError, ValueError):
                raise ConfigError(
                    f"{yaml_key} must be an integer, got {raw[yaml_key]!r}")
            if v < lo:
                raise ConfigError(f"{yaml_key} must be >= {lo}, got {v}")
            setattr(profile, attr, v)
    if "quotaSerializeDispatch" in raw:
        v = raw["quotaSerializeDispatch"]
        if not isinstance(v, bool):
            raise ConfigError(
                f"quotaSerializeDispatch must be a boolean, got {v!r}")
        profile.quota_serialize_dispatch = v
    # native batched dispatch (sched/nativedispatch.py): boolean gate +
    # sampled in-cycle differential period (0 disables sampling)
    if "nativeDispatch" in raw:
        v = raw["nativeDispatch"]
        if not isinstance(v, bool):
            raise ConfigError(
                f"nativeDispatch must be a boolean, got {v!r}")
        profile.native_dispatch = v
    if "nativeDispatchDifferentialPeriod" in raw:
        try:
            v = int(raw["nativeDispatchDifferentialPeriod"])
        except (TypeError, ValueError):
            raise ConfigError(
                "nativeDispatchDifferentialPeriod must be an integer, got "
                f"{raw['nativeDispatchDifferentialPeriod']!r}")
        if v < 0:
            raise ConfigError(
                f"nativeDispatchDifferentialPeriod must be >= 0, got {v}")
        profile.native_dispatch_differential_period = v
    slo = raw.get("slo", {}) or {}
    if not isinstance(slo, dict):
        raise ConfigError(f"slo must be a mapping, got {type(slo).__name__}")
    for yaml_key, attr in (("podE2ESeconds", "slo_pod_e2e_s"),
                           ("gangBoundSeconds", "slo_gang_bound_s")):
        if yaml_key in slo:
            try:
                v = float(slo[yaml_key])
            except (TypeError, ValueError):
                raise ConfigError(
                    f"slo.{yaml_key} must be a number, got "
                    f"{slo[yaml_key]!r}")
            if v < 0:
                raise ConfigError(f"slo.{yaml_key} must be >= 0, got {v}")
            setattr(profile, attr, v)
    unknown = set(slo) - {"podE2ESeconds", "gangBoundSeconds"}
    if unknown:
        raise ConfigError(f"unknown slo fields: {sorted(unknown)}")
    plugins = raw.get("plugins", {}) or {}

    qs = plugins.get("queueSort", {}).get("enabled", [])
    if qs:
        profile.queue_sort = qs[0]["name"]

    for point in _EXTENSION_POINTS:
        spec = plugins.get(point, {}) or {}
        enabled = spec.get("enabled", []) or []
        if point == "score":
            profile.score = [(e["name"], int(e.get("weight", 1))) for e in enabled]
        else:
            getattr(profile, _POINT_ATTR[point]).extend(e["name"] for e in enabled)

    for pc in raw.get("pluginConfig", []) or []:
        name = pc["name"]
        profile.plugin_args[name] = decode_plugin_args(name, pc.get("args", {}))
    # plugins without explicit config get defaulted args
    for name in profile.all_plugin_names():
        if name not in profile.plugin_args and name in ARGS_SCHEME:
            profile.plugin_args[name] = ARGS_SCHEME[name]()
    return profile
