"""Versioned YAML configuration API for the scheduler binary.

Analog of the reference's KubeSchedulerConfiguration machinery: typed,
versioned, defaulted plugin args registered into the scheduler scheme so that
YAML ``pluginConfig`` decodes to typed args structs
(/root/reference/apis/config/register.go:26-45, apis/config/scheme/scheme.go:30-47),
with two coexisting API versions and hand-maintained conversion between them
(/root/reference/apis/config/v1beta2/zz_generated.conversion.go,
v1beta3/...). Decoding is strict — unknown fields are errors — mirroring the
reference's strict codecs (scheme.go:35).

The YAML shape mirrors the reference's deployment profiles
(manifests/*/scheduler-config.yaml): per-extension-point ``enabled`` /
``disabled`` lists with a ``"*"`` wildcard merged over the default plugin set,
plus a ``pluginConfig`` list of ``{name, args}`` decoded through the
``<PluginName>Args`` scheme.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import yaml

from ..fwk.runtime import PluginProfile
from .scheme import ARGS_SCHEME, ConfigError, decode_plugin_args

GROUP = "tpusched.config.tpu.dev"
KIND = "TpuSchedulerConfiguration"
V1BETA1 = f"{GROUP}/v1beta1"    # current version
V1ALPHA1 = f"{GROUP}/v1alpha1"  # legacy version, converted on decode
SUPPORTED_VERSIONS = (V1BETA1, V1ALPHA1)

# The extension points a profile may wire (KubeSchedulerConfiguration's
# `plugins` map keys; SURVEY §1 "QueueSort → ... → PostBind").
EXTENSION_POINTS = ("queueSort", "preFilter", "filter", "postFilter",
                    "preScore", "score", "reserve", "permit", "preBind",
                    "bind", "postBind")

# Default plugin wiring (the upstream default-plugins analog): what a profile
# starts from before enabled/disabled merging. `disabled: [{name: "*"}]`
# clears an extension point, exactly as the coscheduling manifest does for
# queueSort (manifests/coscheduling/scheduler-config.yaml:12-14).
DEFAULT_PLUGINS: Dict[str, List[str]] = {
    "queueSort": ["PrioritySort"],
    "preFilter": [],
    "filter": ["NodeUnschedulable", "NodeName", "NodeSelector",
               "TaintToleration", "NodeResourcesFit"],
    "postFilter": [],
    "preScore": [],
    "score": [],
    "reserve": [],
    "permit": [],
    "preBind": [],
    "bind": ["DefaultBinder"],
    "postBind": [],
}

# v1alpha1 → internal field renames, the hand-maintained conversion table
# (the analog of zz_generated.conversion.go). Keyed by plugin name; values map
# legacy camelCase field → current camelCase field.
_V1ALPHA1_ARG_RENAMES: Dict[str, Dict[str, str]] = {
    "Coscheduling": {"permitWaitingSeconds": "permitWaitingTimeSeconds",
                     "deniedPGExpirationSeconds": "deniedPGExpirationTimeSeconds"},
    "MultiSlice": {"dcnDomainScore": "sameDomainScore",
                   "dcnAdjacentScore": "adjacentDomainScore"},
}


@dataclass
class LeaderElectionConfig:
    """`leaderElection:` block (manifests/coscheduling/scheduler-config.yaml:3-4).

    The scheduler binary acts on it when — and only when — there is shared
    state to arbitrate: with ``--state-dir``, ``leaderElect: true`` runs
    active-standby election on a file lease living NEXT TO the WAL it
    guards (sched/ha.py: campaign before scheduling, renew on
    ``renewIntervalSeconds``, exit-on-lost-lease; takeover replays the WAL
    and the attach-time compaction rotates the WAL inode to fence a
    deposed writer). Without ``--state-dir`` the stanza is decoded but
    inert — two stateless in-process API servers share nothing a lease
    could arbitrate. The controller runner keeps its own Lease-object
    election (controllers/runner.py), matching the reference's split
    (cmd/controller/app/server.go:84-123)."""
    leader_elect: bool = False
    lease_duration_seconds: float = 15.0
    renew_interval_seconds: float = 5.0


@dataclass
class ClientConnectionConfig:
    """`clientConnection:` block; qps/burst mirror the controller API budget
    defaults (cmd/controller/app/options.go:43-44)."""
    qps: float = 5.0
    burst: int = 10
    kubeconfig: str = ""   # accepted for shape parity; in-memory server ignores it


@dataclass
class SchedulerConfiguration:
    """The decoded, internal-version configuration."""
    leader_election: LeaderElectionConfig = field(default_factory=LeaderElectionConfig)
    client_connection: ClientConnectionConfig = field(default_factory=ClientConnectionConfig)
    profiles: List[PluginProfile] = field(default_factory=list)

    def profile(self, scheduler_name: str = "tpusched") -> PluginProfile:
        for p in self.profiles:
            if p.scheduler_name == scheduler_name:
                return p
        raise ConfigError(f"no profile for scheduler {scheduler_name!r}")


def load_file(path: str) -> SchedulerConfiguration:
    with open(path) as f:
        return loads(f.read())


def loads(text: str) -> SchedulerConfiguration:
    raw = yaml.safe_load(text)
    if not isinstance(raw, dict):
        raise ConfigError("config must be a YAML mapping")
    return decode(raw)


def decode(raw: Dict[str, Any]) -> SchedulerConfiguration:
    version = raw.get("apiVersion")
    if version not in SUPPORTED_VERSIONS:
        raise ConfigError(
            f"unsupported apiVersion {version!r} (supported: {SUPPORTED_VERSIONS})")
    if raw.get("kind") != KIND:
        raise ConfigError(f"unsupported kind {raw.get('kind')!r} (want {KIND})")

    known_top = {"apiVersion", "kind", "leaderElection", "clientConnection",
                 "profiles", "podInitialBackoffSeconds", "podMaxBackoffSeconds"}
    for k in raw:
        if k not in known_top:
            raise ConfigError(f"unknown field {k!r} in {KIND}")

    cfg = SchedulerConfiguration()
    le = raw.get("leaderElection") or {}
    _check_fields("leaderElection", le,
                  {"leaderElect", "leaseDurationSeconds", "renewIntervalSeconds"})
    cfg.leader_election = LeaderElectionConfig(
        leader_elect=bool(le.get("leaderElect", False)),
        lease_duration_seconds=float(le.get("leaseDurationSeconds", 15.0)),
        renew_interval_seconds=float(le.get("renewIntervalSeconds", 5.0)))
    cc = raw.get("clientConnection") or {}
    _check_fields("clientConnection", cc, {"qps", "burst", "kubeconfig"})
    cfg.client_connection = ClientConnectionConfig(
        qps=float(cc.get("qps", 5.0)), burst=int(cc.get("burst", 10)),
        kubeconfig=str(cc.get("kubeconfig", "")))

    # upstream podInitialBackoffSeconds / podMaxBackoffSeconds (component
    # config level, scheduler defaults 1/10). Upstream shares one queue
    # across profiles; here each profile owns a queue, so the config-level
    # value is stamped onto every decoded profile. None = absent (use
    # defaults); explicit 0 is honored (retry immediately). Validation is
    # against the EFFECTIVE values, so a configured max below the 1 s
    # default initial is rejected, not silently exceeded.
    raw_init = raw.get("podInitialBackoffSeconds")
    raw_max = raw.get("podMaxBackoffSeconds")
    init_backoff = None if raw_init is None else float(raw_init)
    max_backoff = None if raw_max is None else float(raw_max)
    eff_init = 1.0 if init_backoff is None else init_backoff
    eff_max = 10.0 if max_backoff is None else max_backoff
    if eff_init < 0 or eff_max < 0:
        raise ConfigError("pod backoff seconds must be >= 0")
    if eff_max < eff_init:
        raise ConfigError(
            f"podMaxBackoffSeconds ({eff_max}) must be >= "
            f"podInitialBackoffSeconds ({eff_init})")

    profiles = raw.get("profiles")
    if not profiles:
        raise ConfigError("config must declare at least one profile")
    for p in profiles:
        prof = _decode_profile(p, version)
        prof.pod_initial_backoff_s = init_backoff
        prof.pod_max_backoff_s = max_backoff
        cfg.profiles.append(prof)
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        raise ConfigError(f"duplicate schedulerName in profiles: {names}")
    return cfg


def _decode_profile(raw: Dict[str, Any], version: str) -> PluginProfile:
    _check_fields("profile", raw, {"schedulerName", "plugins", "pluginConfig",
                                   "percentageOfNodesToScore",
                                   "dispatchShards", "bindPoolWorkers",
                                   "quotaSerializeDispatch",
                                   "nativeDispatch",
                                   "nativeDispatchDifferentialPeriod"})
    name = raw.get("schedulerName") or "tpusched"
    pct = int(raw.get("percentageOfNodesToScore") or 0)
    if not 0 <= pct <= 100:
        raise ConfigError(
            f"profile {name!r}: percentageOfNodesToScore must be 0-100, got {pct}")
    # sharded dispatch core (sched/shards.py): dispatchShards 1 = classic
    # single loop (default), 0 = auto-size, N = N pool-partitioned lanes
    # + a global lane; bindPoolWorkers 0 = auto (sized vs. shard count)
    try:
        shards = int(raw.get("dispatchShards", 1))
        bind_workers = int(raw.get("bindPoolWorkers", 0))
    except (TypeError, ValueError):
        raise ConfigError(
            f"profile {name!r}: dispatchShards/bindPoolWorkers must be "
            f"integers")
    if shards < 0 or bind_workers < 0:
        raise ConfigError(
            f"profile {name!r}: dispatchShards/bindPoolWorkers must be "
            f">= 0")
    # legacy wholesale quota serialization (ISSUE 14): the pre-quota-
    # protocol router behavior, kept as the bench baseline arm and an
    # operational escape hatch (doc/ops.md)
    quota_serialize = raw.get("quotaSerializeDispatch", False)
    if not isinstance(quota_serialize, bool):
        raise ConfigError(
            f"profile {name!r}: quotaSerializeDispatch must be a boolean, "
            f"got {quota_serialize!r}")
    # native batched dispatch (sched/nativedispatch.py, ISSUE 16)
    native_dispatch = raw.get("nativeDispatch", True)
    if not isinstance(native_dispatch, bool):
        raise ConfigError(
            f"profile {name!r}: nativeDispatch must be a boolean, got "
            f"{native_dispatch!r}")
    try:
        native_diff = int(raw.get("nativeDispatchDifferentialPeriod", 0))
    except (TypeError, ValueError):
        raise ConfigError(
            f"profile {name!r}: nativeDispatchDifferentialPeriod must be "
            f"an integer")
    if native_diff < 0:
        raise ConfigError(
            f"profile {name!r}: nativeDispatchDifferentialPeriod must be "
            f">= 0")
    plugins = raw.get("plugins") or {}
    for ep in plugins:
        if ep not in EXTENSION_POINTS:
            raise ConfigError(f"unknown extension point {ep!r}")

    wiring: Dict[str, List[Tuple[str, int]]] = {}
    for ep in EXTENSION_POINTS:
        wiring[ep] = _merge_extension_point(ep, plugins.get(ep) or {})

    qs = wiring["queueSort"]
    if len(qs) != 1:
        raise ConfigError(
            f"profile {name!r}: exactly one queueSort plugin required, got "
            f"{[n for n, _ in qs]}")

    args: Dict[str, Any] = {}
    for entry in raw.get("pluginConfig") or []:
        _check_fields("pluginConfig entry", entry, {"name", "args"})
        pname = entry.get("name")
        if pname not in ARGS_SCHEME:
            raise ConfigError(f"pluginConfig for unknown plugin {pname!r}")
        raw_args = dict(entry.get("args") or {})
        if version == V1ALPHA1:
            raw_args = _convert_v1alpha1_args(pname, raw_args)
        args[pname] = decode_plugin_args(pname, raw_args)

    return PluginProfile(
        scheduler_name=name,
        queue_sort=qs[0][0],
        pre_filter=[n for n, _ in wiring["preFilter"]],
        filter=[n for n, _ in wiring["filter"]],
        post_filter=[n for n, _ in wiring["postFilter"]],
        pre_score=[n for n, _ in wiring["preScore"]],
        score=list(wiring["score"]),
        reserve=[n for n, _ in wiring["reserve"]],
        permit=[n for n, _ in wiring["permit"]],
        pre_bind=[n for n, _ in wiring["preBind"]],
        bind=[n for n, _ in wiring["bind"]],
        post_bind=[n for n, _ in wiring["postBind"]],
        plugin_args=args,
        percentage_of_nodes_to_score=pct,
        dispatch_shards=shards,
        bind_pool_workers=bind_workers,
        quota_serialize_dispatch=quota_serialize,
        native_dispatch=native_dispatch,
        native_dispatch_differential_period=native_diff,
    )


def _merge_extension_point(ep: str, spec: Dict[str, Any]) -> List[Tuple[str, int]]:
    """Default plugins + disabled (with "*" wildcard) + enabled, in order."""
    _check_fields(ep, spec, {"enabled", "disabled"})
    current: List[Tuple[str, int]] = [(n, 1) for n in DEFAULT_PLUGINS[ep]]
    for d in spec.get("disabled") or []:
        _check_fields(f"{ep}.disabled entry", d, {"name"})
        dname = d.get("name")
        if dname == "*":
            current = []
        else:
            current = [(n, w) for n, w in current if n != dname]
    for e in spec.get("enabled") or []:
        _check_fields(f"{ep}.enabled entry", e, {"name", "weight"})
        ename = e.get("name")
        if not ename:
            raise ConfigError(f"{ep}.enabled entry missing name")
        if any(n == ename for n, _ in current):
            raise ConfigError(f"plugin {ename!r} enabled twice at {ep}")
        current.append((ename, int(e.get("weight", 1))))
    return current


def _convert_v1alpha1_args(plugin: str, raw_args: Dict[str, Any]) -> Dict[str, Any]:
    renames = _V1ALPHA1_ARG_RENAMES.get(plugin, {})
    out = {}
    for k, v in raw_args.items():
        new = renames.get(k, k)
        if new in out:
            raise ConfigError(
                f"{plugin}Args: both legacy {k!r} and current {new!r} set")
        out[new] = v
    return out


def encode(cfg: SchedulerConfiguration) -> Dict[str, Any]:
    """Internal → v1beta1 wire form (round-trip partner of decode; the
    analog of the conversion machinery's internal→versioned half). Extension
    points are emitted as explicit full wiring: defaults disabled with "*"
    and the profile's plugins enabled in order."""
    profiles = []
    for p in cfg.profiles:
        plugins: Dict[str, Any] = {}
        wiring = {
            "queueSort": [(p.queue_sort, 1)],
            "preFilter": [(n, 1) for n in p.pre_filter],
            "filter": [(n, 1) for n in p.filter],
            "postFilter": [(n, 1) for n in p.post_filter],
            "preScore": [(n, 1) for n in p.pre_score],
            "score": list(p.score),
            "reserve": [(n, 1) for n in p.reserve],
            "permit": [(n, 1) for n in p.permit],
            "preBind": [(n, 1) for n in p.pre_bind],
            "bind": [(n, 1) for n in p.bind],
            "postBind": [(n, 1) for n in p.post_bind],
        }
        for ep, entries in wiring.items():
            spec: Dict[str, Any] = {}
            if DEFAULT_PLUGINS[ep]:
                spec["disabled"] = [{"name": "*"}]
            if entries:
                if ep == "score":
                    spec["enabled"] = [{"name": n, "weight": w} for n, w in entries]
                else:
                    spec["enabled"] = [{"name": n} for n, _ in entries]
            if spec:
                plugins[ep] = spec
        prof: Dict[str, Any] = {"schedulerName": p.scheduler_name}
        if p.dispatch_shards != 1:
            prof["dispatchShards"] = p.dispatch_shards
        if p.quota_serialize_dispatch:
            prof["quotaSerializeDispatch"] = True
        if plugins:
            prof["plugins"] = plugins
        if p.plugin_args:
            prof["pluginConfig"] = [
                {"name": n, "args": _encode_args(a)}
                for n, a in sorted(p.plugin_args.items())]
        profiles.append(prof)
    out: Dict[str, Any] = {
        "apiVersion": V1BETA1,
        "kind": KIND,
        "leaderElection": {
            "leaderElect": cfg.leader_election.leader_elect,
            "leaseDurationSeconds": cfg.leader_election.lease_duration_seconds,
            "renewIntervalSeconds": cfg.leader_election.renew_interval_seconds,
        },
        "clientConnection": {
            "qps": cfg.client_connection.qps,
            "burst": cfg.client_connection.burst,
            "kubeconfig": cfg.client_connection.kubeconfig,
        },
        "profiles": profiles,
    }
    # config-level backoff (stamped identically on every profile at decode;
    # emit from the first — None = unset stays absent, explicit 0 survives)
    if cfg.profiles:
        first = cfg.profiles[0]
        if first.pod_initial_backoff_s is not None:
            out["podInitialBackoffSeconds"] = first.pod_initial_backoff_s
        if first.pod_max_backoff_s is not None:
            out["podMaxBackoffSeconds"] = first.pod_max_backoff_s
    return out


def _encode_args(args: Any) -> Dict[str, Any]:
    import dataclasses
    out = {}
    for f in dataclasses.fields(args):
        out[_snake_to_camel(f.name)] = getattr(args, f.name)
    return out


def _snake_to_camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _check_fields(ctx: str, raw: Dict[str, Any], allowed: set) -> None:
    if not isinstance(raw, dict):
        raise ConfigError(f"{ctx} must be a mapping, got {type(raw).__name__}")
    for k in raw:
        if k not in allowed:
            raise ConfigError(f"unknown field {k!r} in {ctx}")
