"""Internal plugin-args types with their defaults.

Mirrors /root/reference/apis/config/types.go:28-160 plus the hand-written
defaults in apis/config/v1beta3/defaults.go:29-160. The ``<PluginName>Args``
naming convention is load-bearing for YAML decoding (doc/develop.md:21 in the
reference) — ``scheme.py`` maps plugin name → args type by it.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

# Defaults (v1beta3/defaults.go:29-30,50; SURVEY §6 anchors).
DEFAULT_PERMIT_WAITING_TIME_SECONDS = 60
DEFAULT_DENIED_PG_EXPIRATION_TIME_SECONDS = 20
DEFAULT_TARGET_UTILIZATION_PERCENT = 40
DEFAULT_REQUESTS_MULTIPLIER = 1.5
DEFAULT_SAFE_VARIANCE_MARGIN = 1.0
DEFAULT_SAFE_VARIANCE_SENSITIVITY = 1.0
DEFAULT_METRICS_REFRESH_INTERVAL_SECONDS = 30
DEFAULT_METRICS_WINDOW_SECONDS = 60


@dataclass
class TpuSliceArgs:
    """Args for the TpuSlice plugin (FlexGPU successor; the reference plugin
    takes no args — these add deliberate knobs for the TPU resource model)."""
    # binpack: fewer free chips score higher (the reference's reverse
    # normalize, flex_gpu.go:172-176); spread: more free chips score higher.
    score_mode: str = "binpack"


@dataclass
class CoschedulingArgs:
    """types.go:28-39."""
    permit_waiting_time_seconds: int = DEFAULT_PERMIT_WAITING_TIME_SECONDS
    denied_pg_expiration_time_seconds: int = DEFAULT_DENIED_PG_EXPIRATION_TIME_SECONDS
    # PodGroup status patch coalescing window (ISSUE 14 satellite): a
    # gang's permit barrier releases all members at once, and a per-member
    # status patch turns every bind burst into per-bind API fan-out on the
    # binding hot path.  Partial-progress increments within this window
    # coalesce into ONE patch per gang; quorum completion always flushes
    # INLINE (the PodGroup-to-Bound north-star observation keeps its exact
    # clock).  0 = patch per bind (the pre-14 behavior; deterministic
    # replay uses it so patch timing never races the lockstep barrier).
    pg_status_flush_seconds: float = 0.05


@dataclass
class ElasticQuotaArgs:
    """CapacityScheduling needs no args in the reference; placeholder."""
    pass


@dataclass
class TopologyMatchArgs:
    """types.go:144-152 (NodeResourceTopologyMatchArgs): scoring strategy for
    the torus zones."""
    scoring_strategy: str = "LeastAllocated"   # LeastAllocated|MostAllocated|BalancedAllocation
    # resource weights for the strategy (cpu/mem weight 1 default in the
    # reference; here chips).
    resource_weights: dict = field(default_factory=lambda: {"google.com/tpu": 1})
    # blend between the TPU-first corner-packing constraint score (fewest
    # surviving placements wins — anti-fragmentation) and the NRT-style
    # strategy score over the pool zone. 0.7 keeps packing dominant; 0.0
    # reproduces the reference's pure-strategy zone scoring.
    packing_weight: float = 0.7
    # slice preemption (PostFilter): when a slice-shaped gang has no feasible
    # placement, evict the cheapest eligible victim WINDOW (whole torus
    # block) — single-node preemption can never free a contiguous slice.
    # Off by default; the full-stack profile enables it.
    enable_slice_preemption: bool = False
    # one eviction burst per gang within this window — must outlast victim
    # graceful termination (k8s default 30s) or a sibling's failure mid-drain
    # evicts a second window
    slice_preemption_drain_seconds: float = 60.0
    # Window-index differential oracle (ISSUE 13): every Nth pool sweep the
    # index serves is re-run through the Python full-recompute path and the
    # two answers (survivors, membership, assigned, utilization) must be
    # identical — a mismatch counts into
    # tpusched_torus_index_differential_mismatches_total, quarantines the
    # pool's plane and reseeds it from the cache.  0 disables (production
    # default); the TPUSCHED_INDEX_DIFFERENTIAL env overrides (the
    # replay-smoke lockstep gate runs with it at 1 = every sweep).
    index_differential_period: int = 0

    def validate(self) -> None:
        if not 0.0 <= self.packing_weight <= 1.0:
            raise ValueError(
                f"packingWeight must be in [0, 1], got {self.packing_weight}")
        if self.index_differential_period < 0:
            raise ValueError(
                f"indexDifferentialPeriod must be >= 0, got "
                f"{self.index_differential_period}")
        if self.scoring_strategy not in ("LeastAllocated", "MostAllocated",
                                         "BalancedAllocation"):
            raise ValueError(
                f"unknown scoringStrategy {self.scoring_strategy!r}")
        if self.slice_preemption_drain_seconds <= 0:
            raise ValueError("slicePreemptionDrainSeconds must be positive")


@dataclass
class MultiSliceArgs:
    """DCN-aware cross-slice scoring and set-level atomic admission (new; no
    reference analog)."""
    # score weight for sharing a DCN domain with already-placed sibling slices
    same_domain_score: int = 100
    adjacent_domain_score: int = 50
    # Max seconds a member gang waits at the permit barrier for the REST of
    # its set (gangs wait for their own quorum under the Coscheduling
    # timeout; this one is the budget for sibling slices to land). Applies
    # only to PodGroups declaring multislice_set_size > 1.
    set_schedule_timeout_seconds: int = 120
    # How long a torn-down set stays denied (fast PreFilter rejection)
    # before members may retry. Window runs from the first denial.
    denied_set_expiration_time_seconds: int = 20
    # "" (default) = DCN proximity is a preference only. "same-domain" /
    # "same-zone" = hard Filter constraint: once any sibling slice is
    # placed, later slices may only land inside its DCN domain / zone.
    hard_domain_policy: str = ""


@dataclass
class NodeResourcesAllocatableArgs:
    """types.go:50-60: weighted allocatable scoring, Least or Most mode.
    Default weights: 1<<20 per cpu millicore ≈ 1 per memory byte
    (resource_allocation.go:38)."""
    mode: str = "Least"   # Least | Most
    resources: List[dict] = field(default_factory=lambda: [
        {"name": "cpu", "weight": 1 << 20},
        {"name": "memory", "weight": 1},
    ])


@dataclass
class TargetLoadPackingArgs:
    """types.go:88-104."""
    target_utilization: int = DEFAULT_TARGET_UTILIZATION_PERCENT
    default_requests_cpu_millis: int = 1000      # 1-core default
    default_requests_multiplier: float = DEFAULT_REQUESTS_MULTIPLIER
    watcher_address: str = ""                    # empty ⇒ in-process provider
    metrics_refresh_interval_seconds: int = DEFAULT_METRICS_REFRESH_INTERVAL_SECONDS


@dataclass
class LoadVariationRiskBalancingArgs:
    """types.go:106-120."""
    safe_variance_margin: float = DEFAULT_SAFE_VARIANCE_MARGIN
    safe_variance_sensitivity: float = DEFAULT_SAFE_VARIANCE_SENSITIVITY
    watcher_address: str = ""
    metrics_refresh_interval_seconds: int = DEFAULT_METRICS_REFRESH_INTERVAL_SECONDS


@dataclass
class PreemptionTolerationArgs:
    """types.go:154-160: same knobs as DefaultPreemption."""
    min_candidate_nodes_percentage: int = 10
    min_candidate_nodes_absolute: int = 100
