"""TTL cache — replacement for the vendored patrickmn/go-cache the reference
uses for denied/permitted PodGroup backoff caches
(/root/reference/pkg/coscheduling/core/core.go:79-81,103-104)."""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


class TTLCache:
    def __init__(self, default_ttl: float, clock=None,
                 arm: Optional[Callable[[float], None]] = None):
        """``clock`` is the injected now-read (the scheduler passes its
        handle clock's ``now``; None = real monotonic).  ``arm`` — called
        with each entry's absolute expiry — lets a discrete-event clock
        (util/clock.VirtualClock) learn when a window lapses, so
        deterministic replay can jump straight to the lapse instead of
        zeroing the TTL (the denial-window gate this cache exists
        for)."""
        self._ttl = default_ttl
        self._clock = clock or time.monotonic
        self._arm = arm
        self._lock = threading.Lock()
        self._items: Dict[str, Tuple[Any, float]] = {}

    def set(self, key: str, value: Any = True, ttl: Optional[float] = None) -> None:
        exp = self._clock() + (self._ttl if ttl is None else ttl)
        with self._lock:
            self._items[key] = (value, exp)
        if self._arm is not None:
            self._arm(exp)

    def add(self, key: str, value: Any = True,
            ttl: Optional[float] = None) -> bool:
        """Set ONLY if absent (or expired); returns whether it was added —
        go-cache Add semantics (cache.go:92-100). The distinction is
        load-bearing for the denied-PodGroup cache: repeat denials must NOT
        extend the window, or an event-driven retry storm pins a gang in the
        denied state forever (each retry would refresh the TTL it is itself
        rejected by)."""
        now = self._clock()
        exp = now + (self._ttl if ttl is None else ttl)
        with self._lock:
            item = self._items.get(key)
            if item is not None and item[1] >= now:
                return False
            self._items[key] = (value, exp)
        if self._arm is not None:
            self._arm(exp)
        return True

    def remaining(self, key: str) -> float:
        """Seconds until `key` expires; 0.0 if absent or already expired.
        Lets rejection paths tell the scheduler exactly when a retry can
        succeed (Status.with_retry_after)."""
        now = self._clock()
        with self._lock:
            item = self._items.get(key)
            if item is None or item[1] < now:
                return 0.0
            return item[1] - now

    def get(self, key: str):
        """Returns (value, True) if present and fresh, else (None, False)."""
        now = self._clock()
        with self._lock:
            item = self._items.get(key)
            if item is None:
                return None, False
            value, exp = item
            if exp < now:
                del self._items[key]
                return None, False
            return value, True

    def __contains__(self, key: str) -> bool:
        return self.get(key)[1]

    def delete(self, key: str) -> None:
        with self._lock:
            self._items.pop(key, None)

    def items(self):
        """(key, value) pairs still fresh at call time."""
        now = self._clock()
        with self._lock:
            return [(k, v) for k, (v, exp) in self._items.items()
                    if exp >= now]

    def purge(self) -> None:
        now = self._clock()
        with self._lock:
            for k in [k for k, (_, exp) in self._items.items() if exp < now]:
                del self._items[k]
