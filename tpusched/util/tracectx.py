"""Thread-local trace-id correlation context.

The flight recorder (tpusched/trace) activates a cycle trace id here for the
duration of a scheduling/binding cycle; klog lines and API-server Events
emitted inside the cycle pick it up so an operator can jump from a
``FailedScheduling`` event or a log line straight to the matching
``/debug/flightrecorder`` entry.

Deliberately dependency-free (stdlib only): both ``util.klog`` and
``tpusched.trace`` import it, so it must sit below both.
"""
from __future__ import annotations

import threading

_tls = threading.local()


def set(trace_id: str) -> str:  # noqa: A001 — klog-style tiny API
    """Install ``trace_id`` as the current thread's correlation id and
    return the previous one (restore it when the cycle leaves the thread)."""
    prev = getattr(_tls, "id", "")
    _tls.id = trace_id
    return prev


def get() -> str:
    """Current thread's trace id, or '' outside any traced cycle."""
    return getattr(_tls, "id", "")
