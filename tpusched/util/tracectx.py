"""Thread-local trace-id correlation + cross-thread attribution context.

The flight recorder (tpusched/trace) activates a cycle trace id here for the
duration of a scheduling/binding cycle; klog lines and API-server Events
emitted inside the cycle pick it up so an operator can jump from a
``FailedScheduling`` event or a log line straight to the matching
``/debug/flightrecorder`` entry.

The second half is the *attribution context* the sampling profiler
(tpusched/obs/profiler.py) reads: each scheduler-owned thread publishes
"what am I doing right now" — the active framework extension point, the
plugin whose body is running, and the lock it is blocked acquiring — into a
slot the sampler thread can read WITHOUT stopping the world.  The write
path is deliberately the cheapest thing Python can do (one thread-local
getattr plus a list-item store, both atomic under the GIL); the sampler
pays the synchronization cost by copying, so the hot scheduling path never
takes a lock to stay attributable.

Deliberately dependency-free (stdlib only): ``util.klog``,
``util.locking`` and ``tpusched.trace`` import it, so it must sit below
all three.
"""
from __future__ import annotations

import threading
from typing import Dict, Tuple

_tls = threading.local()

# thread ident → [extension_point, plugin, lock] — the per-thread slot is a
# mutable list so the hot path stores into an already-published object and
# the sampler reads whatever triple is current.  Keys are pruned by the
# profiler against the live sys._current_frames() set (ident reuse after a
# thread dies merely re-purposes a slot, which is fine for sampling).
_attrs: Dict[int, list] = {}

_POINT, _PLUGIN, _LOCK = 0, 1, 2


def set(trace_id: str) -> str:  # noqa: A001 — klog-style tiny API
    """Install ``trace_id`` as the current thread's correlation id and
    return the previous one (restore it when the cycle leaves the thread)."""
    prev = getattr(_tls, "id", "")
    _tls.id = trace_id
    return prev


def get() -> str:
    """Current thread's trace id, or '' outside any traced cycle."""
    return getattr(_tls, "id", "")


# -- attribution context (read by the sampling profiler) ----------------------

def _slot() -> list:
    s = getattr(_tls, "attr", None)
    if s is None:
        s = _tls.attr = ["", "", ""]
    # re-assert registration on EVERY call (one GIL-atomic dict store of an
    # existing key): the profiler's prune races threads that started after
    # its frames snapshot — a pruned-but-live thread must re-register at
    # its next write, or its samples stay unattributed for its lifetime
    _attrs[threading.get_ident()] = s
    return s


def set_point(point: str) -> str:
    """Publish the framework extension point this thread is executing
    (``''`` outside any point).  Returns the previous value so nested /
    re-entrant sites restore instead of clearing."""
    s = _slot()
    prev = s[_POINT]
    s[_POINT] = point
    return prev


def set_plugin(plugin: str) -> str:
    """Publish the plugin whose body this thread is executing."""
    s = _slot()
    prev = s[_PLUGIN]
    s[_PLUGIN] = plugin
    return prev


def set_lock(name: str) -> str:
    """Publish the lock this thread is currently BLOCKED acquiring
    (GuardedLock telemetry mode sets it around the contended-acquire slow
    path only — an uncontended acquire never writes here)."""
    s = _slot()
    prev = s[_LOCK]
    s[_LOCK] = name
    return prev


def attribution(ident: int) -> Tuple[str, str, str]:
    """(extension_point, plugin, lock) last published by thread ``ident``,
    or empty strings.  Sampler-side: tolerates the slot mutating while read
    (each element is an atomic load; a torn triple is one misattributed
    sample, not an error)."""
    s = _attrs.get(ident)
    if s is None:
        return ("", "", "")
    return (s[_POINT], s[_PLUGIN], s[_LOCK])


def prune_attributions(live_idents) -> None:
    """Drop slots for threads no longer alive (profiler housekeeping —
    called with the ident set of sys._current_frames())."""
    for ident in list(_attrs):
        if ident not in live_idents:
            _attrs.pop(ident, None)
