"""Pod equivalence keys for the scheduling equivalence-class cache.

Two pods are *equivalent* when every Filter-relevant input the scheduler
reads off the pod itself is identical: same namespace, labels (which carry
gang membership), scheduling constraints (selector/name/tolerations/
priority) and per-container resource shape. Gang members stamped from one
template are the motivating class — a 256-pod slice gang is 256 equivalent
pods — but any identical singletons form one too.

The key deliberately covers MORE than the in-tree plugins read today
(e.g. init containers, overhead): an over-precise key only costs cache
misses, an under-precise one would alias pods with different feasibility.
Plugin state that lives OUTSIDE the pod (PodGroup specs, topology CRs,
denial windows, claims) is covered separately by per-plugin fingerprints
(fwk.interfaces.EquivalenceAware), and cluster state by the scheduler
cache's mutation cursor — the key only has to pin the pod's own half.
"""
from __future__ import annotations

from typing import Hashable


def _container_fp(containers) -> tuple:
    return tuple((tuple(sorted(c.requests.items())),
                  tuple(sorted(c.limits.items())))
                 for c in containers)


def equivalence_key(pod) -> Hashable:
    """Hashable equivalence-class key for ``pod``. Total: every pod has a
    key (per-plugin fingerprints, not this key, carry the veto power).

    Memoized per pod object (same discipline as podutil's request memo:
    pod specs are replaced wholesale on update, never mutated in place).
    Annotations are excluded on purpose: no Filter/PreFilter plugin reads
    them, and Reserve writes device annotations onto the assumed DEEPCOPY,
    not the queued object.
    """
    cached = getattr(pod, "_equiv_key_memo", None)
    if cached is not None:
        return cached
    spec = pod.spec
    key = (
        pod.meta.namespace,
        tuple(sorted(pod.meta.labels.items())),
        spec.scheduler_name,
        spec.priority,
        spec.priority_class_name,
        spec.node_name,
        tuple(sorted(spec.node_selector.items())),
        tuple((t.key, t.operator, t.value, t.effect)
              for t in spec.tolerations),
        _container_fp(spec.containers),
        _container_fp(spec.init_containers),
        tuple(sorted(spec.overhead.items())),
    )
    try:
        object.__setattr__(pod, "_equiv_key_memo", key)
    except AttributeError:
        pass
    return key
