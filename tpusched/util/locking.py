"""Lock discipline: declared guards + a debug-mode lock-order recorder.

The sharded scheduler core (ROADMAP item 1) moves the hot path from
one-cycle-at-a-time to concurrent per-pool dispatch over shared
cache/queue/telemetry state.  That regime needs the locking conventions this
repo has kept by habit — "mutate ``_pods`` only under ``_lock``", "never
acquire the queue lock while holding the cache lock" — turned into declared,
machine-checked invariants.  Two halves:

static
    ``@guarded_by("_lock", "_pods", ...)`` declares which lock guards which
    fields.  tpulint's ``lock-discipline`` rule (tpusched/analysis) reads the
    declaration and verifies every mutation of a guarded field happens inside
    ``with self._lock:`` or in a ``*_locked``-suffixed method (the repo's
    caller-holds-the-lock convention).

runtime (debug mode only)
    ``GuardedLock`` returns an *instrumented* lock that feeds a global
    acquisition-order recorder: a per-thread stack of held locks builds the
    order graph (edges by lock NAME, so every Cache instance contributes to
    one "sched.Cache" node), and a new edge that closes a cycle — a potential
    deadlock — is recorded (and optionally raised) the moment it is first
    observed, long before any schedule actually interleaves into the hang.
    ``@guarded_by`` additionally wraps the declared container fields in
    mutation-asserting proxies and installs a ``__setattr__`` checker, so an
    unguarded mutation of declared state is caught at the mutation site.
    The chaos soaks (testing/chaos.py) run with this enabled and assert zero
    cycles and zero unguarded mutations across their 5k-cycle runs.

Zero overhead when debug mode is off: ``GuardedLock(...)`` returns a plain
``threading.RLock``/``Lock`` and ``@guarded_by`` only records metadata on the
class — no wrapper, no per-operation check, no ``__setattr__`` override
(instances get their class swapped to an instrumented subclass only when
constructed in debug mode).  Enable with ``set_debug(True)`` (or
``TPUSCHED_LOCK_DEBUG=1``) *before* constructing the objects to observe:
instrumentation is decided at construction time, which is what keeps the
off path free.

A fourth hook, layered ON TOP of debug mode: the INTERLEAVING EXPLORER
(tpusched/verify).  ``set_verify_hook(runtime)`` installs a process-global
observer that debug-mode locks consult at every acquisition boundary —
before a non-reentrant acquire, after a full release, across a Condition
``wait()`` hand-off, and at every guarded-container mutation.  The explorer
uses those callbacks to take cooperative control of scheduler-owned
threads and drive them through chosen interleavings deterministically;
with no hook installed (the default, including all of debug mode's normal
uses) the cost is one module-global ``is None`` test per boundary.
``GuardedCondition`` is the Condition flavor whose wait/notify the
explorer can model — off the explorer it behaves exactly like
``threading.Condition`` over the same lock.

A third, independent mode: CONTENTION TELEMETRY (``set_telemetry(True)`` /
``TPUSCHED_LOCK_TELEMETRY=1``).  Distinct from debug mode — debug answers
"is the lock *discipline* sound" in tests/soaks and may be arbitrarily
strict; telemetry answers "where does wall time go under locks" in a
running scheduler and must be cheap enough to leave on while profiling.
In telemetry mode ``GuardedLock`` returns a ``_TelemetryLock`` that
records contended-acquire waits and long holds into the
``tpusched_lock_wait_seconds`` / ``tpusched_lock_hold_seconds`` histogram
families (labeled by lock name) and publishes "blocked on <lock>" into the
profiler's attribution context (util/tracectx) for the duration of a
contended acquire.  Like debug mode, the choice is made at construction
time, and with BOTH modes off the factory returns the plain stdlib lock —
the structural zero-overhead contract is pinned in tests/test_locking.py.
Debug wins when both are requested (the order recorder subsumes the
telemetry use case in soaks).
"""
from __future__ import annotations

import os
import threading
import time as _time
from typing import Any, Dict, List, Optional, Set, Tuple

from . import tracectx

__all__ = ["GuardedLock", "GuardedCondition", "guarded_by",
           "thread_confined", "set_debug",
           "debug_enabled", "set_telemetry", "telemetry_enabled",
           "recorder", "LockOrderError",
           "GuardedStateError", "LockOrderRecorder",
           "set_verify_hook", "verify_hook", "verify_point"]

_DEBUG = os.environ.get("TPUSCHED_LOCK_DEBUG", "") not in ("", "0", "false")
_TELEMETRY = os.environ.get("TPUSCHED_LOCK_TELEMETRY", "") \
    not in ("", "0", "false")
_MAX_VIOLATIONS = 256          # bounded: a hot unguarded site must not OOM
# holds shorter than this are not observed (a healthy hot path holds the
# cache lock for ~µs thousands of times per second — recording every one
# would cost more than the holds themselves and bury the pathological tail)
LONG_HOLD_THRESHOLD_S = 0.001


def set_debug(on: bool) -> bool:
    """Toggle debug-mode instrumentation for locks/classes constructed
    AFTER this call.  Returns the previous value (restore in finally)."""
    global _DEBUG
    prev, _DEBUG = _DEBUG, bool(on)
    return prev


def debug_enabled() -> bool:
    return _DEBUG


def set_telemetry(on: bool) -> bool:
    """Toggle contention-telemetry mode for locks constructed AFTER this
    call (construction-time decision, same contract as ``set_debug``).
    Returns the previous value (restore in finally)."""
    global _TELEMETRY
    prev, _TELEMETRY = _TELEMETRY, bool(on)
    return prev


def telemetry_enabled() -> bool:
    return _TELEMETRY


# -- interleaving-explorer hook (tpusched/verify) ------------------------------
#
# The explorer registers a runtime object here; debug-mode locks report
# their acquisition boundaries to it so it can suspend/resume scheduler-
# owned threads at exactly the points where interleavings differ.  The
# protocol (all methods must tolerate calls from threads the explorer does
# not manage, and return immediately for them):
#
#   on_acquire(name, ident, blocking) -> bool   before a non-reentrant
#       acquire; False means "would block and blocking=False" — the caller
#       returns False without touching the real lock.
#   on_release(name, ident)                     after a FULL real release.
#   on_cond_wait(cond, timeout) -> bool | None  a GuardedCondition wait;
#       None means "not handled — do a real wait".
#   on_cond_notify(cond, n)                     before a real notify; n is
#       the wake count (None = notify_all).
#   on_point(label)                             explicit yield point
#       (guarded-container mutations, _BindingPool boundaries, ...).

_VERIFY_HOOK = None


def set_verify_hook(hook):
    """Install (or with None, remove) the interleaving-explorer hook for
    ALL debug-mode locks in the process.  Returns the previous hook
    (restore in finally).  Only the explorer should call this."""
    global _VERIFY_HOOK
    prev, _VERIFY_HOOK = _VERIFY_HOOK, hook
    return prev


def verify_hook():
    return _VERIFY_HOOK


def verify_point(label: str) -> None:
    """Explicit explorer yield point for boundaries no GuardedLock marks
    (e.g. the binding pool's plain ``queue.Queue`` hand-off).  One global
    read + ``is None`` test when no explorer is active."""
    h = _VERIFY_HOOK
    if h is not None:
        h.on_point(label)


class LockOrderError(RuntimeError):
    """A lock acquisition closed a cycle in the acquisition-order graph."""


class GuardedStateError(RuntimeError):
    """Guarded state was mutated without its declared lock held."""


class LockOrderRecorder:
    """Global acquisition-order graph + guarded-mutation violation log.

    Nodes are lock NAMES (``sched.Cache``), not instances: the invariant
    worth enforcing is the class-level order policy — if thread A ever
    acquires Cache→Queue and thread B Queue→Cache, the pair can deadlock no
    matter which instances are involved.  Reentrant reacquisition of the
    SAME instance is not an edge; nesting two *distinct* instances of one
    name is a real self-edge (classic AB/BA risk between siblings) and is
    reported as a cycle.

    Its own synchronization uses a raw ``threading.Lock`` on purpose — the
    recorder must never feed itself.
    """

    def __init__(self, strict: bool = False):
        self.strict = strict
        self._mu = threading.Lock()
        self._tls = threading.local()
        # approximate (unsynchronized increment — a liveness witness for
        # "instrumentation was actually on", not an exact statistic)
        self.acquires = 0
        # name -> set of names acquired while holding it, with the first
        # witness (thread, holder name chain) kept for the report
        self._edges: Dict[str, Set[str]] = {}
        self._edge_witness: Dict[Tuple[str, str], str] = {}
        self._cycles: List[str] = []
        self._guard_violations: List[str] = []
        self._order_violations: List[str] = []

    # -- per-thread held stack -----------------------------------------------

    def _stack(self) -> List[Tuple[str, int]]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_acquire(self, name: str, ident: int) -> None:
        self.acquires += 1
        stack = self._stack()
        if stack:
            top_name, top_ident = stack[-1]
            if top_ident != ident:      # reentrancy on the same instance is
                self._add_edge(top_name, name)   # not an ordering fact
        stack.append((name, ident))

    def on_release(self, name: str, ident: int) -> None:
        stack = self._stack()
        # released out of LIFO order is legal (lock handoff patterns);
        # remove by identity, newest first
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == ident:
                del stack[i]
                return

    # -- graph ---------------------------------------------------------------

    def _add_edge(self, frm: str, to: str) -> None:
        with self._mu:
            outs = self._edges.setdefault(frm, set())
            if to in outs:
                return                 # known edge: nothing new to check
            outs.add(to)
            t = threading.current_thread().name
            self._edge_witness[(frm, to)] = t
            path = self._find_path(to, frm)
            if path is None:
                return
            cyc = " -> ".join([frm] + path)
            msg = (f"lock-order cycle: {cyc} (closing edge {frm} -> {to} "
                   f"first seen on thread {t!r})")
            if len(self._cycles) < _MAX_VIOLATIONS:
                self._cycles.append(msg)
        if self.strict:
            raise LockOrderError(msg)

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS src ↝ dst over the edge set; caller holds ``_mu``."""
        seen = {src}
        stack: List[Tuple[str, List[str]]] = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- guarded-state assertions ---------------------------------------------

    def guard_violation(self, msg: str) -> None:
        with self._mu:
            if len(self._guard_violations) < _MAX_VIOLATIONS:
                self._guard_violations.append(msg)
        if self.strict:
            raise GuardedStateError(msg)

    def order_violation(self, msg: str) -> None:
        with self._mu:
            if len(self._order_violations) < _MAX_VIOLATIONS:
                self._order_violations.append(msg)
        if self.strict:
            raise GuardedStateError(msg)

    # -- report ---------------------------------------------------------------

    def edges(self) -> Dict[str, Set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def cycles(self) -> List[str]:
        with self._mu:
            return list(self._cycles)

    def violations(self) -> List[str]:
        """All recorded discipline violations (cycles + unguarded
        mutations + thread-confinement breaks)."""
        with self._mu:
            return (list(self._cycles) + list(self._guard_violations)
                    + list(self._order_violations))

    def reset(self) -> None:
        self.acquires = 0
        with self._mu:
            self._edges.clear()
            self._edge_witness.clear()
            self._cycles.clear()
            self._guard_violations.clear()
            self._order_violations.clear()

    def report(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "acquires": self.acquires,
                "edges": sorted(f"{a} -> {b}"
                                for a, outs in self._edges.items()
                                for b in outs),
                "cycles": list(self._cycles),
                "guard_violations": list(self._guard_violations),
                "order_violations": list(self._order_violations),
            }


_RECORDER = LockOrderRecorder()


def recorder() -> LockOrderRecorder:
    return _RECORDER


class _InstrumentedLock:
    """Debug-mode lock: a (R)Lock that reports to the order recorder and
    knows its owner, so guarded-state proxies can ask ``is_held()``.
    Implements the private Condition protocol (``_is_owned`` /
    ``_release_save`` / ``_acquire_restore``) so
    ``threading.Condition(GuardedLock(...))`` keeps the recorder's
    per-thread stack exact across ``wait()``."""

    __slots__ = ("name", "_inner", "_reentrant", "_owner", "_count", "_rec")

    def __init__(self, name: str, reentrant: bool,
                 rec: Optional[LockOrderRecorder] = None):
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._owner: Optional[int] = None
        self._count = 0
        self._rec = rec if rec is not None else _RECORDER

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            self._inner.acquire()
            self._count += 1
            return True                 # reentrant: no recorder event
        h = _VERIFY_HOOK
        if h is not None and not h.on_acquire(self.name, id(self), blocking):
            return False                # explorer: modeled try-acquire miss
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._count = 1
            self._rec.on_acquire(self.name, id(self))
        return got

    def release(self) -> None:
        if self._owner != threading.get_ident():
            self._rec.order_violation(
                f"{self.name}: released by non-owner thread "
                f"{threading.current_thread().name!r}")
        self._count -= 1
        full = self._count <= 0
        if full:
            self._owner = None
            self._rec.on_release(self.name, id(self))
        self._inner.release()
        if full:
            h = _VERIFY_HOOK
            if h is not None:
                h.on_release(self.name, id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self) -> bool:
        return self._owner is not None

    def is_held(self) -> bool:
        return self._owner == threading.get_ident()

    # Condition protocol ------------------------------------------------------

    def _is_owned(self) -> bool:
        return self.is_held()

    def _release_save(self):
        """Full release for Condition.wait: unwind reentrancy in one step."""
        count, self._count = self._count, 0
        self._owner = None
        self._rec.on_release(self.name, id(self))
        for _ in range(count - 1):
            self._inner.release()
        self._inner.release()
        h = _VERIFY_HOOK
        if h is not None:
            h.on_release(self.name, id(self))
        return count

    def _acquire_restore(self, count) -> None:
        h = _VERIFY_HOOK
        if h is not None:
            h.on_acquire(self.name, id(self), True)
        for _ in range(count):
            self._inner.acquire()
        self._owner = threading.get_ident()
        self._count = count
        self._rec.on_acquire(self.name, id(self))


class _TelemetryLock:
    """Contention-telemetry lock (telemetry mode): a (R)Lock that records
    contended-acquire waits and long holds into the ``tpusched_lock_*``
    histogram families, and publishes "blocked on <name>" into the
    profiler's attribution context while it waits.

    Cost model: the uncontended path pays one extra non-blocking
    ``acquire(False)`` try plus a ``perf_counter`` read — the slow
    (contended) path is the only one that touches a histogram, so a
    healthy lock costs nanoseconds and a fought-over one tells on itself.
    Implements the private Condition protocol so
    ``threading.Condition(GuardedLock(...))`` keeps hold accounting exact
    across ``wait()`` (the wait itself is NOT a hold)."""

    __slots__ = ("name", "_inner", "_reentrant", "_owner", "_count",
                 "_hold_t0", "_wait_hist", "_hold_hist")

    def __init__(self, name: str, reentrant: bool):
        from .metrics import lock_hold_seconds, lock_wait_seconds
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._owner: Optional[int] = None
        self._count = 0
        self._hold_t0 = 0.0
        self._wait_hist = lock_wait_seconds.with_labels(name)
        self._hold_hist = lock_hold_seconds.with_labels(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            self._inner.acquire()
            self._count += 1
            return True
        if self._inner.acquire(False):          # uncontended fast path
            got = True
        elif not blocking:
            return False
        else:
            t0 = _time.perf_counter()
            prev = tracectx.set_lock(self.name)
            try:
                got = self._inner.acquire(True, timeout)
            finally:
                tracectx.set_lock(prev)
            if got:
                self._wait_hist.observe(_time.perf_counter() - t0)
        if got:
            self._owner = me
            self._count = 1
            self._hold_t0 = _time.perf_counter()
        return got

    def release(self) -> None:
        if self._count <= 1:
            held = _time.perf_counter() - self._hold_t0
            self._owner = None
            self._count = 0
            if held >= LONG_HOLD_THRESHOLD_S:
                self._hold_hist.observe(held)
        else:
            self._count -= 1
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self) -> bool:
        return self._owner is not None

    def is_held(self) -> bool:
        return self._owner == threading.get_ident()

    # Condition protocol ------------------------------------------------------

    def _is_owned(self) -> bool:
        return self.is_held()

    def _release_save(self):
        """Full release for Condition.wait: the hold ends here (the wait
        is queue idle time, not a hold — charging it would make every
        consumer pop() look like a pathological holder)."""
        held = _time.perf_counter() - self._hold_t0
        count, self._count = self._count, 0
        self._owner = None
        if held >= LONG_HOLD_THRESHOLD_S:
            self._hold_hist.observe(held)
        for _ in range(count - 1):
            self._inner.release()
        self._inner.release()
        return count

    def _acquire_restore(self, count) -> None:
        if not self._inner.acquire(False):      # contended reacquire after
            t0 = _time.perf_counter()           # notify: a real wait
            prev = tracectx.set_lock(self.name)
            try:
                self._inner.acquire()
            finally:
                tracectx.set_lock(prev)
            self._wait_hist.observe(_time.perf_counter() - t0)
        for _ in range(count - 1):
            self._inner.acquire()
        self._owner = threading.get_ident()
        self._count = count
        self._hold_t0 = _time.perf_counter()


def GuardedLock(name: str, reentrant: bool = True):  # noqa: N802 — ctor-like
    """A named lock participating in lock discipline.  Debug and telemetry
    modes off (the default): a plain ``threading.RLock``/``Lock`` — zero
    overhead, byte-identical hot path.  Debug mode on: an instrumented lock
    feeding the acquisition-order recorder and answering ownership queries
    for the guarded-state proxies.  Telemetry mode on (and debug off): a
    contention-telemetry lock feeding the ``tpusched_lock_*`` histograms."""
    if _DEBUG:
        return _InstrumentedLock(name, reentrant)
    if _TELEMETRY:
        return _TelemetryLock(name, reentrant)
    return threading.RLock() if reentrant else threading.Lock()


class GuardedCondition(threading.Condition):
    """``threading.Condition`` whose wait/notify the interleaving explorer
    (tpusched/verify) can take over.  With no explorer hook installed —
    production, debug soaks, telemetry — every method defers straight to
    the stdlib implementation over the same (possibly instrumented) lock;
    the only added cost is one module-global ``is None`` test.

    Under the explorer, ``wait()`` becomes a MODELED wait: the waiter is
    registered in the explorer's wakeup model *before* the lock is
    released (the same atomicity the real Condition provides, so a modeled
    notify cannot be lost), the thread parks at a scheduling decision
    point instead of a real waiter lock, and the re-acquire goes back
    through the instrumented lock's ``_acquire_restore`` — which is
    exactly what keeps the recorder's per-thread lock-stack accounting
    intact across the release → notify → re-acquire hand-off."""

    def wait(self, timeout: Optional[float] = None):
        h = _VERIFY_HOOK
        if h is not None:
            handled = h.on_cond_wait(self, timeout)
            if handled is not None:
                return handled
        return super().wait(timeout)

    def notify(self, n: int = 1) -> None:
        h = _VERIFY_HOOK
        if h is not None:
            h.on_cond_notify(self, n)
        super().notify(n)

    def notify_all(self) -> None:
        h = _VERIFY_HOOK
        if h is not None:
            h.on_cond_notify(self, None)
        super().notify_all()


# =============================================================================
# Guarded-state runtime assertions (@guarded_by debug half)
# =============================================================================


def _lock_is_held(lock) -> bool:
    """Best-effort 'does the CURRENT thread hold this?' across the lock
    flavors a guard can name: instrumented, RLock, Condition (recurse on
    its inner lock), plain Lock (ownerless — ``locked()`` is the best
    available witness)."""
    inner = getattr(lock, "_lock", lock)     # Condition → its lock
    held = getattr(inner, "is_held", None)
    if held is not None:
        return held()
    owned = getattr(inner, "_is_owned", None)
    if owned is not None:
        return owned()
    return inner.locked()


def _check(owner_ref, field: str, op: str) -> None:
    owner, lock_attr = owner_ref
    h = _VERIFY_HOOK
    if h is not None:
        # every guarded-container mutation is an explorer yield point —
        # the label keys dependence, so two threads mutating the same
        # declared field are ordered facts in the explored schedule
        h.on_point(f"guarded:{type(owner).__name__}.{field}")
    lock = getattr(owner, lock_attr, None)
    if lock is None or _lock_is_held(lock):
        return
    _RECORDER.guard_violation(
        f"{type(owner).__name__}.{field}.{op} without {lock_attr} held "
        f"(thread {threading.current_thread().name!r})")


def _make_guarded_container(value, owner_ref, field: str):
    """Wrap a container value in a subclass that asserts the guard on every
    mutator.  Unknown types pass through unwrapped (scalar rebinds are
    caught by the instrumented ``__setattr__`` instead).

    Known limit: wrapping COPIES the container (``cls(value)``), so code
    that keeps an alias to the object it assigned
    (``d = {}; self._pods = d; d[k] = v``) mutates the orphaned original
    — unobserved by the proxy AND invisible to the instance.  None of the
    annotated classes alias their guarded fields (the static
    lock-discipline rule has no alias escape in-tree either); if sharded
    dispatch ever introduces the pattern, mutate through ``self.<field>``
    or the guard is fiction."""
    import collections

    def mutators(base, names):
        ns = {}
        for n in names:
            orig = getattr(base, n, None)
            if orig is None:
                continue

            def wrapped(self, *a, __orig=orig, __n=n, **kw):
                _check(owner_ref, field, __n)
                return __orig(self, *a, **kw)
            ns[n] = wrapped
        return ns

    if isinstance(value, collections.OrderedDict):
        cls = type("_GuardedODict", (collections.OrderedDict,), mutators(
            collections.OrderedDict,
            ("__setitem__", "__delitem__", "pop", "popitem", "clear",
             "update", "setdefault", "move_to_end")))
        return cls(value)
    if isinstance(value, dict):
        cls = type("_GuardedDict", (dict,), mutators(
            dict, ("__setitem__", "__delitem__", "pop", "popitem", "clear",
                   "update", "setdefault")))
        return cls(value)
    if isinstance(value, collections.deque):
        cls = type("_GuardedDeque", (collections.deque,), mutators(
            collections.deque,
            ("append", "appendleft", "pop", "popleft", "extend",
             "extendleft", "clear", "remove", "rotate", "insert")))
        out = cls(value, value.maxlen)
        return out
    if isinstance(value, set):
        cls = type("_GuardedSet", (set,), mutators(
            set, ("add", "discard", "remove", "pop", "clear", "update",
                  "difference_update", "intersection_update",
                  "symmetric_difference_update")))
        return cls(value)
    if isinstance(value, list):
        cls = type("_GuardedList", (list,), mutators(
            list, ("append", "extend", "insert", "pop", "remove", "clear",
                   "sort", "reverse", "__setitem__", "__delitem__")))
        return cls(value)
    return value


def guarded_by(lock_attr: str, *fields: str):
    """Class decorator declaring that ``lock_attr`` guards ``fields``.

    Always: records the declaration as ``cls.__tpulint_guarded__`` — the
    static ``lock-discipline`` rule reads it, and so can humans.

    Debug mode (and only then — decided per INSTANCE at construction):
    after ``__init__`` returns, the instance's declared container fields
    are wrapped in mutation-asserting proxies and its class is swapped to
    a subclass whose ``__setattr__`` asserts the guard on rebinds of the
    declared fields (re-wrapping new container values so the check
    survives ``self._pending_moves = {}``-style swaps)."""
    fields_t = tuple(fields)

    def deco(cls):
        declared = dict(getattr(cls, "__tpulint_guarded__", ()) or {})
        declared[lock_attr] = tuple(declared.get(lock_attr, ())) + fields_t
        cls.__tpulint_guarded__ = declared
        orig_init = cls.__init__

        def init(self, *a, **kw):
            orig_init(self, *a, **kw)
            # exact-type only: a subclass's __init__ may still be running
            # after this super() call returns, and its construction-time
            # writes must not be judged (construction happens-before
            # publication) — subclasses opt in with their own decorator
            if not _DEBUG or type(self) is not cls:
                return
            _instrument_instance(self, cls)

        init.__wrapped__ = orig_init
        init.__name__ = "__init__"
        cls.__init__ = init
        return cls
    return deco


def _instrument_instance(self, cls) -> None:
    declared = cls.__tpulint_guarded__
    for lock_attr, fs in declared.items():
        ref = (self, lock_attr)
        for f in fs:
            if f in self.__dict__:
                object.__setattr__(
                    self, f,
                    _make_guarded_container(self.__dict__[f], ref, f))
    field_to_lock = {f: la for la, fs in declared.items() for f in fs}

    def setattr_(obj, name, value, __map=field_to_lock):
        la = __map.get(name)
        if la is not None:
            _check((obj, la), name, "rebind")
            value = _make_guarded_container(value, (obj, la), name)
        object.__setattr__(obj, name, value)

    dbg = type(cls.__name__, (type(self),),
               {"__setattr__": setattr_, "__tpulint_debug__": True,
                "__module__": cls.__module__})
    object.__setattr__(self, "__class__", dbg)


def thread_confined(cls):
    """Class decorator for single-threaded-by-contract state (the
    equivalence cache: only the scheduleOne loop may touch it).  Debug
    mode (decided per instance at construction, like ``guarded_by``) swaps
    the instance's class for a subclass whose public methods record the
    first calling thread and flag any call from another; off: instances
    are untouched — zero overhead."""
    cls.__tpulint_confined__ = True
    orig_init = cls.__init__

    def confine(name, fn):
        def wrapped(self, *a, **kw):
            me = threading.get_ident()
            owner = self.__dict__.get("_tpulint_owner_thread")
            if owner is None:
                object.__setattr__(self, "_tpulint_owner_thread", me)
            elif owner != me:
                _RECORDER.guard_violation(
                    f"{cls.__name__}.{name} called from thread "
                    f"{threading.current_thread().name!r} but the instance "
                    f"is confined to its first caller")
            return fn(self, *a, **kw)
        wrapped.__name__ = name
        wrapped.__wrapped__ = fn
        return wrapped

    def init(self, *a, **kw):
        orig_init(self, *a, **kw)
        if not _DEBUG or type(self) is not cls:   # exact-type only, as in
            return                                # guarded_by
        object.__setattr__(self, "_tpulint_owner_thread", None)
        ns: Dict[str, Any] = {"__tpulint_debug__": True,
                              "__module__": cls.__module__}
        for name, attr in vars(cls).items():
            if not name.startswith("_") and callable(attr):
                ns[name] = confine(name, attr)
        object.__setattr__(self, "__class__",
                           type(cls.__name__, (type(self),), ns))

    init.__wrapped__ = orig_init
    init.__name__ = "__init__"
    cls.__init__ = init
    return cls
