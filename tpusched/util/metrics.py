"""Minimal Prometheus-style metrics registry.

The reference inherits kube-scheduler's registry and increments upstream
counters (metrics.PreemptionAttempts.Inc(),
/root/reference/pkg/capacityscheduling/capacity_scheduling.go:322); the
controller is scraped via ServiceMonitor (config/prometheus/monitor.yaml).
Here: counters + histograms with a text exposition dump, including the
north-star PodGroup-to-Bound latency histogram (BASELINE.md).
"""
from __future__ import annotations

import bisect
import collections
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from . import klog

_DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.0, 5.0, 10.0, 30.0, 60.0)


def escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping (exposition format spec):
    backslash, double-quote and newline must be escaped or one hostile
    value (a pod name, an error string) corrupts every later sample line."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _escape_help(h: str) -> str:
    """# HELP escaping: backslash and newline only (quotes are legal)."""
    return h.replace("\\", r"\\").replace("\n", r"\n")


def format_labels(label_names: Tuple[str, ...],
                  label_values: Tuple[str, ...]) -> str:
    """``k1="v1",k2="v2"`` with proper value escaping — the one formatter
    every labeled family goes through."""
    return ",".join(f'{k}="{escape_label_value(v)}"'
                    for k, v in zip(label_names, label_values))


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._value += delta

    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(Counter):
    def set(self, v: float) -> None:
        with self._lock:
            self._value = v


class GaugeFunc:
    """Scrape-time gauge: value() calls a provider. Re-registering replaces
    the provider, so a restarted component (new scheduler in-process, as the
    test harness does constantly) takes over its metric instead of leaving a
    stale closure over dead state.

    A provider returning ``None`` declares itself DEAD (its weakref target
    is gone — e.g. a stopped scheduler's queue): the registry prunes the
    entry at the next expose() instead of emitting a stale zero-valued
    series forever. HA failover and the what-if planner construct schedulers
    under fresh label sets constantly; without pruning every one of them
    leaks a gauge_func entry for the life of the process."""

    def __init__(self, name: str, fn, help_: str = "", labels: str = ""):
        self.name, self.help, self.labels = name, help_, labels
        self._fn = fn
        self.dead = False
        self.error = ""          # last provider failure ('' = healthy)

    def set_fn(self, fn) -> None:
        self._fn = fn
        self.dead = False
        self.error = ""          # new provider: the old failure is history

    def value(self) -> float:
        try:
            v = self._fn()
            if v is None:
                self.dead = True
                return 0.0
            return float(v)
        except Exception as e:  # noqa: BLE001 — a raising provider is
            # treated like a dead one: pruned at scrape, reason retained
            self.dead = True
            self.error = str(e)
            return 0.0


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: Tuple[float, ...] = _DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        # bounded sample window for exact quantiles in bench; buckets remain
        # exact forever (an always-on control plane must not leak memory)
        self._samples: "collections.deque[float]" = collections.deque(maxlen=100_000)

    def observe(self, v: float) -> None:
        with self._lock:
            i = bisect.bisect_left(self.buckets, v)
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._samples.append(v)

    def count(self) -> int:
        with self._lock:
            return self._count

    def quantile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            xs = sorted(self._samples)
            idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
            return xs[idx]

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._samples.clear()


class HistogramVec:
    """Labeled histogram family — the upstream
    framework_extension_point_duration_seconds{extension_point=...} shape.
    Children are created on first observation per label tuple."""

    def __init__(self, name: str, label_names: Tuple[str, ...],
                 help_: str = "", buckets: Tuple[float, ...] = _DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.label_names = tuple(label_names)
        self.buckets = buckets
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Histogram] = {}

    def with_labels(self, *label_values: str) -> Histogram:
        key = tuple(label_values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: want labels {self.label_names}, got {key}")
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Histogram(self.name, self.help, self.buckets)
                self._children[key] = child
            return child

    def children(self) -> Dict[Tuple[str, ...], Histogram]:
        with self._lock:
            return dict(self._children)


class _ScalarVec:
    """Labeled scalar family (counter/gauge children created on first use).

    ``value()`` returns the SUM over children so a family can stand in for
    the unlabeled counter it replaced — call sites that watched the total
    (tests, the chaos soak's invariants) keep working across the
    name-mangled → labeled-children migration."""

    _child_type = Counter

    def __init__(self, name: str, label_names: Tuple[str, ...],
                 help_: str = ""):
        self.name, self.help = name, help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Counter] = {}

    def with_labels(self, *label_values) -> Counter:
        key = tuple(str(v) for v in label_values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: want labels {self.label_names}, got {key}")
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child_type(self.name, self.help)
                self._children[key] = child
            return child

    def children(self) -> Dict[Tuple[str, ...], Counter]:
        with self._lock:
            return dict(self._children)

    def value(self) -> float:
        return sum(c.value() for c in self.children().values())

    def clear(self) -> None:
        """Drop every child (collectors that rebuild the family per refresh
        use this so vanished label sets — a deleted pool, a removed quota —
        do not linger as stale series)."""
        with self._lock:
            self._children.clear()

    def remove(self, *label_values) -> None:
        """Drop one child: a vanished label set (deleted pool, removed
        quota) must stop being exposed, not freeze at its last value."""
        with self._lock:
            self._children.pop(tuple(str(v) for v in label_values), None)


class CounterVec(_ScalarVec):
    _child_type = Counter


class GaugeVec(_ScalarVec):
    _child_type = Gauge


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        # scrape-time collectors (capacity/fragmentation telemetry): called
        # before each expose() so gauge families with DYNAMIC label sets
        # (per pool, per quota namespace) refresh without a background
        # thread. A collector raising is dropped from that scrape only —
        # telemetry must never take /metrics down with it.
        self._collectors: List[Callable[[], None]] = []

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_))

    def counter_vec(self, name: str, label_names: Tuple[str, ...],
                    help_: str = "") -> CounterVec:
        return self._get_or_make(
            name, lambda: CounterVec(name, label_names, help_))

    def gauge_vec(self, name: str, label_names: Tuple[str, ...],
                  help_: str = "") -> GaugeVec:
        return self._get_or_make(
            name, lambda: GaugeVec(name, label_names, help_))

    def register_collector(self, fn: Callable[[], None]) -> Callable[[], None]:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)
        return fn

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(name, lambda: Histogram(name, help_, buckets))

    def histogram_vec(self, name: str, label_names: Tuple[str, ...],
                      help_: str = "",
                      buckets=_DEFAULT_BUCKETS) -> HistogramVec:
        return self._get_or_make(
            name, lambda: HistogramVec(name, label_names, help_, buckets))

    def gauge_func(self, name: str, fn, help_: str = "",
                   labels: str = "") -> GaugeFunc:
        key = f"{name}{{{labels}}}" if labels else name
        g = self._get_or_make(key, lambda: GaugeFunc(name, fn, help_, labels))
        g.set_fn(fn)
        return g

    def _get_or_make(self, name, ctor):
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = ctor()
            return self._metrics[name]

    @staticmethod
    def _metric_type(m) -> str:
        # order matters: Gauge subclasses Counter
        if isinstance(m, (Gauge, GaugeFunc, GaugeVec)):
            return "gauge"
        if isinstance(m, (Counter, CounterVec)):
            return "counter"
        if isinstance(m, (Histogram, HistogramVec)):
            return "histogram"
        return "untyped"

    def expose(self) -> str:
        """Prometheus text exposition format, conformant per the format
        spec: one ``# HELP``/``# TYPE`` header per metric FAMILY (emitted
        before its first sample, never repeated — gauge_func series of one
        name share a single header), escaped HELP text and label values,
        and deterministic ordering (families by name, children by label
        tuple). GaugeFunc entries whose provider reports a dead target are
        pruned here rather than emitted as stale zeros (see GaugeFunc)."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — telemetry refresh
                # is best-effort (/metrics must stay up), but the broken
                # collector must be visible to operators
                klog.error_s(e, "metrics collector failed during scrape")
        lines: List[str] = []
        dead: List[str] = []
        with self._lock:
            metrics = dict(self._metrics)
        # group registry keys by metric FAMILY name: gauge_func series are
        # keyed 'name{labels}' and must share one HELP/TYPE header
        families: Dict[str, List[Tuple[str, object]]] = {}
        for key, m in metrics.items():
            families.setdefault(getattr(m, "name", key), []).append((key, m))
        for name in sorted(families):
            entries = sorted(families[name], key=lambda kv: kv[0])
            m0 = entries[0][1]
            help_ = getattr(m0, "help", "")
            if help_:
                lines.append(f"# HELP {name} {_escape_help(help_)}")
            lines.append(f"# TYPE {name} {self._metric_type(m0)}")
            emitted = 0
            for key, m in entries:
                if isinstance(m, HistogramVec):
                    for values, child in sorted(m.children().items()):
                        self._expose_histogram(
                            lines, name, child,
                            format_labels(m.label_names, values))
                        emitted += 1
                elif isinstance(m, Histogram):
                    self._expose_histogram(lines, name, m, "")
                    emitted += 1
                elif isinstance(m, (CounterVec, GaugeVec)):
                    for values, child in sorted(m.children().items()):
                        labels = format_labels(m.label_names, values)
                        lines.append(f"{name}{{{labels}}} {child.value()}")
                        emitted += 1
                else:
                    v = m.value()
                    if isinstance(m, GaugeFunc) and m.dead:
                        dead.append(key)
                        continue
                    labels = getattr(m, "labels", "")
                    suffix = f"{{{labels}}}" if labels else ""
                    lines.append(f"{name}{suffix} {v}")
                    emitted += 1
            if emitted == 0:
                # every series of the family was pruned (dead gauge_funcs)
                # or the vec has no children yet: drop the orphan header
                del lines[-1]
                if help_:
                    del lines[-1]
        if dead:
            with self._lock:
                for key in dead:
                    m = self._metrics.get(key)
                    # re-registration may have revived the slot since
                    if isinstance(m, GaugeFunc) and m.dead:
                        del self._metrics[key]
        return "\n".join(lines) + "\n"

    @staticmethod
    def _expose_histogram(lines: List[str], name: str, m: Histogram,
                          labels: str) -> None:
        prefix = f"{labels}," if labels else ""
        cum = 0
        with m._lock:
            for b, c in zip(m.buckets, m._counts):
                cum += c
                lines.append(f'{name}_bucket{{{prefix}le="{b}"}} {cum}')
            lines.append(f'{name}_bucket{{{prefix}le="+Inf"}} {m._count}')
            suffix = f"{{{labels}}}" if labels else ""
            lines.append(f"{name}_sum{suffix} {m._sum}")
            lines.append(f"{name}_count{suffix} {m._count}")


# Global scheduler registry + well-known metrics.
REGISTRY = Registry()

preemption_attempts = REGISTRY.counter(
    "tpusched_preemption_attempts_total", "Preemption attempts (PostFilter).")
slice_preemption_victims = REGISTRY.counter(
    "tpusched_slice_preemption_victims_total",
    "Pods evicted by slice (window-wise) preemption.")
e2e_scheduling_seconds = REGISTRY.histogram(
    "tpusched_e2e_scheduling_duration_seconds", "Pop-to-bound per pod.")
pod_group_to_bound_seconds = REGISTRY.histogram(
    "tpusched_podgroup_to_bound_duration_seconds",
    "First-member-seen to last-member-bound per PodGroup (north-star metric).")
schedule_attempts = REGISTRY.counter(
    "tpusched_schedule_attempts_total", "Scheduling cycles run.")
bind_total = REGISTRY.counter("tpusched_bind_total", "Successful binds.")

# Equivalence-class scheduling cache (sched/equivcache.py). A lookup lands in
# exactly one of: hit, miss (no entry for the class), invalidation (an entry
# existed but its validity triple failed — mutation cursor, nominator
# generation, or a plugin fingerprint moved — or a cached node vanished),
# fallback (a valid entry was found but the hit path aborted to the full
# path: cached feasible set drained under the dynamic re-filter, a filter
# errored, host selection failed, or the differential oracle disagreed), or
# bypass (nominated pods in play: the cache is not consulted at all) — so
# hits + misses + invalidations + fallbacks + bypasses == cycles that
# reached the lookup. Creation-side: veto counts cycles where an
# EquivalenceAware plugin refused to certify its PreFilter output as
# reusable. differential_mismatches MUST stay 0: it counts cache-hit
# placements that differed from the full path under differential mode.
equiv_cache_hits = REGISTRY.counter(
    "tpusched_equiv_cache_hits_total",
    "Scheduling cycles served from the equivalence cache.")
equiv_cache_misses = REGISTRY.counter(
    "tpusched_equiv_cache_misses_total",
    "Cycles with no cache entry for the pod's equivalence class.")
equiv_cache_invalidations = REGISTRY.counter(
    "tpusched_equiv_cache_invalidations_total",
    "Cache entries dropped because cursor/nominator/fingerprint moved.")
equiv_cache_bypasses = REGISTRY.counter(
    "tpusched_equiv_cache_bypasses_total",
    "Cycles that skipped the cache because nominated pods exist.")
equiv_cache_vetoes = REGISTRY.counter(
    "tpusched_equiv_cache_vetoes_total",
    "Entry creations vetoed by an EquivalenceAware plugin.")
equiv_cache_fallbacks = REGISTRY.counter(
    "tpusched_equiv_cache_fallbacks_total",
    "Valid-entry cycles that aborted to the full path (set drained, "
    "filter error, selection failure, or differential disagreement).")
equiv_cache_differential_mismatches = REGISTRY.counter(
    "tpusched_equiv_cache_differential_mismatches_total",
    "Differential-mode hits whose placement differed from the full path.")

# Incremental torus window index (topology/windowindex.py, ISSUE 13).
# updates counts every cache-transition hook applied; rebuilds counts full
# plane (re)materializations (topology CR change, attach seeding, or a
# differential-mismatch self-heal); cells_touched counts free-plane cell
# flips — the Δ the O(Δ) maintenance claim is measured in.  queries land
# as served (table lookup answered the PreFilter sweep) or fallback (the
# cursor-consistency rule refused: version mismatch, stale/mixed plane,
# live window claims).  differential_mismatches MUST stay 0: it counts
# sampled in-cycle oracle checks where the index answer differed from the
# Python full recompute (each one also quarantines + reseeds the pool).
torus_index_updates_total = REGISTRY.counter(
    "tpusched_torus_index_updates_total",
    "Cache-transition updates applied to the torus window index.")
torus_index_rebuilds_total = REGISTRY.counter(
    "tpusched_torus_index_rebuilds_total",
    "Full pool-plane rebuilds of the torus window index.")
torus_index_cells_touched_total = REGISTRY.counter(
    "tpusched_torus_index_cells_touched_total",
    "Free-plane cell flips applied incrementally to the window index.")
torus_index_queries = REGISTRY.counter_vec(
    "tpusched_torus_index_queries_total", ("result",),
    "Window-index PreFilter sweeps by outcome (served|fallback).")
torus_index_differential_mismatches = REGISTRY.counter(
    "tpusched_torus_index_differential_mismatches_total",
    "Sampled differential checks where the index disagreed with the "
    "Python full-recompute oracle.")

# Flight recorder (tpusched/trace): queue-wait is the span the cycle trace
# decomposes out of e2e latency (pop time - last enqueue time), and every
# pinned anomaly trace (permit timeout, bind failure, gang denial,
# preemption) counts here so dashboards can alert before anyone reads dumps.
# Labeled by dispatch shard (sharded core, ROADMAP item 1): '' on the
# classic single loop, 's<N>'/'global' per lane when sharding is on — a
# hot or starved shard shows up as ITS queue-wait distribution diverging
# from its peers'. Family-level totals keep the pre-sharding meaning.
queue_wait_seconds = REGISTRY.histogram_vec(
    "tpusched_scheduling_queue_wait_duration_seconds", ("shard",),
    "Last-enqueue to pop per scheduling cycle (the trace's queue-wait "
    "span), by dispatch shard.")
# Labeled by anomaly kind (permit_timeout, bind_failed, gang_denied,
# gang_stuck, ...) so dashboards can alert on ONE failure mode without
# name-mangled per-kind metrics; .value() is the family total.
flight_recorder_anomalies = REGISTRY.counter_vec(
    "tpusched_flight_recorder_anomalies_total", ("kind",),
    "Cycle traces pinned by the flight recorder as anomalies, by kind.")
# API-failure resilience (apiserver/client.py retry layer + the scheduler's
# degraded mode). retries counts every re-attempt the client made after a
# retriable failure; retry_exhausted counts calls that failed terminally
# AFTER burning their retry budget (each of these also feeds the scheduler's
# degraded-mode trip counter). events_dropped counts Event emissions
# swallowed by the best-effort recorder path — an Event must never fail a
# scheduling/binding cycle. gang_bind_rollbacks counts whole-PodGroup
# rollbacks triggered by a terminal mid-gang bind failure (each one also
# pins a gang_bind_rollback anomaly trace in the flight recorder).
# tpusched_degraded_mode itself is a per-scheduler gauge_func registered by
# the Scheduler (0 = normal, 1 = pop-dispatch paused).
api_retries = REGISTRY.counter_vec(
    "tpusched_api_retries_total", ("verb",),
    "API calls re-attempted after a retriable failure, by verb.")
api_retry_exhausted = REGISTRY.counter_vec(
    "tpusched_api_retry_exhausted_total", ("verb",),
    "API calls that failed terminally after exhausting their retry "
    "budget, by verb.")
events_dropped = REGISTRY.counter(
    "tpusched_events_dropped_total",
    "Best-effort Event emissions swallowed instead of raised into a cycle.")
gang_bind_rollbacks = REGISTRY.counter(
    "tpusched_gang_bind_rollbacks_total",
    "Whole-gang rollbacks after a terminal mid-gang bind failure.")

# Node & slice failure resilience (controllers/nodelifecycle.py,
# controllers/gangrepair.py, the scheduler's stuck-gang watchdog).
# nodes_not_ready is the CURRENT count of heartbeat-managed nodes holding a
# Ready=False condition (set by the lifecycle sweep); transitions counts
# every Ready→NotReady edge. node_pod_evictions counts pods deleted off
# dead/NotReady nodes (grace-lapsed eviction + orphan GC). gang_repairs
# counts whole-gang repair actions (restart-gang or backfill) after member
# loss to dead hardware; gang_stuck counts watchdog no-progress findings
# (each also pins a gang_stuck anomaly trace).
nodes_not_ready = REGISTRY.gauge(
    "tpusched_nodes_not_ready",
    "Heartbeat-managed nodes currently holding a Ready=False condition.")
node_not_ready_transitions = REGISTRY.counter(
    "tpusched_node_not_ready_transitions_total",
    "Ready→NotReady transitions marked by the node lifecycle controller.")
node_pod_evictions = REGISTRY.counter(
    "tpusched_node_pod_evictions_total",
    "Pods evicted off dead/NotReady nodes by the lifecycle controller.")
gang_repairs = REGISTRY.counter(
    "tpusched_gang_repairs_total",
    "Whole-gang repair actions after member loss to dead hardware.")
gang_stuck_total = REGISTRY.counter(
    "tpusched_gang_stuck_total",
    "Stuck-gang watchdog findings (no scheduling progress past deadline).")

# Upstream framework_extension_point_duration_seconds analog. Deliberate
# divergence: the per-node Filter/Score sweeps are recorded once per CYCLE
# (the whole sweep), not once per node — at 1024-host scale a per-node
# observation in the hot loop would cost more than the work it measures.
extension_point_seconds = REGISTRY.histogram_vec(
    "tpusched_framework_extension_point_duration_seconds",
    ("extension_point",),
    "Per-cycle latency of each framework extension point.")
# Per-plugin companion (upstream plugin_execution_duration_seconds): wired
# only at the once-per-cycle points — never inside the per-node Filter/Score
# sweeps (see fwk/runtime._timed_plugin).
plugin_execution_seconds = REGISTRY.histogram_vec(
    "tpusched_plugin_execution_duration_seconds",
    ("plugin", "extension_point"),
    "Per-invocation plugin latency at the cold extension points.")

# Lock-contention telemetry (util/locking.py telemetry mode — opt-in,
# distinct from debug mode, which stays zero-overhead when off). Buckets
# start in the microseconds: the locks worth watching (cache, queue,
# recorder) are held for µs–ms, and the default duration buckets would
# collapse every observation into the first bucket. wait counts CONTENDED
# acquires only (the uncontended fast path never observes — its count would
# drown the signal); hold counts holds longer than the long-hold threshold.
_LOCK_BUCKETS = (0.000001, 0.000005, 0.00001, 0.00005, 0.0001, 0.0005,
                 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)
lock_wait_seconds = REGISTRY.histogram_vec(
    "tpusched_lock_wait_seconds", ("lock",),
    "Contended-acquire wait per named lock (telemetry mode only).",
    buckets=_LOCK_BUCKETS)
lock_hold_seconds = REGISTRY.histogram_vec(
    "tpusched_lock_hold_seconds", ("lock",),
    "Long lock holds per named lock (telemetry mode only; holds above "
    "the long-hold threshold).", buckets=_LOCK_BUCKETS)

# Fleet throughput telemetry (tpusched/obs/throughput.py, fed by the
# scheduler and _BindingPool). These are the SUSTAINED-throughput counters
# the arrival-storm bench and the sharded-core work (ROADMAP item 1) rate
# against: rate(tpusched_binds_total[1m]) is the fleet's binds/sec.
# Labeled by scheduler profile so one process hosting several profiles
# (HA, what-if planners run under fresh names) attributes throughput
# correctly; .value() is the process total. They deliberately coexist
# with the older unlabeled tpusched_bind_total/tpusched_schedule_attempts_
# total (dashboards already scrape those; renaming a scraped family is a
# breaking change this repo does not make).
# The shard label ('' single loop, 's<N>'/'global' per dispatch lane)
# attributes sustained throughput to the lane that produced it — the
# first divergence to look at when one shard runs hot or starved.
binds_total = REGISTRY.counter_vec(
    "tpusched_binds_total", ("scheduler", "shard"),
    "Successful bind commits, by scheduler profile and dispatch shard.")
scheduling_cycles_total = REGISTRY.counter_vec(
    "tpusched_scheduling_cycles_total", ("scheduler", "shard"),
    "Scheduling cycles started, by scheduler profile and dispatch shard.")
# Sharded dispatch conflict/escalation accounting (sched/shards.py):
# conflicts = optimistic commits refused because a foreign mutation raced
# the cycle's pool (the cycle re-derives on fresh state — correctness
# preserved, one cycle of work spent); escalations = pods a shard-
# restricted cycle could not place that re-entered the global lane.
shard_conflicts_total = REGISTRY.counter_vec(
    "tpusched_shard_conflicts_total", ("shard",),
    "Optimistic shard commits refused by a raced pool cursor, by lane.")
shard_escalations_total = REGISTRY.counter_vec(
    "tpusched_shard_escalations_total", ("shard",),
    "Pods escalated from a shard lane to the global dispatch lane.")
# quota-guarded commits refused by a raced quota EPOCH (ISSUE 14: the
# fleet-wide compare-and-reserve for ElasticQuota admission) — separate
# from pool conflicts because the remedies differ (doc/ops.md: a hot
# quota-conflict loop points at concurrent quota'd traffic, not at pool
# contention)
shard_quota_conflicts_total = REGISTRY.counter_vec(
    "tpusched_shard_quota_conflicts_total", ("shard",),
    "Quota-guarded commits refused by a raced quota epoch, by lane.")

# Sampling profiler self-accounting (tpusched/obs/profiler.py): the
# sampler's own sample count — the denominator for every attribution
# share, and the prof-smoke gate's liveness witness.
profiler_samples_total = REGISTRY.counter(
    "tpusched_profiler_samples_total",
    "Stack samples taken by the hot-path sampling profiler.")

# Fleet trace capture (tpusched/obs/fleetrace.py): the durable cluster-
# event journal replay/policy-evaluation work consumes. events counts
# records ACCEPTED into the writer queue by kind; dropped counts records
# refused at the queue budget (capture is bounded — it sheds load, it
# never blocks the informer boundary); bytes is the on-disk append volume
# after JSON encoding (rotation/compaction deletions do not subtract).
fleetrace_events_total = REGISTRY.counter_vec(
    "tpusched_fleetrace_events_total", ("kind",),
    "Fleet-trace events captured, by event kind.")
fleetrace_dropped_total = REGISTRY.counter(
    "tpusched_fleetrace_dropped_total",
    "Fleet-trace events dropped at the capture queue budget.")
fleetrace_bytes_total = REGISTRY.counter(
    "tpusched_fleetrace_bytes_written_total",
    "Bytes appended to fleet-trace segment files.")

# Gang runtime goodput telemetry (tpusched/obs/goodput.py, fed by the
# heartbeat-piggybacked GangMemberStatus reports). reports counts reports
# ACCEPTED into the aggregator; shed counts reports refused at its
# entry/byte budgets (ingest is bounded — runtime telemetry sheds, it
# never grows without bound); dropped counts reports lost before the
# apiserver fan-out (client-side best-effort path). The per-gang gauge
# families below are registered by the aggregator and REMOVED when a gang
# is evicted/torn down, so cardinality tracks live gangs only.
goodput_reports_total = REGISTRY.counter(
    "tpusched_goodput_reports_total",
    "Gang member runtime status reports accepted by the aggregator.")
goodput_reports_shed = REGISTRY.counter(
    "tpusched_goodput_reports_shed_total",
    "Runtime status reports shed at the aggregator's entry/byte budgets.")
goodput_reports_dropped = REGISTRY.counter(
    "tpusched_goodput_reports_dropped_total",
    "Runtime status reports lost in the best-effort client fan-out.")
gang_goodput_units = REGISTRY.gauge_vec(
    "tpusched_gang_goodput_units_per_second", ("gang", "unit"),
    "Aggregate member-reported throughput per RUNNING gang (unit/s).")
gang_goodput_per_chip = REGISTRY.gauge_vec(
    "tpusched_gang_goodput_per_chip", ("gang", "unit"),
    "Member-reported throughput per TPU chip for a RUNNING gang.")
gang_step_skew = REGISTRY.gauge_vec(
    "tpusched_gang_goodput_step_skew", ("gang",),
    "Slowest member's rolling step time over the gang median (1.0 = "
    "perfectly even; the straggler detector's input signal).")
gang_stragglers = REGISTRY.gauge_vec(
    "tpusched_gang_stragglers", ("gang",),
    "Members currently flagged as stragglers per RUNNING gang.")
gang_straggler_events = REGISTRY.counter_vec(
    "tpusched_gang_straggler_events_total", ("gang",),
    "Straggler detections (hysteresis entry edges), by gang.")
workload_goodput_per_chip = REGISTRY.gauge_vec(
    "tpusched_workload_goodput_per_chip", ("workload", "generation"),
    "EWMA goodput-per-chip by workload fingerprint and pool generation "
    "(the Gavel throughput-matrix cell, ROADMAP item 3).")

# Native batched dispatch inner loop (sched/nativedispatch.py, ISSUE 16).
# cycles counts kernel sweeps executed (one per candidate-set evaluation);
# pods counts placements that completed through the native path (the bind
# commit itself stays Cache.assume_pod_guarded); fallbacks counts cycles
# the native path declined, by reason (no-native, profile, pod-shape,
# claims, prescore, no-feasible, inexact, …) — the ops runbook's first
# diagnostic read.  differential_mismatches MUST stay 0: it counts sampled
# in-cycle oracle re-runs whose placement differed from the kernel's (each
# one also re-routes that cycle to the oracle's answer).
native_dispatch_cycles_total = REGISTRY.counter(
    "tpusched_native_dispatch_cycles_total",
    "Candidate-set sweeps evaluated by the native dispatch kernel.")
native_dispatch_pods_total = REGISTRY.counter(
    "tpusched_native_dispatch_pods_total",
    "Pods whose Filter/Score/rank completed through the native kernel.")
native_dispatch_fallbacks = REGISTRY.counter_vec(
    "tpusched_native_dispatch_fallbacks_total", ("reason",),
    "Cycles the native dispatch path declined, by reason.")
native_dispatch_differential_mismatches = REGISTRY.counter(
    "tpusched_native_dispatch_differential_mismatches_total",
    "Sampled oracle re-runs disagreeing with the native dispatch kernel.")

# Coalesced bind-side watch fan-out (apiserver/server.py, ISSUE 16).
# batches counts flush-window drains (each delivers >= 1 events in store-
# commit order); events counts watch events delivered through the batcher;
# flush_seconds observes commit-to-delivery latency per batch — the knob's
# direct cost, bounded by the flush window plus handler time.
fanout_batches_total = REGISTRY.counter(
    "tpusched_fanout_batches_total",
    "Coalesced watch-dispatch flush batches delivered.")
fanout_events_total = REGISTRY.counter(
    "tpusched_fanout_events_total",
    "Watch events delivered through the coalesced fan-out batcher.")
fanout_flush_seconds = REGISTRY.histogram(
    "tpusched_fanout_flush_seconds",
    "Commit-to-delivery latency of coalesced watch flush batches.",
    buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1, .25, 1.0))

# The closed incident plane (obs/timeline.py, obs/sentinel.py,
# obs/incident.py — ISSUE 20).  timeline_samples counts committed health
# ticks; timeline_overflow counts ring entries EVICTED under the
# entry/byte budget (counted, never stored — the always-on discipline).
# sentinel_firings is labeled by detector so a dashboard can alert on
# one anomaly class; incident bundle written/dropped split tells an
# operator whether the black box actually has the 3am evidence or the
# disk budget ate it.
timeline_samples_total = REGISTRY.counter(
    "tpusched_timeline_samples_total",
    "Health timeline ticks committed to the in-process ring.")
timeline_overflow_total = REGISTRY.counter(
    "tpusched_timeline_overflow_total",
    "Timeline ring entries evicted under the entry/byte budget.")
sentinel_firings_total = REGISTRY.counter_vec(
    "tpusched_sentinel_firings_total", ("detector",),
    "Anomaly sentinel firings, by detector.")
incident_bundles_written_total = REGISTRY.counter(
    "tpusched_incident_bundles_written_total",
    "Black-box incident bundles committed (atomic write or memory ring).")
incident_bundles_dropped_total = REGISTRY.counter(
    "tpusched_incident_bundles_dropped_total",
    "Incident bundles dropped or evicted (budget, cooldown excluded).")
