"""Structured, leveled logging — klog.InfoS/ErrorS analog.

The reference enforces structured logging repo-wide
(/root/reference/hack/verify-structured-logging.sh:17-19) with verbosity
conventions V(4)-V(6) for scheduling detail and V(10) for firehose
(flex_gpu.go:42, trimaran/handler.go:93). Same conventions here:
``V(4).info_s("msg", pod=..., node=...)``.
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time

from . import tracectx

_logger = logging.getLogger("tpusched")
if not _logger.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter("%(message)s"))
    _logger.addHandler(h)
    _logger.setLevel(logging.INFO)

_verbosity = int(os.environ.get("TPUSCHED_V", "0"))
_lock = threading.Lock()


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def verbosity() -> int:
    return _verbosity


def _fmt(msg: str, kv: dict) -> str:
    # flight-recorder correlation: a log line emitted inside a traced
    # scheduling/binding cycle carries that cycle's trace id, so operators
    # can jump from any line to the matching /debug/flightrecorder entry
    tid = tracectx.get()
    if tid and "trace" not in kv:
        kv = {**kv, "trace": tid}
    ts = time.strftime("%H:%M:%S", time.localtime())
    parts = [f'{k}="{v}"' if isinstance(v, str) else f"{k}={v}" for k, v in kv.items()]
    return f'{ts} "{msg}" ' + " ".join(parts) if parts else f'{ts} "{msg}"'


class _Verbose:
    def __init__(self, level: int):
        self._enabled = level <= _verbosity

    def info_s(self, msg: str, **kv) -> None:
        if self._enabled:
            with _lock:
                _logger.info("I " + _fmt(msg, kv))


def V(level: int) -> _Verbose:  # noqa: N802 — klog naming
    return _Verbose(level)


def info_s(msg: str, **kv) -> None:
    with _lock:
        _logger.info("I " + _fmt(msg, kv))


def error_s(err, msg: str, **kv) -> None:
    if err is not None:
        kv = {"err": str(err), **kv}
    with _lock:
        _logger.error("E " + _fmt(msg, kv))


def warning_s(msg: str, **kv) -> None:
    with _lock:
        _logger.warning("W " + _fmt(msg, kv))
