"""Injectable scheduler clock: one process-wide time substrate.

Every scheduler-owned gate — pod backoff release, Coscheduling denial
window, permit-barrier deadline, stuck-gang watchdog sweep, shard
escalation TTL, PG-status flush window, unschedulableQ safety-net flush —
used to read wall time ad hoc (``time.monotonic()`` / an injected bare
callable).  That made recorded-trace replay a choice between two bad
modes: *timed* replay re-pays the recorded hours in wall seconds, and
*zeroed-gate* lockstep (PR 9) deletes exactly the retry/timeout dynamics
a policy study needs to measure.

This module is the third mode's substrate.  A ``Clock`` carries two
reads (``now()`` monotonic-flavored, ``wall()`` epoch-flavored — the two
timebases the codebase already mixes deliberately) plus a *deadline
registry*: gate sites ``arm()`` the absolute instant their window
lapses.  ``WallClock`` is the zero-overhead production default — reads
delegate straight to ``time``, ``arm()`` is a no-op (real time advances
by itself).  ``VirtualClock`` is a discrete-event engine: time moves
only when the owner advances it, and when the replay driver finds the
system quiescent it jumps straight to the earliest armed deadline
(``advance_to_next_deadline``) instead of sleeping — recorded hours
compress into wall seconds while every timeout still fires, in faithful
order, at its recorded-timeline instant (sim/replay.py).

Timebase discipline: armed deadlines live on the ``now()`` scale.  Sites
whose deadlines were computed from ``wall()`` reads (the scheduling
queue's backoff expiries — its timestamps feed wall-flavored latency
math) pass ``wall=True`` and the clock normalizes; under ``WallClock``
the flag is moot (no-op arm), under ``VirtualClock`` the two scales
differ by a constant offset fixed at construction.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Clock", "WallClock", "CallableClock", "VirtualClock",
           "as_clock", "WALL"]

# Bound on the remembered fired-deadline log (VirtualClock): replay
# reports read it for retry-ordinal attribution; a day-long trace fires
# far more than a report needs to prove non-vacuity.
_FIRED_LOG_CAP = 4096


class Clock:
    """The protocol.  Subclasses override everything; the base exists so
    ``isinstance(x, Clock)`` is the one dispatch test."""

    #: discrete-event clocks advance only when driven; live surfaces
    #: consult this to skip real-time waits that would never wake
    virtual = False

    def now(self) -> float:                      # monotonic-flavored
        raise NotImplementedError

    def wall(self) -> float:                     # epoch-flavored
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def wait_until(self, deadline: float) -> None:
        """Block (wall) / advance (virtual) until ``now() >= deadline``.
        Never over-advances a virtual clock past ``deadline``."""
        raise NotImplementedError

    # -- deadline registry ----------------------------------------------------

    def arm(self, label: str, deadline: float, *,
            wall: bool = False) -> int:
        """Register an absolute instant a scheduler gate lapses at.
        Zero-overhead no-op on the wall clock (real time fires gates by
        itself); the discrete-event engine records it so a quiescent
        replay can jump straight there.  Returns a token for
        ``cancel()`` (0 = nothing registered)."""
        return 0

    def cancel(self, token: int) -> None:
        """Disarm a previously armed deadline.  Best-effort: firing a
        stale deadline is always harmless (the gate site re-checks its
        own state), so sites only cancel when it is cheap to."""


class WallClock(Clock):
    """Production default: real time, no registry.  The method bodies
    delegate straight to ``time`` so injecting this costs nothing over
    the ad-hoc reads it replaces."""

    virtual = False
    now = staticmethod(time.monotonic)
    wall = staticmethod(time.time)   # the epoch read this clock centralizes
    sleep = staticmethod(time.sleep)

    def wait_until(self, deadline: float) -> None:
        remaining = deadline - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)


class CallableClock(Clock):
    """Adapter for the legacy injected-callable idiom (``clock=lambda:
    t`` in unit tests and the verify scenarios): both reads serve the
    one callable, the registry is a no-op, nothing sleeps."""

    virtual = False

    def __init__(self, fn: Callable[[], float]):
        self._fn = fn

    def now(self) -> float:
        return self._fn()

    def wall(self) -> float:
        return self._fn()

    def sleep(self, seconds: float) -> None:
        return None

    def wait_until(self, deadline: float) -> None:
        return None


class VirtualClock(Clock):
    """Discrete-event time: ``now()`` returns the virtual instant, which
    moves only via ``advance*``/``sleep``/``wait_until``.  Armed
    deadlines sit in a heap; ``advance_to_next_deadline()`` pops the
    earliest live one and jumps time to it, returning (label, deadline)
    so the driver can attribute what fired.  Thread-safe: the replay
    driver advances while bind-pool workers and watch callbacks read."""

    virtual = True

    def __init__(self, start: float = 0.0, wall0: Optional[float] = None):
        self._lock = threading.Lock()
        self._t = float(start)
        # wall() = now() + offset; fixed at construction so the two
        # scales stay a constant apart (arm(wall=True) normalizes by it)
        self._wall_offset = (wall0 - start) if wall0 is not None else 0.0
        self._heap: List[Tuple[float, int, int]] = []   # (deadline, seq, tok)
        self._armed: Dict[int, Tuple[float, str]] = {}  # tok → (deadline, label)
        self._seq = itertools.count(1)
        self._fired: List[Tuple[float, str]] = []
        self._fired_total = 0
        self._fired_by_label: Dict[str, int] = {}

    # -- reads ----------------------------------------------------------------

    def now(self) -> float:
        with self._lock:
            return self._t

    def wall(self) -> float:
        with self._lock:
            return self._t + self._wall_offset

    # -- movement -------------------------------------------------------------

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            self.advance(seconds)

    def advance(self, seconds: float) -> None:
        with self._lock:
            self._t += max(0.0, seconds)

    def advance_to(self, instant: float) -> None:
        """Jump to ``instant`` (never backward).  Pending deadlines at or
        before it stay pending — the driver fires them explicitly via
        ``advance_to_next_deadline`` so every lapse is attributed."""
        with self._lock:
            self._t = max(self._t, instant)

    def wait_until(self, deadline: float) -> None:
        self.advance_to(deadline)

    # -- deadline registry ----------------------------------------------------

    def arm(self, label: str, deadline: float, *,
            wall: bool = False) -> int:
        if wall:
            deadline -= self._wall_offset
        with self._lock:
            tok = next(self._seq)
            self._armed[tok] = (deadline, label)
            heapq.heappush(self._heap, (deadline, tok, tok))
            return tok

    def cancel(self, token: int) -> None:
        with self._lock:
            self._armed.pop(token, None)   # heap entry lazily skipped

    def next_deadline(self) -> Optional[float]:
        """Earliest live armed deadline (``now()`` scale), or None."""
        with self._lock:
            return self._peek_locked()

    def _peek_locked(self) -> Optional[float]:
        while self._heap:
            deadline, _, tok = self._heap[0]
            if tok in self._armed:
                return deadline
            heapq.heappop(self._heap)
        return None

    def advance_to_next_deadline(
            self, limit: Optional[float] = None
    ) -> Optional[Tuple[str, float]]:
        """Pop the earliest live deadline and jump time to it; returns
        (label, deadline) or None when nothing is armed (or the earliest
        lies at/after ``limit`` — then time does NOT move; the caller
        owns the jump to its own horizon)."""
        with self._lock:
            deadline = self._peek_locked()
            if deadline is None or (limit is not None
                                    and deadline >= limit):
                return None
            _, _, tok = heapq.heappop(self._heap)
            _, label = self._armed.pop(tok)
            self._t = max(self._t, deadline)
            self._fired_total += 1
            self._fired_by_label[label] = \
                self._fired_by_label.get(label, 0) + 1
            if len(self._fired) < _FIRED_LOG_CAP:
                self._fired.append((self._t, label))
            return label, deadline

    # -- introspection --------------------------------------------------------

    def armed_count(self) -> int:
        with self._lock:
            return len(self._armed)

    def fired(self) -> List[Tuple[float, str]]:
        """The fired-deadline log (bounded; ``fired_total`` is exact)."""
        with self._lock:
            return list(self._fired)

    def fired_total(self) -> int:
        with self._lock:
            return self._fired_total

    def fired_by_label(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._fired_by_label)


#: the shared zero-overhead default — component constructors resolve
#: ``clock=None`` to this instead of re-instantiating
WALL = WallClock()


def as_clock(clock) -> Clock:
    """Normalize every historical ``clock=`` spelling to a ``Clock``:
    None / ``time.time`` / ``time.monotonic`` → the shared WallClock,
    a ``Clock`` → itself, any other callable → ``CallableClock`` (the
    injected-fake-clock test idiom keeps working unchanged)."""
    if clock is None or clock is time.time or clock is time.monotonic:
        return WALL
    if isinstance(clock, Clock):
        return clock
    if callable(clock):
        return CallableClock(clock)
    raise TypeError(f"not a clock: {clock!r}")
