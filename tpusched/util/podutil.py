"""Pod resource helpers (reference analog: /root/reference/pkg/util/resource.go)."""
from __future__ import annotations

from typing import Dict

from ..api.core import POD_FAILED, POD_SUCCEEDED, Pod
from ..api.resources import CPU, MEMORY, PODS, ResourceList


def _container_requests(c) -> Dict[str, int]:
    """Container requests with the API server's defaulting applied: a resource
    set only in limits defaults requests to the limit (mandatory for extended
    resources like google.com/tpu)."""
    req = dict(c.requests)
    for k, v in c.limits.items():
        req.setdefault(k, v)
    return req


def pod_effective_request(pod: Pod) -> ResourceList:
    """Effective request = max(Σ containers, max(initContainers)) per resource,
    plus overhead (resource.go:50-78 / k8s resourcehelper semantics)."""
    total: Dict[str, int] = {}
    for c in pod.spec.containers:
        for k, v in _container_requests(c).items():
            total[k] = total.get(k, 0) + v
    for c in pod.spec.init_containers:
        for k, v in _container_requests(c).items():
            if v > total.get(k, 0):
                total[k] = v
    for k, v in pod.spec.overhead.items():
        total[k] = total.get(k, 0) + v
    return total


def pod_request_with_defaults(pod: Pod, non_zero: bool = False) -> ResourceList:
    """Like pod_effective_request but with the scheduler's non-zero defaults
    (100m cpu / 200Mi memory) applied when requested — the upstream
    NonZeroRequest convention used by the scheduler cache.

    Memoized per pod object (hot path: every NodeInfo.add_pod); safe because
    pod specs are replaced wholesale on update, never mutated in place."""
    cache = getattr(pod, "_req_memo", None)
    if cache is not None and non_zero in cache:
        return cache[non_zero]
    req = pod_effective_request(pod)
    if non_zero:
        req.setdefault(CPU, 0)
        req.setdefault(MEMORY, 0)
        if req[CPU] == 0:
            req[CPU] = 100
        if req[MEMORY] == 0:
            req[MEMORY] = 200 * 1024 * 1024
    req[PODS] = 1
    if cache is None:
        cache = {}
        try:
            object.__setattr__(pod, "_req_memo", cache)
        except AttributeError:
            return req
    cache[non_zero] = req
    return req


def is_pod_terminated(pod: Pod) -> bool:
    return pod.status.phase in (POD_SUCCEEDED, POD_FAILED)


def resources_over_bound(used, delta, bound) -> bool:
    """any resource NAMED BY ``bound`` with used+delta > bound — the cmp2
    comparison semantics of ElasticQuota bounds (elasticquota.go:90-100:
    a bound omitting a resource places no limit on it).  ONE copy shared
    by CapacityScheduling's admission (plugins/capacity) and the cache's
    commit-time compare-and-reserve (sched/cache.assume_pod_guarded):
    the quota protocol is only sound while both evaluate the identical
    rule, so they must not drift."""
    for k, b in bound.items():
        v = used.get(k, 0) + (delta.get(k, 0) if delta else 0)
        if v > b:
            return True
    return False


def is_pod_active(pod: Pod) -> bool:
    return not is_pod_terminated(pod) and not pod.is_terminating()


def assigned(pod: Pod) -> bool:
    return bool(pod.spec.node_name)
