"""Pod resource helpers (reference analog: /root/reference/pkg/util/resource.go)."""
from __future__ import annotations

from typing import Dict

from ..api.core import POD_FAILED, POD_SUCCEEDED, Pod
from ..api.resources import CPU, MEMORY, PODS, ResourceList


def pod_effective_request(pod: Pod) -> ResourceList:
    """Effective request = max(Σ containers, max(initContainers)) per resource,
    plus overhead (resource.go:50-78 / k8s resourcehelper semantics)."""
    total: Dict[str, int] = {}
    for c in pod.spec.containers:
        for k, v in c.requests.items():
            total[k] = total.get(k, 0) + v
    for c in pod.spec.init_containers:
        for k, v in c.requests.items():
            if v > total.get(k, 0):
                total[k] = v
    for k, v in pod.spec.overhead.items():
        total[k] = total.get(k, 0) + v
    return total


def pod_request_with_defaults(pod: Pod, non_zero: bool = False) -> ResourceList:
    """Like pod_effective_request but with the scheduler's non-zero defaults
    (100m cpu / 200Mi memory) applied when requested — the upstream
    NonZeroRequest convention used by the scheduler cache."""
    req = pod_effective_request(pod)
    if non_zero:
        req.setdefault(CPU, 0)
        req.setdefault(MEMORY, 0)
        if req[CPU] == 0:
            req[CPU] = 100
        if req[MEMORY] == 0:
            req[MEMORY] = 200 * 1024 * 1024
    req[PODS] = 1
    return req


def is_pod_terminated(pod: Pod) -> bool:
    return pod.status.phase in (POD_SUCCEEDED, POD_FAILED)


def is_pod_active(pod: Pod) -> bool:
    return not is_pod_terminated(pod) and not pod.is_terminating()


def assigned(pod: Pod) -> bool:
    return bool(pod.spec.node_name)
