"""Shared utilities (reference analog: /root/reference/pkg/util)."""
