"""Observability HTTP endpoint: /metrics, /healthz, /debug/*.

The reference inherits the kube-scheduler's serving stack — Prometheus
/metrics scraped via ServiceMonitor (/root/reference/config/prometheus/
monitor.yaml:4-22) and component-base /debug/pprof (SURVEY §5). This is the
rebuild's equivalent for its own binaries:

- ``/metrics``   Prometheus text exposition of util.metrics.REGISTRY
- ``/healthz``   liveness ("ok")
- ``/readyz``    readiness (caller-supplied probe)
- ``/debug/threads``  stack dump of every thread (the pprof-goroutine analog;
  the first place to look when a Permit barrier hangs)
- ``/debug/trace``  last N cycle traces from the flight recorder
  (``?n=``, ``?pod=`` substring filter, ``?format=perfetto`` for a
  Chrome/Perfetto trace-event document)
- ``/debug/gangs``  per-PodGroup stitched gang traces (critical path,
  permit barrier, stragglers, per-member attribution)
- ``/debug/flightrecorder``  the full dump: stats + ring + pinned anomaly
  traces + gangs — a wedged gang is explainable from this one document
- ``/debug/explain``  the why-pending diagnosis engine (tpusched/obs):
  ``?pod=`` / ``?gang=`` → rolling rejection aggregate + blocking plugin
  + suggested unblock signal; no argument → cluster top-blockers + SLO
  summary (also served by ``python -m tpusched.cmd.explain``)
- ``/debug/profile``  the hot-path sampling profiler (tpusched/obs/
  profiler): collapsed-stack (flamegraph-compatible) text of the rolling
  aggregate; ``?seconds=N`` collects a fresh window first (blocking, capped
  at 60 s); ``?format=json`` adds the top-N attribution table + sampler
  stats.  The same top-N table rides along in ``/debug/flightrecorder``'s
  health section.
- ``/debug/fleetrace``  fleet trace capture status (tpusched/obs/
  fleetrace): armed/disarmed, trace directory, segments, bytes written,
  events by kind, queue depth and drop count.
- ``/debug/goodput``  gang runtime goodput telemetry (tpusched/obs/
  goodput): per-gang runtime health (rolling goodput, step skew,
  straggler attribution), aggregator stats, and the workload×generation
  throughput matrix; ``?gang=`` narrows to one gang's health document.
- ``/debug/``  the index: every registered debug endpoint with a
  one-line description (there are enough now that nothing short of this
  page enumerates them).
"""
from __future__ import annotations

import json
import sys
import threading
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from . import klog
from .metrics import REGISTRY


# The /debug/ index: one line per registered debug route.  Every route
# mounted in Handler.do_GET must appear here — the index test pins the
# two against each other so a new endpoint cannot ship unlisted.
DEBUG_ENDPOINTS = {
    "/debug/threads": "stack dump of every thread (the pprof-goroutine "
                      "analog; first stop for a hung permit barrier)",
    "/debug/trace": "last N flight-recorder cycle traces (?n=, ?pod= "
                    "substring, ?format=perfetto)",
    "/debug/gangs": "per-PodGroup stitched gang traces: critical path, "
                    "permit barrier, per-member attribution",
    "/debug/flightrecorder": "full flight-recorder dump: stats + ring + "
                             "pinned anomaly traces + health section",
    "/debug/explain": "why-pending / why-slow diagnosis (?pod=, ?gang=; "
                      "no argument = cluster top blockers + SLO summary)",
    "/debug/profile": "hot-path sampling profiler, flamegraph-collapsed "
                      "stacks (?seconds=N fresh window, ?format=json)",
    "/debug/fleetrace": "fleet trace capture status: armed, directory, "
                        "segments, events by kind, queue depth, drops",
    "/debug/goodput": "gang runtime goodput: per-gang health, straggler "
                      "attribution, workload×generation throughput "
                      "matrix (?gang= for one gang)",
    "/debug/timeline": "fleet health timeline: bounded time-series ring "
                       "over bind rate, pending depth, SLO burn, "
                       "fragmentation, conflicts (?window= seconds)",
    "/debug/incidents": "black-box incident bundles: sentinel firings + "
                        "bundle index (?id= for one full bundle)",
    "/debug/vars": "process variables (thread count)",
}


def _thread_dump() -> str:
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        name = t.name if t else f"thread-{ident}"
        daemon = t.daemon if t else "?"
        out.append(f"--- {name} (ident={ident} daemon={daemon}) ---")
        out.extend(l.rstrip() for l in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


class MetricsServer:
    """Serves the registry on <host>:<port>; port=0 picks a free one.
    Default bind is loopback (safe for local runs); in-cluster deployments
    scrape via ServiceMonitor and must bind 0.0.0.0 (--metrics-bind-address).

    ``recorder``: the flight recorder backing the /debug/trace,
    /debug/gangs and /debug/flightrecorder routes; None = resolve the
    process-global recorder at request time (so a bench/test that installs
    a fresh recorder is picked up without rebuilding the server)."""

    def __init__(self, port: int = 0,
                 ready_probe: Optional[Callable[[], bool]] = None,
                 host: str = "127.0.0.1", recorder=None):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    self._send(200, REGISTRY.expose(),
                               "text/plain; version=0.0.4")
                elif path == "/healthz":
                    self._send(200, "ok\n")
                elif path == "/readyz":
                    ready = server.ready_probe() if server.ready_probe else True
                    self._send(200 if ready else 503,
                               "ok\n" if ready else "not ready\n")
                elif path == "/debug/threads":
                    self._send(200, _thread_dump())
                elif path == "/debug/trace":
                    self._send_json(self._trace_payload(query))
                elif path == "/debug/gangs":
                    self._send_json({"gangs": server.recorder().gangs.dump()})
                elif path == "/debug/flightrecorder":
                    dump = server.recorder().dump()
                    # hot-path attribution rides along in the health
                    # section: a wedged-or-slow scheduler is explainable
                    # (and its cycle budget attributable) from ONE document
                    from .. import obs
                    # tpulint: disable=shadow-isolation — live debug
                    # surface; shadow schedulers never mount a server
                    prof = obs.default_profiler()
                    if prof.running:
                        dump.setdefault("health", {})["profiler"] = \
                            prof.health()
                    # native batched dispatch (ISSUE 16) counters as a
                    # health section: cycles/pods through the kernel,
                    # declines by reason, and the oracle-mismatch count
                    # that MUST stay 0 — the first read of the ops
                    # runbook's native-dispatch triage
                    from . import metrics as m
                    dump.setdefault("health", {})["native"] = {
                        "cycles_total":
                            m.native_dispatch_cycles_total.value(),
                        "pods_total":
                            m.native_dispatch_pods_total.value(),
                        "fallbacks_by_reason": {
                            k[0]: c.value() for k, c in
                            m.native_dispatch_fallbacks.children()
                            .items()},
                        "differential_mismatches_total":
                            m.native_dispatch_differential_mismatches
                            .value(),
                    }
                    self._send_json(dump)
                elif path == "/debug/profile":
                    code, body, ctype = self._profile_payload(query)
                    self._send(code, body, ctype)
                elif path == "/debug/explain":
                    code, payload = self._explain_payload(query)
                    self._send(code, json.dumps(payload) + "\n",
                               "application/json")
                elif path == "/debug/fleetrace":
                    from .. import obs
                    # tpulint: disable=shadow-isolation — live debug
                    # surface; shadow schedulers never mount a server
                    self._send_json(obs.default_fleetrecorder().status())
                elif path == "/debug/goodput":
                    code, payload = self._goodput_payload(query)
                    self._send(code, json.dumps(payload) + "\n",
                               "application/json")
                elif path == "/debug/timeline":
                    self._send_json(self._timeline_payload(query))
                elif path == "/debug/incidents":
                    code, payload = self._incidents_payload(query)
                    self._send(code, json.dumps(payload, default=str)
                               + "\n", "application/json")
                elif path in ("/debug", "/debug/"):
                    self._send_json({"endpoints": DEBUG_ENDPOINTS})
                elif path == "/debug/vars":
                    self._send(200, json.dumps(
                        {"threads": threading.active_count()}) + "\n",
                        "application/json")
                else:
                    self._send(404, "not found\n")

            def _profile_payload(self, query: str):
                """/debug/profile: collapsed stacks from the hot-path
                sampling profiler.  ``?seconds=N`` collects a fresh
                bounded window (blocking this handler thread — the server
                is threading, so /metrics stays live); default serves the
                rolling aggregate.  ``?format=json`` wraps collapsed text
                with the top-N attribution table + sampler stats."""
                from .. import obs
                qs = urllib.parse.parse_qs(query)
                # tpulint: disable=shadow-isolation — live debug surface,
                # same contract as default_engine in _explain_payload
                prof = obs.default_profiler()
                if not prof.running:
                    return (503, "profiler not running (TPUSCHED_PROFILE=0 "
                                 "or no live scheduler constructed yet)\n",
                            "text/plain")
                try:
                    seconds = float(qs["seconds"][0]) if "seconds" in qs \
                        else 0.0
                except ValueError:
                    seconds = 0.0
                if seconds > 0:
                    agg = prof.capture(min(seconds, 60.0))
                    if agg is None:
                        return (429, "too many concurrent capture windows; "
                                     "retry shortly or read the rolling "
                                     "aggregate (no ?seconds=)\n",
                                "text/plain")
                    collapsed = agg.collapsed()
                    top = agg.top_attribution(10)
                    stats = agg.stats()
                else:
                    collapsed = prof.collapsed()
                    top = prof.top_attribution(10)
                    stats = prof.stats()
                if qs.get("format", [""])[0] == "json":
                    return (200, json.dumps(
                        {"collapsed": collapsed, "top": top,
                         "stats": stats}) + "\n", "application/json")
                return 200, collapsed, "text/plain"

            def _goodput_payload(self, query: str):
                """/debug/goodput: the gang-runtime-health surface.
                Late-bound process-global aggregator (tpusched.obs) —
                same contract as the flight-recorder routes."""
                from .. import obs
                qs = urllib.parse.parse_qs(query)
                # tpulint: disable=shadow-isolation — the debug server
                # serves the LIVE process surfaces by contract; shadow
                # schedulers never mount an HTTP server
                agg = obs.default_goodput()
                gang = qs.get("gang", [None])[0]
                if gang is not None:
                    out = agg.gang_health(gang)
                    if out is None:
                        return 404, {"error": f"gang {gang!r} has no "
                                              "runtime reports (not "
                                              "running, torn down, or "
                                              "members never reported)"}
                    return 200, out
                return 200, agg.dump()

            def _timeline_payload(self, query: str):
                """/debug/timeline: the fleet health time-series ring
                (tpusched/obs/timeline.py).  ``?window=SECONDS`` bounds
                the returned samples; default is the full ring."""
                from .. import obs
                qs = urllib.parse.parse_qs(query)
                # tpulint: disable=shadow-isolation — the debug server
                # serves the LIVE process surfaces by contract; shadow
                # schedulers never mount an HTTP server
                tl = obs.default_timeline()
                try:
                    window = float(qs["window"][0]) if "window" in qs \
                        else None
                except ValueError:
                    window = None
                return tl.dump(window)

            def _incidents_payload(self, query: str):
                """/debug/incidents: the black-box bundle surface
                (tpusched/obs/incident.py) — sentinel state + bundle
                index; ``?id=`` serves one full bundle."""
                from .. import obs
                qs = urllib.parse.parse_qs(query)
                # tpulint: disable=shadow-isolation — the debug server
                # serves the LIVE process surfaces by contract; shadow
                # schedulers never mount an HTTP server
                mgr = obs.default_incidents()
                bundle_id = qs.get("id", [None])[0]
                if bundle_id is not None:
                    doc = mgr.get(bundle_id)
                    if doc is None:
                        return 404, {"error": f"no bundle {bundle_id!r} "
                                              "(evicted by the disk "
                                              "budget, or never written)"}
                    return 200, doc
                # tpulint: disable=shadow-isolation — live surface,
                # same contract as default_incidents above
                sentinel = obs.default_sentinel()
                return 200, {"stats": mgr.stats(),
                             "sentinel": sentinel.stats(),
                             "firings": sentinel.firings()[-32:],
                             "bundles": mgr.list()}

            def _explain_payload(self, query: str):
                """/debug/explain: the why-pending diagnosis surface.
                Late-bound process-global engine/SLO tracker (tpusched.obs)
                — same contract as the flight-recorder routes."""
                from .. import obs
                qs = urllib.parse.parse_qs(query)
                # tpulint: disable=shadow-isolation — the debug server
                # serves the LIVE process surfaces by contract; shadow
                # schedulers never mount an HTTP server
                engine = obs.default_engine()
                pod = qs.get("pod", [None])[0]
                gang = qs.get("gang", [None])[0]
                if pod is not None:
                    out = engine.explain_pod(pod)
                    if out is None:
                        return 404, {"error": f"no pending diagnosis for "
                                              f"pod {pod!r} (bound, "
                                              "deleted, or never seen)"}
                    return 200, out
                if gang is not None:
                    out = engine.explain_gang(gang)
                    if out is None:
                        # no pending diagnosis — the gang may be bound
                        # and RUNNING: answer with its runtime goodput
                        # health (straggler attribution) instead of the
                        # historical "no pending diagnosis" dead end
                        # tpulint: disable=shadow-isolation — live
                        # surface, same contract as default_engine above
                        run = obs.default_goodput().gang_health(gang)
                        if run is not None:
                            return 200, run
                        return 404, {"error": f"no pending diagnosis for "
                                              f"gang {gang!r}, and no "
                                              "runtime goodput reports "
                                              "(see /debug/goodput)"}
                    # stitch in the permit-barrier view when the flight
                    # recorder holds one (tracing may be off: optional)
                    gt = server.recorder().gangs.get(out["gang"])
                    if gt is not None:
                        gd = gt.to_dict()
                        out["permit_barrier"] = gd.get("permit_barrier")
                        out["members_seen_by_tracer"] = gd["members_seen"]
                    return 200, out
                dump = engine.dump()
                # tpulint: disable=shadow-isolation — live surface,
                # same contract as default_engine above
                dump["slo"] = obs.default_slo().summary()
                return 200, dump

            def _trace_payload(self, query: str):
                qs = urllib.parse.parse_qs(query)
                rec = server.recorder()
                try:
                    n = int(qs["n"][0]) if "n" in qs else None
                except ValueError:
                    n = None
                pod = qs.get("pod", [None])[0]
                if qs.get("format", [""])[0] == "perfetto":
                    from ..trace import export
                    traces = rec.traces()
                    pinned = rec.pinned_traces()
                    if pod:               # same filters as the JSON form
                        traces = [t for t in traces if pod in t.pod_key]
                        pinned = [t for t in pinned if pod in t.pod_key]
                    if n is not None:
                        traces = traces[-n:] if n > 0 else []
                    return export.to_perfetto(traces, pinned)
                return {"stats": rec.stats(), "cycles": rec.cycles(n, pod)}

            def _send_json(self, payload) -> None:
                self._send(200, json.dumps(payload) + "\n",
                           "application/json")

            def _send(self, code: int, body: str, ctype: str = "text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):  # route through klog, V(6)
                klog.V(6).info_s("http " + fmt % args)

        self.ready_probe = ready_probe
        self._recorder = recorder
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    def recorder(self):
        """The flight recorder serving /debug/* (late-bound global unless
        one was injected)."""
        if self._recorder is not None:
            return self._recorder
        from .. import trace
        # tpulint: disable=shadow-isolation — live debug surface;
        # shadows get private recorders injected at construction
        return trace.default_recorder()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="tpusched-metrics-http",
                                        daemon=True)
        self._thread.start()
        klog.info_s("metrics endpoint up", port=self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
