"""Observability HTTP endpoint: /metrics, /healthz, /debug/threads.

The reference inherits the kube-scheduler's serving stack — Prometheus
/metrics scraped via ServiceMonitor (/root/reference/config/prometheus/
monitor.yaml:4-22) and component-base /debug/pprof (SURVEY §5). This is the
rebuild's equivalent for its own binaries:

- ``/metrics``   Prometheus text exposition of util.metrics.REGISTRY
- ``/healthz``   liveness ("ok")
- ``/readyz``    readiness (caller-supplied probe)
- ``/debug/threads``  stack dump of every thread (the pprof-goroutine analog)
"""
from __future__ import annotations

import json
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from . import klog
from .metrics import REGISTRY


def _thread_dump() -> str:
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        name = t.name if t else f"thread-{ident}"
        daemon = t.daemon if t else "?"
        out.append(f"--- {name} (ident={ident} daemon={daemon}) ---")
        out.extend(l.rstrip() for l in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


class MetricsServer:
    """Serves the registry on <host>:<port>; port=0 picks a free one.
    Default bind is loopback (safe for local runs); in-cluster deployments
    scrape via ServiceMonitor and must bind 0.0.0.0 (--metrics-bind-address)."""

    def __init__(self, port: int = 0,
                 ready_probe: Optional[Callable[[], bool]] = None,
                 host: str = "127.0.0.1"):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, REGISTRY.expose(),
                               "text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    self._send(200, "ok\n")
                elif self.path == "/readyz":
                    ready = server.ready_probe() if server.ready_probe else True
                    self._send(200 if ready else 503,
                               "ok\n" if ready else "not ready\n")
                elif self.path == "/debug/threads":
                    self._send(200, _thread_dump())
                elif self.path == "/debug/vars":
                    self._send(200, json.dumps(
                        {"threads": threading.active_count()}) + "\n",
                        "application/json")
                else:
                    self._send(404, "not found\n")

            def _send(self, code: int, body: str, ctype: str = "text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args):  # route through klog, V(6)
                klog.V(6).info_s("http " + fmt % args)

        self.ready_probe = ready_probe
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="tpusched-metrics-http",
                                        daemon=True)
        self._thread.start()
        klog.info_s("metrics endpoint up", port=self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
