"""Chunked thread-pool parallelism for the scheduling hot loop.

Rebuild of the upstream hosting loop's per-node parallelism
(/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/core/generic_scheduler.go:266,426
runs Filter and Score across nodes on 16 workers via
``workqueue.ParallelizeUntil``). Python's GIL changes the economics: pure-
Python plugin bodies serialize, but the native torus engine is called
through ctypes (which releases the GIL for the call) and numpy releases it
for vectorized work — so the pool buys real concurrency exactly where the
per-node cost is concentrated, and bounded overhead elsewhere. Chunking
keeps GIL handoffs amortized: each worker takes a contiguous chunk of the
index space, checking the early-stop predicate between items.
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

DEFAULT_PARALLELISM = 16  # upstream KubeSchedulerConfiguration default


class Parallelizer:
    """A persistent worker pool with ParallelizeUntil semantics.

    ``until(n, work, stop)`` invokes ``work(i)`` for i in [0, n) across the
    pool, skipping remaining items once ``stop()`` turns true (checked
    between items, so a bounded overshoot of in-flight items can still
    complete — same contract as upstream's context cancellation). Exceptions
    propagate to the caller after all workers settle.

    With ``workers <= 1`` everything runs inline on the caller thread —
    zero-overhead fallback for tiny clusters and deterministic tests.
    """

    def __init__(self, workers: int = 0):
        if workers <= 0:
            workers = min(DEFAULT_PARALLELISM, (os.cpu_count() or 4))
        self.workers = workers
        self._pool: Optional[ThreadPoolExecutor] = None
        self._lock = threading.Lock()
        self._tls = threading.local()

    def inline_scope(self):
        """Context manager forcing INLINE execution for until()/map() calls
        made from this thread while active.  Sharded dispatch lanes use it
        around their (partition-restricted, already-small) sweeps: the
        lanes themselves are the concurrency, and handing a 200-node
        pure-Python sweep to a shared pool under the GIL buys only
        future/chunk dispatch overhead and GIL handoffs."""
        par = self

        class _Inline:
            def __enter__(self):
                par._tls.inline = getattr(par._tls, "inline", 0) + 1

            def __exit__(self, *exc):
                par._tls.inline -= 1
                return False
        return _Inline()

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.workers,
                    thread_name_prefix="tpusched-par")
            return self._pool

    # below this many items the GIL makes pool dispatch pure overhead for
    # Python-level work; run inline (native/numpy-heavy callers still win
    # above it)
    INLINE_THRESHOLD = 128

    def until(self, n: int, work: Callable[[int], None],
              stop: Optional[Callable[[], bool]] = None) -> None:
        if n <= 0:
            return
        if self.workers <= 1 or n < self.INLINE_THRESHOLD \
                or getattr(self._tls, "inline", 0):
            for i in range(n):
                if stop is not None and stop():
                    return
                work(i)
            return
        pool = self._ensure_pool()
        # upstream chunk sizing: n / (workers*4) — small enough to balance;
        # floor 8 so task dispatch stays amortized under the GIL
        chunk = max(8, n // (self.workers * 4))
        starts = range(0, n, chunk)

        def run_chunk(lo: int) -> None:
            for i in range(lo, min(lo + chunk, n)):
                if stop is not None and stop():
                    return
                work(i)

        futures = [pool.submit(run_chunk, lo) for lo in starts]
        err = None
        for f in futures:
            try:
                f.result()
            except BaseException as e:  # keep draining so the pool settles
                if err is None:
                    err = e
        if err is not None:
            raise err

    def map(self, fn: Callable[[int], object], n: int) -> list:
        """Parallel [fn(0), …, fn(n−1)] with ordered results."""
        out = [None] * n

        def work(i: int) -> None:
            out[i] = fn(i)

        self.until(n, work)
        return out

    def close(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
