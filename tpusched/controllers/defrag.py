"""Defrag controller: opt-in, consent-gated actuation of migration plans.

The advisor (sim/defrag.py, KEP-302) answers *which gang migration would
admit a fragmentation-blocked job*; this controller closes the loop. The
reference ecosystem splits this role into a separate descheduler project
that evicts by policy and hopes the scheduler does better next time; here
the plan is verified on a shadow (real scheduler, zero mutation) BEFORE
anything is evicted, and nothing moves without consent:

- a gang is BLOCKED when it declares a slice shape and its members have
  been Pending for longer than ``blocked_after_s``;
- migration candidates are restricted to fully-bound gangs whose PodGroup
  carries ``defrag.tpu.dev/allow-migration: "true"`` — no workload moves
  because a controller thought it best;
- the plan trial forks a shadow, removes the candidate, and waits for the
  BLOCKED gang's own pending pods to bind there (no synthetic probe gang —
  a probe would race the real pending pods for the freed window), then
  re-places the migrant; only a plan where everyone lands is actuated;
- actuation = evict the migrant's pods and resubmit unbound copies; the
  real scheduler re-places the migrant while the freed window admits the
  blocked gang (reservation-release wakeups handle the requeue);
- rate-limited to one migration per ``cooldown_s``; ``dry_run`` logs the
  plan without acting.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api.scheduling import (PG_FINISHED, PG_FAILED, POD_GROUP_INDEX,
                              POD_GROUP_LABEL, pod_group_index_key)
from ..apiserver import Clientset, InformerFactory
from ..apiserver import server as srv
from ..plugins import default_registry
from ..sched import Scheduler
from ..sim.defrag import sanitize_for_resubmit
from ..sim.whatif import _make_profile, _shadow_of
from ..util import klog
from ..util.metrics import REGISTRY

ALLOW_MIGRATION_ANNOTATION = "defrag.tpu.dev/allow-migration"

defrag_migrations_total = REGISTRY.counter(
    "tpusched_defrag_migrations_total",
    "Gangs migrated by the defrag controller.")


class DefragController:
    def __init__(self, api: srv.APIServer, *,
                 blocked_after_s: float = 60.0,
                 scan_interval_s: float = 15.0,
                 cooldown_s: float = 120.0,
                 shadow_timeout_s: float = 20.0,
                 dry_run: bool = False,
                 clock=time.time):
        self.api = api
        self.client = Clientset(api)
        self.informers = InformerFactory(api)
        self.blocked_after_s = blocked_after_s
        self.scan_interval_s = scan_interval_s
        self.cooldown_s = cooldown_s
        self.shadow_timeout_s = shadow_timeout_s
        self.dry_run = dry_run
        self.clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_actuation = 0.0
        self.migrations = 0            # actuations performed (tests/metrics)
        # actuations whose blocked gang did NOT take the freed window in
        # time (it was deep in gang-denial TTL / backoff): the migrant was
        # resubmitted, nothing was lost, but the actuation bought nothing.
        # Repeated misses for one gang under a small cooldown look like
        # eviction churn — watch this counter before lowering cooldown_s.
        self.window_misses = 0
        self.last_plan: Optional[dict] = None
        # negative trial cache: (blocked, candidate-unit) → rv at failure.
        # A failed shadow trial is deterministic for unchanged state, and a
        # trial costs a full shadow scheduler for up to shadow_timeout_s —
        # without this, one permanently-blocked gang re-burns every
        # candidate every scan forever
        self._failed_trials: Dict[Tuple[str, Tuple[str, ...]], int] = {}

        self.pg_informer = self.informers.podgroups()
        self.pod_informer = self.informers.pods()
        self.pod_informer.add_index(POD_GROUP_INDEX, pod_group_index_key)

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpusched-defrag")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # detach informers: the runner rebuilds controllers on every
        # leadership cycle; leaked watch handlers would process every
        # event forever (same discipline as PodGroupController.stop)
        self.informers.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.scan_interval_s):
            try:
                self.reconcile_once()
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                klog.error_s(e, "defrag reconcile failed")

    # -- reconcile ------------------------------------------------------------

    def reconcile_once(self) -> Optional[dict]:
        """One scan: find the oldest blocked slice gang, plan, (maybe) act.
        Returns the actuated (or dry-run) plan dict, None when idle."""
        if self.clock() - self._last_actuation < self.cooldown_s:
            return None
        # prune the negative trial cache: entries recorded against an older
        # store rv can never match again (the guard compares equality), and
        # keeping them would leak an entry per gang pair forever
        rv = self.api.current_resource_version()
        self._failed_trials = {k: v for k, v in self._failed_trials.items()
                               if v == rv}
        blocked = self._blocked_gangs()
        if not blocked:
            return None
        candidates = self._consenting_bound_gangs()
        if not candidates:
            return None
        for full, _age in blocked:
            plan = self._plan_for(full, candidates)
            if plan is None:
                continue
            self.last_plan = plan
            if self.dry_run:
                klog.info_s("defrag plan (dry-run)", blocked=full,
                            migrate=plan["migrate"])
                # rate-limit REPLANNING too: the plan is in last_plan, and
                # recomputing it every scan costs a shadow run
                self._last_actuation = self.clock()
                return plan
            self._actuate(plan)
            self._last_actuation = self.clock()
            return plan
        return None

    def _blocked_gangs(self) -> List[Tuple[str, float]]:
        """Slice gangs whose members are all still Pending past the
        threshold, oldest first."""
        now = self.clock()
        out = []
        for pg in self.pg_informer.items():
            if not pg.spec.tpu_slice_shape:
                continue
            if pg.status.phase in (PG_FINISHED, PG_FAILED):
                continue
            members = self.pod_informer.by_index(POD_GROUP_INDEX, pg.key)
            if not members or len(members) < pg.spec.min_member:
                continue               # not fully submitted: not our case
            if any(p.spec.node_name for p in members):
                continue               # partially bound: scheduler's business
            # age of the NEWEST member: the gang is blocked only since its
            # last pod arrived (gang admission can't start before that)
            age = now - max(p.meta.creation_timestamp for p in members)
            if age >= self.blocked_after_s:
                out.append((pg.key, age))
        out.sort(key=lambda t: -t[1])
        return out

    def _consenting_bound_gangs(self) -> List[Tuple[Tuple[str, ...], int]]:
        """Migration UNITS: (gang full names, combined chip footprint),
        smallest first. The unit grouping — a plain gang is a unit of one,
        an atomic multislice set is ONE unit or none (half-migrating a
        bound set would strand the surviving slices, the same law the set
        disruption floor enforces for preemption) — is the advisor's
        ``_resident_units``; this controller only adds the consent filter,
        so the two can never drift on what counts as migratable."""
        from ..sim.defrag import _resident_units
        consent = {pg.key for pg in self.pg_informer.items()
                   if pg.meta.annotations.get(
                       ALLOW_MIGRATION_ANNOTATION, "") == "true"}
        if not consent:
            return []
        out = []
        for unit in _resident_units(self.api):
            names = tuple(g[0] for g in unit)
            if all(n in consent for n in names):
                out.append((names, sum(g[2] for g in unit)))
        return out

    # -- planning -------------------------------------------------------------

    def _plan_for(self, blocked_full: str,
                  candidates: List[Tuple[Tuple[str, ...], int]]
                  ) -> Optional[dict]:
        """Shadow-trial each candidate UNIT (cheapest first): remove every
        gang in the unit, wait for the blocked gang's OWN pending pods to
        bind, re-place the migrants (atomic sets re-admit through their own
        barrier in the shadow, so a unit whose set cannot re-land whole is
        rejected). Returns {blocked, migrate: [fulls...], chips} or None."""
        blocked_keys = [p.meta.key for p in self.pod_informer.by_index(
            POD_GROUP_INDEX, blocked_full)]
        profile = _make_profile(False, self.shadow_timeout_s)
        rv = self.api.current_resource_version()
        for unit, unit_chips in candidates:
            if blocked_full in unit:
                continue
            if self._failed_trials.get((blocked_full, unit)) == rv:
                continue   # state unchanged since this trial failed
            fork = _shadow_of(self.api, None)
            moved = []     # (full, pg, pods) per gang in the unit
            for cand_full in unit:
                cns, cname = cand_full.split("/", 1)
                pods = [p for p in fork.list(srv.PODS, cns)
                        if p.meta.labels.get(POD_GROUP_LABEL) == cname]
                pg = fork.try_get(srv.POD_GROUPS, cand_full)
                for p in pods:
                    fork.delete(srv.PODS, p.meta.key)
                if pg is not None:
                    fork.delete(srv.POD_GROUPS, cand_full)
                moved.append((cand_full, pg, pods))
            sched = Scheduler(fork, default_registry(), profile,
                              telemetry=False)
            sched.run()
            try:
                if not self._wait_bound(fork, blocked_keys):
                    self._failed_trials[(blocked_full, unit)] = rv
                    continue
                keys = []
                for _full, pg, pods in moved:
                    if pg is not None:
                        pg.meta.resource_version = 0
                        fork.create(srv.POD_GROUPS, pg)
                    for p in pods:
                        q = sanitize_for_resubmit(p)
                        fork.create(srv.PODS, q)
                        keys.append(q.meta.key)
                if not self._wait_bound(fork, keys):
                    # a migrant would be homeless: not a plan
                    self._failed_trials[(blocked_full, unit)] = rv
                    continue
                return {"blocked": blocked_full, "migrate": list(unit),
                        "chips": unit_chips}
            finally:
                sched.stop()
        return None

    def _wait_bound(self, fork, keys: List[str]) -> bool:
        deadline = time.monotonic() + self.shadow_timeout_s
        while time.monotonic() < deadline:
            if self._stop.is_set():
                return False
            live = [fork.peek(srv.PODS, k) for k in keys]
            if all(p is not None and p.spec.node_name for p in live):
                return True
            time.sleep(0.02)
        return False

    # -- actuation ------------------------------------------------------------

    def _actuate(self, plan: dict) -> None:
        """Evict the migrant, wait for the blocked gang to take the freed
        window, THEN resubmit the migrant — the same sequencing the shadow
        trial verified. Resubmitting immediately would race the blocked
        gang for the window it just vacated (the migrant is smaller and
        off backoff, so it tends to win and re-fragment the pool). The
        migrant is resubmitted even if the blocked gang misses its wait —
        losing a consenting workload is never acceptable."""
        unit = plan["migrate"]
        moved = []
        for cand_full in unit:
            cns, cname = cand_full.split("/", 1)
            moved += [p for p in self.api.list(srv.PODS, cns)
                      if p.meta.labels.get(POD_GROUP_LABEL) == cname]
        klog.info_s("defrag actuation: migrating unit", gangs=unit,
                    members=len(moved), toAdmit=plan["blocked"])
        resubmit = []
        for p in moved:
            resubmit.append(sanitize_for_resubmit(p))
            try:
                self.api.delete(srv.PODS, p.meta.key)
            except srv.NotFound:
                pass
            self.client.record_event(
                p.meta.key, "Pod", "Normal", "DefragMigrated",
                f"migrated to admit blocked gang {plan['blocked']}")
        blocked_keys = [p.meta.key for p in self.pod_informer.by_index(
            POD_GROUP_INDEX, plan["blocked"])]
        if not self._wait_bound(self.api, blocked_keys):
            self.window_misses += 1
            klog.error_s(None, "blocked gang missed the freed window; "
                         "resubmitting the migrants anyway",
                         blocked=plan["blocked"], migrated=unit,
                         windowMisses=self.window_misses)
        for q in resubmit:
            # fault-tolerant per pod: eviction already happened — one
            # failed create (a Conflict from an external recreate during
            # the wait window) must not strand the REST of the gang
            try:
                self.api.create(srv.PODS, q)
            except Exception as e:  # noqa: BLE001
                pg_name = q.meta.labels.get(POD_GROUP_LABEL, "")
                klog.error_s(e, "defrag resubmit failed for pod",
                             pod=q.meta.key,
                             gang=f"{q.meta.namespace}/{pg_name}")
        self.migrations += 1
        defrag_migrations_total.inc()
