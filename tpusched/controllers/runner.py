"""Controller runner: options + leader election + lifecycle.

Rebuild of /root/reference/cmd/controller/app (options.go:23-52, server.go:55-129):
builds clients/informers, optionally campaigns for a coordination lease named
"sched-plugins-controller" and only runs controllers while leading; exits
leadership cleanly on stop. QPS/burst mirror the controller API budget
(defaults qps=5 burst=10 workers=1, options.go:43-45).
"""
from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass

from ..apiserver import server as srv
from ..util import klog
from .elasticquota import ElasticQuotaController
from .podgroup import PodGroupController

LEASE_NAME = "sched-plugins-controller"


@dataclass
class ServerRunOptions:
    """options.go:39-47 (kubeconfig/in-cluster flags are meaningless against
    the in-memory server and intentionally absent)."""
    api_qps: float = 5.0
    api_burst: int = 10
    workers: int = 1
    enable_leader_election: bool = False
    lease_duration_s: float = 15.0
    renew_interval_s: float = 5.0
    # defrag actuation (controllers/defrag.py) is opt-in twice over: the
    # flag enables the controller, and each migrated gang must carry the
    # consent annotation. dry-run plans without evicting.
    enable_defrag: bool = False
    defrag_dry_run: bool = False
    defrag_blocked_after_s: float = 60.0
    defrag_cooldown_s: float = 120.0
    # node & slice failure resilience: heartbeat-driven node health +
    # eviction (nodelifecycle.py) and gang-granular repair after hardware
    # loss (gangrepair.py). On by default — a fleet without them assumes
    # immortal hardware.
    enable_node_lifecycle: bool = True
    node_heartbeat_grace_s: float = 10.0
    node_pod_eviction_grace_s: float = 30.0
    enable_gang_repair: bool = True
    gang_repair_cooldown_s: float = 1.0


class ControllerRunner:
    def __init__(self, api: srv.APIServer,
                 options: ServerRunOptions = ServerRunOptions()):
        self.api = api
        self.options = options
        self.identity = f"controller-{uuid.uuid4().hex[:8]}"
        self._stop = threading.Event()
        self._thread = None
        self._controllers = []
        self.is_leader = threading.Event()

    def run(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="controller-runner")
        self._thread.start()

    def _run(self) -> None:
        if self.options.enable_leader_election:
            # campaign; block until we acquire the lease (server.go:84-123)
            while not self._stop.is_set():
                if self.api.acquire_or_renew_lease(
                        LEASE_NAME, self.identity, self.options.lease_duration_s):
                    break
                time.sleep(self.options.renew_interval_s / 5)
            if self._stop.is_set():
                return
            klog.info_s("started leading", identity=self.identity)
        self.is_leader.set()
        self._start_controllers()
        if self.options.enable_leader_election:
            # renew loop; losing the lease means exit (exit-on-lost-lease)
            while not self._stop.is_set():
                if not self.api.acquire_or_renew_lease(
                        LEASE_NAME, self.identity, self.options.lease_duration_s):
                    klog.error_s(None, "leader election lost; stopping controllers",
                                 identity=self.identity)
                    break
                time.sleep(self.options.renew_interval_s)
            self._stop_controllers()
            self.is_leader.clear()

    def _start_controllers(self) -> None:
        self._controllers = [
            PodGroupController(self.api, workers=self.options.workers),
            ElasticQuotaController(self.api, workers=self.options.workers),
        ]
        if self.options.enable_defrag:
            from .defrag import DefragController
            self._controllers.append(DefragController(
                self.api, dry_run=self.options.defrag_dry_run,
                blocked_after_s=self.options.defrag_blocked_after_s,
                cooldown_s=self.options.defrag_cooldown_s))
        if self.options.enable_node_lifecycle:
            from .nodelifecycle import NodeLifecycleController
            self._controllers.append(NodeLifecycleController(
                self.api,
                heartbeat_grace_s=self.options.node_heartbeat_grace_s,
                pod_eviction_grace_s=self.options.node_pod_eviction_grace_s))
        if self.options.enable_gang_repair:
            from .gangrepair import GangRepairController
            self._controllers.append(GangRepairController(
                self.api, workers=self.options.workers,
                cooldown_s=self.options.gang_repair_cooldown_s))
        for c in self._controllers:
            c.run()

    def _stop_controllers(self) -> None:
        for c in self._controllers:
            c.stop()
        self._controllers = []

    def stop(self) -> None:
        self._stop.set()
        self._stop_controllers()
        if self._thread:
            self._thread.join(timeout=5)
