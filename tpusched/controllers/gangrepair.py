"""Gang repair controller: gang-granular reaction to hardware loss.

The "repair" stage of the node-failure pipeline (nodelifecycle.py detects,
this controller repairs, the scheduler reschedules). When a PodGroup loses
bound members to a dead/NotReady node (the member pods were evicted by the
lifecycle controller, or orphan-GC'd after a node kill), the gang is not
left to wedge half-alive: honoring a per-PG repair policy the controller
re-establishes the gang's desired member set and lets the coscheduling
barrier re-admit it atomically on healthy hardware.

Policies (annotation ``repair-policy.scheduling.tpu.dev`` on the PodGroup):

- ``restart-gang`` (default): evict the surviving BOUND members too, then
  recreate every lost/evicted member fresh — mirroring the all-or-nothing
  semantics of coscheduling/multislice (and JobSet's RecreateAll failure
  policy): a TPU training gang that lost a slice host restarts from its
  checkpoint anyway, and survivors squatting their chips only strand
  capacity the retry needs.
- ``backfill``: keep bound survivors, recreate only the lost members; the
  permit barrier counts survivors toward quorum so only the replacements
  re-schedule (a serving gang whose members are independent prefers this).

Member specs are captured from the pods themselves when first seen (the
workload controller's desired-state analog — there is no Job template in
this control plane). Only LOSSES ATTRIBUTED TO HARDWARE trigger repair: a
pod deleted while its node was healthy is user intent, and its template is
dropped instead of resurrected.

PG phase is reset through the normal status patch path so the PodGroup
controller's phase machine stays the single owner of forward transitions.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .. import trace
from ..api.core import Pod, node_health_error
from ..api.meta import ObjectMeta
from ..api.scheduling import (PG_FAILED, PG_FINISHED, PG_PENDING,
                              PG_SCHEDULING, POD_GROUP_INDEX, PodGroup,
                              pod_group_full_name, pod_group_index_key)
from ..apiserver import Clientset, InformerFactory
from ..apiserver import server as srv
from ..util import klog
from ..util.metrics import gang_repairs
from ..util.podutil import assigned
from .workqueue import WorkQueue

REPAIR_POLICY_ANNOTATION = "repair-policy.scheduling.tpu.dev"
REPAIR_RESTART_GANG = "restart-gang"
REPAIR_BACKFILL = "backfill"

# Annotations the scheduler writes at Reserve time — a recreated member must
# shed them or the chip/coordinate model would read stale placement facts.
_SCHEDULER_ANNOTATIONS = (
    "tpuslice.scheduling.tpu.dev/chip-index",
    "topology.tpu.dev/coord",
    "topology.tpu.dev/pool",
)


def _sanitize_template(pod: Pod) -> Pod:
    """A clean, unbound copy of a member pod suitable for recreation."""
    t = pod.deepcopy()
    t.spec.node_name = ""
    t.status = type(t.status)()
    for k in _SCHEDULER_ANNOTATIONS:
        t.meta.annotations.pop(k, None)
    return t


def _fresh_member(template: Pod) -> Pod:
    """A recreate-able pod: template spec under a brand-new ObjectMeta
    (fresh uid/resourceVersion — the old identity died with the node)."""
    t = _sanitize_template(template)
    t.meta = ObjectMeta(name=t.meta.name, namespace=t.meta.namespace,
                        labels=dict(t.meta.labels),
                        annotations=dict(t.meta.annotations),
                        owner_references=list(t.meta.owner_references))
    return t


class GangRepairController:
    def __init__(self, api: srv.APIServer, workers: int = 1,
                 cooldown_s: float = 1.0, clock=time.time):
        self.api = api
        self.client = Clientset(api)
        self.informers = InformerFactory(api)
        self.queue = WorkQueue()
        self.workers = workers
        self.cooldown_s = cooldown_s
        self.clock = clock
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        # pg_key → {member name: clean template}
        self._templates: Dict[str, Dict[str, Pod]] = {}
        # pg_key → {member names lost to dead hardware, pending repair}
        self._lost: Dict[str, set] = {}
        self._last_repair: Dict[str, float] = {}
        # pod keys the repair itself is deleting: their DELETE events must
        # not be read as user intent (which would drop the template the
        # recreate right behind the eviction needs)
        self._evicting: set = set()

        self.pg_informer = self.informers.podgroups()
        self.node_informer = self.informers.nodes()
        self.pod_informer = self.informers.pods()
        self.pod_informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
        self.pod_informer.add_event_handler(on_add=self._pod_added,
                                            on_delete=self._pod_deleted)
        self.pg_informer.add_event_handler(
            on_delete=lambda pg: self._forget(pg.key), replay=False)

    # -- event handlers -------------------------------------------------------

    def _pod_added(self, pod: Pod) -> None:
        pg_key = pod_group_full_name(pod)
        if not pg_key:
            return
        with self._lock:
            members = self._templates.setdefault(pg_key, {})
            if pod.name not in members or not assigned(pod):
                # prefer the unbound shape; a bound-first sighting (controller
                # started late) is sanitized on capture
                members[pod.name] = _sanitize_template(pod)
            # a member re-appearing (repair's own recreate, or user resubmit)
            # is no longer lost
            lost = self._lost.get(pg_key)
            if lost:
                lost.discard(pod.name)

    def _pod_deleted(self, pod: Pod) -> None:
        pg_key = pod_group_full_name(pod)
        if not pg_key:
            return
        with self._lock:
            if pod.key in self._evicting:
                self._evicting.discard(pod.key)
                return
        if not assigned(pod) or pod.status.phase in ("Succeeded", "Failed"):
            # unbound deletion or a finished member: user/workload intent —
            # never resurrect it
            with self._lock:
                members = self._templates.get(pg_key)
                if members:
                    members.pop(pod.name, None)
            return
        node = self.node_informer.get(f"/{pod.spec.node_name}")
        hardware_loss = node is None or node_health_error(node) is not None
        if not hardware_loss:
            with self._lock:
                members = self._templates.get(pg_key)
                if members:
                    members.pop(pod.name, None)
            return
        with self._lock:
            self._lost.setdefault(pg_key, set()).add(pod.name)
        klog.warning_s("gang member lost to dead hardware", pod=pod.key,
                       node=pod.spec.node_name, gang=pg_key)
        self.queue.add(pg_key)

    def _forget(self, pg_key: str) -> None:
        with self._lock:
            self._templates.pop(pg_key, None)
            self._lost.pop(pg_key, None)
            self._last_repair.pop(pg_key, None)

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"gang-repair-{i}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()
        for t in self._threads:
            t.join(timeout=5)
        self.informers.close()

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                err = self.sync(key)
                if err is None:
                    self.queue.forget(key)
                else:
                    klog.error_s(err, "gang repair sync failed", podGroup=key)
                    self.queue.add_rate_limited(key)
            except Exception as e:
                klog.error_s(e, "gang repair sync panicked", podGroup=key)
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)

    # -- repair ---------------------------------------------------------------

    def sync(self, pg_key: str) -> Optional[Exception]:
        pg = self.pg_informer.get(pg_key)
        if pg is None:
            self._forget(pg_key)
            return None
        if pg.status.phase in (PG_FINISHED, PG_FAILED):
            self._forget(pg_key)
            return None
        with self._lock:
            lost = set(self._lost.get(pg_key) or ())
            templates = dict(self._templates.get(pg_key) or {})
        if not lost:
            return None
        now = self.clock()
        with self._lock:
            last = self._last_repair.get(pg_key, 0.0)
        remaining = self.cooldown_s - (now - last)
        if remaining > 0:
            # a burst of eviction events for one failure = one repair: one
            # delayed requeue at cooldown lapse, not a rate-limited
            # busy-poll through the whole window
            self.queue.add_after(pg_key, remaining + 0.01)
            return None

        live = {p.name: p for p in
                self.pod_informer.by_index(POD_GROUP_INDEX, pg_key)}
        missing = [n for n in lost if n not in live and n in templates]
        unknown = [n for n in lost if n not in live and n not in templates]
        if unknown:
            klog.error_s(None, "lost gang members have no captured template",
                         podGroup=pg_key, members=len(unknown))
        if not missing:
            with self._lock:
                if self._lost.get(pg_key) is not None:
                    self._lost[pg_key] -= lost
            return None

        policy = pg.meta.annotations.get(REPAIR_POLICY_ANNOTATION,
                                         REPAIR_RESTART_GANG)
        if policy not in (REPAIR_RESTART_GANG, REPAIR_BACKFILL):
            policy = REPAIR_RESTART_GANG

        evicted: List[str] = []
        recreate = list(missing)
        if policy == REPAIR_RESTART_GANG:
            # all-or-nothing: bound survivors restart with the gang
            for name, p in live.items():
                if assigned(p):
                    with self._lock:
                        self._evicting.add(p.key)
                    try:
                        # uid precondition: never kill a same-name pod that
                        # replaced the survivor we observed
                        self.client.pods.delete(p.key, uid=p.meta.uid)
                    except (srv.NotFound, srv.Conflict):
                        with self._lock:
                            self._evicting.discard(p.key)
                    except Exception as e:  # noqa: BLE001
                        with self._lock:
                            self._evicting.discard(p.key)
                        return e
                    evicted.append(name)
                    if name in templates:
                        recreate.append(name)
                        # the evicted survivor is now a loss too: if this
                        # sync fails before its recreate lands, the retry
                        # must still recreate it (the successful create's
                        # ADD event discards it from _lost again)
                        with self._lock:
                            self._lost.setdefault(pg_key, set()).add(name)

        err = self._reset_pg_status(pg_key, policy)
        if err is not None:
            return err

        for name in recreate:
            fresh = _fresh_member(templates[name])
            try:
                self.client.pods.create(fresh)
            except srv.Conflict:
                pass        # already recreated (competing worker / resubmit)
            except Exception as e:  # noqa: BLE001
                return e

        with self._lock:
            if pg_key in self._lost:
                self._lost[pg_key] -= lost
            # under the same lock as _forget: a PG deleted mid-sync must
            # not have its just-popped entry resurrected (and then leaked)
            if (pg_key in self._templates or pg_key in self._lost
                    or self.pg_informer.get(pg_key) is not None):
                self._last_repair[pg_key] = now
        gang_repairs.inc()
        trace.pin_event("gang_repair", subject=pg_key, gang_name=pg_key,
                        policy=policy, lost=len(missing),
                        evicted_survivors=len(evicted),
                        recreated=len(recreate))
        self.client.record_event(
            pg_key, "PodGroup", "Warning", "GangRepair",
            f"policy={policy} lost={sorted(missing)} "
            f"evicted={sorted(evicted)} recreated={len(recreate)}")
        klog.warning_s("gang repaired after hardware loss", gang=pg_key,
                       policy=policy, lost=len(missing),
                       evicted_survivors=len(evicted))
        return None

    def _reset_pg_status(self, pg_key: str, policy: str) -> Optional[Exception]:
        """Rewind the PG phase machine so the gang re-admits: restart-gang
        goes back to Pending with a zeroed scheduled count; backfill stays
        Scheduling with scheduled reflecting the bound survivors (the
        scheduler's PostBind re-increments as replacements bind)."""
        # counted BEFORE the patch: mutate runs under the store lock and
        # must stay pure (no informer reads inside it)
        bound = sum(1 for p in self.pod_informer.by_index(
            POD_GROUP_INDEX, pg_key) if assigned(p))

        def mutate(g: PodGroup):
            if policy == REPAIR_RESTART_GANG:
                g.status.phase = PG_PENDING
                g.status.scheduled = 0
            else:
                g.status.phase = PG_SCHEDULING
                g.status.scheduled = bound
            g.status.schedule_start_time = None
        try:
            self.client.podgroups.patch(pg_key, mutate)
        except srv.NotFound:
            return None
        except Exception as e:  # noqa: BLE001
            return e
        return None
