"""ElasticQuota controller: recomputes status.used.

Rebuild of /root/reference/pkg/controller/elasticquota.go: on any EQ or pod
event, used = Σ effective requests of Running pods in the namespace
(:212-224), zeroed over the union of min/max resource names; merge-patch if
changed (:168-210); emits Event "Synced" (:208). One EQ per namespace
(reference assumption, :264-265 — preserved deliberately).
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..api.core import POD_RUNNING, Pod
from ..api.scheduling import ElasticQuota
from ..apiserver import Clientset, InformerFactory
from ..apiserver import server as srv
from ..util import klog
from ..util.podutil import pod_effective_request
from .workqueue import WorkQueue


class ElasticQuotaController:
    def __init__(self, api: srv.APIServer, workers: int = 1):
        self.api = api
        self.client = Clientset(api)
        self.informers = InformerFactory(api)
        self.queue = WorkQueue()
        self.workers = workers
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

        self.eq_informer = self.informers.elasticquotas()
        self.pod_informer = self.informers.pods()
        self.eq_informer.add_event_handler(
            on_add=self._eq_changed,
            on_update=lambda old, new: self._eq_changed(new),
            on_delete=self._eq_changed)
        self.pod_informer.add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_changed)

    def _eq_changed(self, eq: ElasticQuota) -> None:
        self.queue.add_rate_limited(eq.key)

    def _pod_changed(self, pod: Pod) -> None:
        eqs = self.eq_informer.items(namespace=pod.namespace)
        if eqs:
            # one EQ per namespace (reference assumption)
            self._eq_changed(eqs[0])

    def run(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"eq-controller-{i}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()
        for t in self._threads:
            t.join(timeout=5)
        # detach from the watch fan-out: a stopped controller (e.g. after
        # losing the leader lease) must not keep enqueueing into a queue
        # no worker drains
        self.informers.close()

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                err = self.sync_handler(key)
                if err is None:
                    self.queue.forget(key)
                else:
                    klog.error_s(err, "error syncing elastic quota", eq=key)
                    self.queue.add_rate_limited(key)
            except Exception as e:
                klog.error_s(e, "sync panicked", eq=key)
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)

    def sync_handler(self, key: str) -> Optional[Exception]:
        eq = self.eq_informer.get(key)
        if eq is None:
            return None
        used = self._compute_used(eq)
        if used == eq.status.used:
            return None
        try:
            def mutate(e: ElasticQuota):
                e.status.used = used
            self.client.elasticquotas.patch(key, mutate)
            self.client.record_event(key, "ElasticQuota", "Normal", "Synced",
                                     f"ElasticQuota {key} synced successfully")
        except srv.NotFound:
            return None
        except Exception as e:
            return e
        return None

    def _compute_used(self, eq: ElasticQuota) -> dict:
        # zero-valued entries for every resource named in min/max, so scale-down
        # to zero is visible in the patch (newZeroUsed, elasticquota.go)
        used = {k: 0 for k in set(eq.spec.min) | set(eq.spec.max)}
        for pod in self.pod_informer.items(namespace=eq.meta.namespace):
            if pod.status.phase == POD_RUNNING:
                for k, v in pod_effective_request(pod).items():
                    used[k] = used.get(k, 0) + v
        return used
