"""Async controllers (reference analog: /root/reference/pkg/controller +
cmd/controller/app): PodGroup phase machine, ElasticQuota usage accounting,
node lifecycle (heartbeat health + eviction), gang repair after hardware
loss, workqueue plumbing, and the runner with leader election."""
from .workqueue import WorkQueue
from .podgroup import PodGroupController
from .elasticquota import ElasticQuotaController
from .nodelifecycle import NodeLifecycleController
from .gangrepair import (GangRepairController, REPAIR_BACKFILL,
                         REPAIR_POLICY_ANNOTATION, REPAIR_RESTART_GANG)
from .runner import ControllerRunner, ServerRunOptions

__all__ = ["WorkQueue", "PodGroupController", "ElasticQuotaController",
           "NodeLifecycleController", "GangRepairController",
           "REPAIR_POLICY_ANNOTATION", "REPAIR_RESTART_GANG",
           "REPAIR_BACKFILL", "ControllerRunner", "ServerRunOptions"]
