"""Async controllers (reference analog: /root/reference/pkg/controller +
cmd/controller/app): PodGroup phase machine, ElasticQuota usage accounting,
workqueue plumbing, and the runner with leader election."""
from .workqueue import WorkQueue
from .podgroup import PodGroupController
from .elasticquota import ElasticQuotaController
from .runner import ControllerRunner, ServerRunOptions

__all__ = ["WorkQueue", "PodGroupController", "ElasticQuotaController",
           "ControllerRunner", "ServerRunOptions"]
