"""PodGroup controller: drives PodGroup.Status.Phase.

Rebuild of /root/reference/pkg/controller/podgroup.go: workqueue fed by PG and
member-pod events (:112-155); syncHandler phase machine (:185-273):
"" → Pending → PreScheduling (≥MinMember pods exist; fills OccupiedBy from
owner refs :291-303) → Scheduling/Scheduled (set by the coscheduling plugin's
PostBind) → Running → Finished/Failed by counting member pod phases;
merge-patches status (:275-289); skips groups stuck >48h (:122-126).
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..api.core import POD_FAILED, POD_RUNNING, POD_SUCCEEDED, Pod
from ..api.scheduling import (PG_FAILED, PG_FINISHED, PG_PENDING,
                              PG_PRE_SCHEDULING, PG_RUNNING, PG_SCHEDULED,
                              PG_SCHEDULING, POD_GROUP_INDEX, PodGroup,
                              pod_group_index_key, pod_group_label)
from ..apiserver import Clientset, InformerFactory
from ..apiserver import server as srv
from ..util import klog
from .workqueue import WorkQueue

STUCK_GROUP_MAX_AGE_S = 48 * 3600.0


class PodGroupController:
    def __init__(self, api: srv.APIServer, workers: int = 1, clock=time.time):
        self.api = api
        self.client = Clientset(api)
        self.informers = InformerFactory(api)
        self.queue = WorkQueue()
        self.workers = workers
        self.clock = clock
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

        self.pg_informer = self.informers.podgroups()
        self.pod_informer = self.informers.pods()
        self.pod_informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
        self.pg_informer.add_event_handler(on_add=self._pg_added,
                                           on_update=lambda old, new: self._pg_added(new))
        self.pod_informer.add_event_handler(on_add=self._pod_added,
                                            on_update=lambda old, new: self._pod_added(new))

    # -- event handlers (podgroup.go:112-155) ---------------------------------

    def _pg_added(self, pg: PodGroup) -> None:
        if pg.status.phase in (PG_FINISHED, PG_FAILED):
            return
        # skip groups whose scheduling started >48h after creation (pods GCed)
        if (pg.status.scheduled == pg.spec.min_member and pg.status.running == 0
                and pg.status.schedule_start_time is not None
                and pg.status.schedule_start_time - pg.meta.creation_timestamp
                > STUCK_GROUP_MAX_AGE_S):
            return
        klog.V(5).info_s("enqueue podGroup", podGroup=pg.key)
        self.queue.add(pg.key)

    def _pod_added(self, pod: Pod) -> None:
        pg_name = pod_group_label(pod)
        if not pg_name:
            return
        pg = self.pg_informer.get(f"{pod.namespace}/{pg_name}")
        if pg is None:
            return
        self._pg_added(pg)

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"pg-controller-{i}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()
        for t in self._threads:
            t.join(timeout=5)
        # detach from the watch fan-out: a stopped controller (e.g. after
        # losing the leader lease) must not keep enqueueing into a queue
        # no worker drains
        self.informers.close()

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                err = self.sync_handler(key)
                if err is None:
                    self.queue.forget(key)
                else:
                    klog.error_s(err, "error syncing pod group", podGroup=key)
                    self.queue.add_rate_limited(key)
            except Exception as e:
                klog.error_s(e, "sync panicked", podGroup=key)
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)

    # -- phase machine (podgroup.go:185-273) ----------------------------------

    def sync_handler(self, key: str) -> Optional[Exception]:
        pg = self.pg_informer.get(key)
        if pg is None:
            klog.V(5).info_s("pod group has been deleted", podGroup=key)
            return None
        pods = self.pod_informer.by_index(POD_GROUP_INDEX, key)

        # The phase machine runs INSIDE the atomic patch, against the live
        # object — never writing status.scheduled (owned by the scheduler's
        # PostBind). The reference survives the equivalent race only because
        # its merge patch sends changed fields; replacing the whole status
        # from a stale read would clobber concurrent scheduled-count patches.
        probe = pg.deepcopy()
        self._apply_phase_machine(probe, pods)
        if probe.status == pg.status:
            return None  # avoid patch→event→resync loops
        try:
            self.client.podgroups.patch(
                key, lambda live: self._apply_phase_machine(live, pods))
        except srv.NotFound:
            return None
        except Exception as e:
            return e
        return None

    def _apply_phase_machine(self, pg: PodGroup, pods: List[Pod]) -> None:
        st = pg.status
        if st.phase == "":
            st.phase = PG_PENDING
            return
        if st.phase == PG_PENDING:
            if len(pods) >= pg.spec.min_member:
                st.phase = PG_PRE_SCHEDULING
                self._fill_occupied(pg, pods[0])
            return
        st.running = sum(1 for p in pods if p.status.phase == POD_RUNNING)
        st.succeeded = sum(1 for p in pods if p.status.phase == POD_SUCCEEDED)
        st.failed = sum(1 for p in pods if p.status.phase == POD_FAILED)
        if not pods:
            st.phase = PG_PENDING
            return
        if st.scheduled >= pg.spec.min_member and st.phase == PG_SCHEDULING:
            st.phase = PG_SCHEDULED
        if (st.succeeded + st.running >= pg.spec.min_member
                and st.phase == PG_SCHEDULED):
            st.phase = PG_RUNNING
        # terminal states
        if st.failed and st.failed + st.running + st.succeeded >= pg.spec.min_member:
            st.phase = PG_FAILED
        if st.succeeded >= pg.spec.min_member:
            st.phase = PG_FINISHED

    def _fill_occupied(self, pg: PodGroup, pod: Pod) -> None:
        refs = sorted(f"{pod.namespace}/{ref.name}"
                      for ref in pod.meta.owner_references)
        if refs:
            pg.status.occupied_by = ";".join(refs)
