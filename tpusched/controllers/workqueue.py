"""Rate-limited workqueue — client-go workqueue semantics:

- an item present in the queue is deduplicated;
- an item being processed is not redelivered until done() — if re-added
  meanwhile it is requeued after done();
- add_rate_limited applies per-item exponential backoff (5ms base, 16s cap,
  client-go defaults); forget() resets the failure count.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Dict, Optional, Set

BASE_DELAY_S = 0.005
MAX_DELAY_S = 16.0


class WorkQueue:
    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._cond = threading.Condition()
        self._queue: list = []          # FIFO of ready items
        self._queued: Set[str] = set()
        self._processing: Set[str] = set()
        self._dirty: Set[str] = set()   # re-added while processing
        self._delayed: list = []        # (ready_time, seq, item)
        self._seq = itertools.count()
        self._failures: Dict[str, int] = {}
        self._shutdown = False

    def add(self, item: str) -> None:
        with self._cond:
            if item in self._queued:
                return
            if item in self._processing:
                self._dirty.add(item)
                return
            self._queued.add(item)
            self._queue.append(item)
            self._cond.notify()

    def add_after(self, item: str, delay: float) -> None:
        with self._cond:
            heapq.heappush(self._delayed, (self._clock() + delay,
                                           next(self._seq), item))
            self._cond.notify()

    def add_rate_limited(self, item: str) -> None:
        n = self._failures.get(item, 0)
        self._failures[item] = n + 1
        self.add_after(item, min(MAX_DELAY_S, BASE_DELAY_S * (2 ** n)))

    def forget(self, item: str) -> None:
        with self._cond:
            self._failures.pop(item, None)

    def _flush_delayed_locked(self) -> None:
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item not in self._queued and item not in self._processing:
                self._queued.add(item)
                self._queue.append(item)
            elif item in self._processing:
                self._dirty.add(item)

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                self._flush_delayed_locked()
                if self._shutdown:
                    return None
                if self._queue:
                    item = self._queue.pop(0)
                    self._queued.discard(item)
                    self._processing.add(item)
                    return item
                wait = 0.1
                if self._delayed:
                    wait = min(wait, max(0.0, self._delayed[0][0] - self._clock()))
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return None
                    wait = min(wait, remaining)
                self._cond.wait(wait)

    def done(self, item: str) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._queued:
                    self._queued.add(item)
                    self._queue.append(item)
                    self._cond.notify()

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)
