"""Node lifecycle controller: heartbeat-driven node health + pod eviction.

The slice of kube-controller-manager's node lifecycle controller the
scheduler's failure-resilience loop needs, TPU-flavored. Nodes that opt into
health management (``status.last_heartbeat_time`` set — TestCluster fixture
nodes without it are implicitly healthy forever) are swept on a short
period:

- heartbeat missed for ``heartbeat_grace_s``  ⇒ Ready=False condition +
  the ``node.tpu.dev/not-ready`` NoSchedule taint (placement-producing
  Filters also consult the condition directly via
  ``api.core.node_health_error``);
- heartbeat resumes                            ⇒ Ready=True, taint removed;
- NotReady persists for ``pod_eviction_grace_s`` ⇒ the node's bound pods are
  deleted (the k8s NoExecute eviction analog), which is what lets the gang
  repair controller re-place the gang on healthy hardware;
- a pod bound to a node that no longer EXISTS is deleted immediately
  (pod-GC orphan semantics): a killed node must not strand its gang.

Fleet papers (PAPERS.md, "Training Supercomputers…") make slice
failure-and-repair the dominant availability cost — this controller is the
"detect" stage of the detect→repair→reschedule pipeline; gangrepair.py is
the "repair" stage.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .. import trace
from ..api.core import (NODE_READY, Node, Pod, TAINT_NODE_NOT_READY, Taint,
                        node_ready)
from ..apiserver import Clientset, InformerFactory
from ..apiserver import server as srv
from ..util import klog
from ..util.metrics import (node_not_ready_transitions, node_pod_evictions,
                            nodes_not_ready)

# Pod-informer index on the bound-to node name: the eviction and orphan-GC
# sweeps visit O(affected) pods per tick instead of scanning the fleet.
POD_NODE_INDEX = "tpusched/pod-node"


def pod_node_index_key(pod) -> Optional[str]:
    return pod.spec.node_name or None


class NodeLifecycleController:
    def __init__(self, api: srv.APIServer, heartbeat_grace_s: float = 10.0,
                 pod_eviction_grace_s: float = 30.0,
                 sweep_interval_s: float = 1.0, clock=time.time):
        self.api = api
        self.client = Clientset(api)
        self.informers = InformerFactory(api)
        self.node_informer = self.informers.nodes()
        self.pod_informer = self.informers.pods()
        self.pod_informer.add_index(POD_NODE_INDEX, pod_node_index_key)
        self.heartbeat_grace_s = heartbeat_grace_s
        self.pod_eviction_grace_s = pod_eviction_grace_s
        self.sweep_interval_s = sweep_interval_s
        self.clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # NotReady-since per node (monotonic-free: the injected clock), kept
        # controller-local so a restart re-grants the eviction grace instead
        # of mass-evicting on the first sweep after recovery
        self._not_ready_since: Dict[str, float] = {}

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="node-lifecycle")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.informers.close()

    def _run(self) -> None:
        while not self._stop.wait(self.sweep_interval_s):
            try:
                self.sweep_once()
            except Exception as e:  # the monitor must survive anything
                klog.error_s(e, "node lifecycle sweep panicked")

    # -- the sweep ------------------------------------------------------------

    def sweep_once(self) -> None:
        now = self.clock()
        not_ready = 0
        node_names = set()
        for node in self.node_informer.items():
            node_names.add(node.name)
            hb = node.status.last_heartbeat_time
            if hb is None:
                continue            # not heartbeat-managed
            missed = now - hb > self.heartbeat_grace_s
            if missed and node_ready(node):
                self._mark_not_ready(node, now)
            elif not missed and not node_ready(node):
                self._mark_ready(node, now)
            if not node_ready(self.node_informer.get(node.meta.key) or node):
                not_ready += 1
                since = self._not_ready_since.setdefault(node.name, now)
                if now - since > self.pod_eviction_grace_s:
                    self._evict_pods(node.name, "node NotReady past the "
                                                "eviction grace period")
            else:
                self._not_ready_since.pop(node.name, None)
        nodes_not_ready.set(not_ready)
        self._not_ready_since = {n: t for n, t in
                                 self._not_ready_since.items()
                                 if n in node_names}
        # orphan GC: pods bound to a node object that no longer exists can
        # never run — delete them now so the gang repair controller can act.
        # O(bound-to nodes) via the pod-node index, and the node lookup is
        # LIVE (informer get at delete time), not the sweep-start snapshot:
        # a replacement node created mid-sweep with a repaired gang freshly
        # bound to it must not have those pods GC'd by a stale membership
        # set (the uid precondition would not save them — they are the very
        # instances we would be deleting).
        for node_name in self.pod_informer.index_values(POD_NODE_INDEX):
            if self.node_informer.get(f"/{node_name}") is not None:
                continue
            for pod in self.pod_informer.by_index(POD_NODE_INDEX, node_name):
                if not pod.is_terminating() \
                        and self.node_informer.get(
                            f"/{pod.spec.node_name}") is None:
                    self._delete_pod(
                        pod, f"node {pod.spec.node_name} is gone "
                             f"(orphaned pod GC)")

    # -- transitions ----------------------------------------------------------

    def _mark_not_ready(self, node: Node, now: float) -> None:
        def mutate(live: Node):
            live.set_condition(NODE_READY, "False", reason="HeartbeatMissed",
                               message="kubelet stopped posting heartbeats",
                               now=now)
            if not any(t.key == TAINT_NODE_NOT_READY
                       for t in live.spec.taints):
                live.spec.taints.append(Taint(key=TAINT_NODE_NOT_READY,
                                              effect="NoSchedule"))
        try:
            self.client.nodes.patch(node.meta.key, mutate)
        except srv.NotFound:
            return
        except Exception as e:  # noqa: BLE001 — retried next sweep
            klog.error_s(e, "NotReady patch failed", node=node.name)
            return
        self._not_ready_since.setdefault(node.name, now)
        node_not_ready_transitions.inc()
        trace.pin_event("node_not_ready", subject=f"node/{node.name}",
                        node=node.name,
                        heartbeat_age_s=round(
                            now - (node.status.last_heartbeat_time or now), 2))
        self.client.record_event(node.meta.key, "Node", "Warning",
                                 "NodeNotReady",
                                 "heartbeat missed beyond grace period")
        klog.warning_s("node marked NotReady", node=node.name)

    def _mark_ready(self, node: Node, now: float) -> None:
        def mutate(live: Node):
            live.set_condition(NODE_READY, "True", reason="HeartbeatResumed",
                               now=now)
            live.spec.taints = [t for t in live.spec.taints
                                if t.key != TAINT_NODE_NOT_READY]
        try:
            self.client.nodes.patch(node.meta.key, mutate)
        except srv.NotFound:
            return
        except Exception as e:  # noqa: BLE001 — retried next sweep
            klog.error_s(e, "Ready patch failed", node=node.name)
            return
        self._not_ready_since.pop(node.name, None)
        self.client.record_event(node.meta.key, "Node", "Normal",
                                 "NodeReady", "heartbeat resumed")
        klog.info_s("node recovered to Ready", node=node.name)

    # -- eviction -------------------------------------------------------------

    def _bound_pods(self, node_name: str) -> List[Pod]:
        return self.pod_informer.by_index(POD_NODE_INDEX, node_name)

    def _evict_pods(self, node_name: str, reason: str) -> None:
        for pod in self._bound_pods(node_name):
            self._delete_pod(pod, reason)

    def _delete_pod(self, pod: Pod, reason: str) -> None:
        try:
            # uid precondition: the sweep works off a point-in-time list,
            # and the gang repair controller recreates lost members under
            # the SAME name — a stale eviction must fail (Conflict) rather
            # than kill the replacement
            self.client.pods.delete(pod.key, uid=pod.meta.uid)
        except (srv.NotFound, srv.Conflict):
            return
        except Exception as e:  # noqa: BLE001 — retried next sweep
            klog.error_s(e, "pod eviction failed", pod=pod.key)
            return
        node_pod_evictions.inc()
        self.client.record_event(pod.key, "Pod", "Warning", "Evicted", reason)
        klog.warning_s("evicted pod off failed node", pod=pod.key,
                       node=pod.spec.node_name, reason=reason)
