"""Systematic interleaving exploration with deterministic replay.

The explorer drives one SCENARIO (a small set of threads over real
scheduler objects, see scenarios.py) through many cooperative schedules
(runtime.CoopRuntime), checking the scenario's invariants plus the lock
discipline recorder (the C7 half of the chaos soaks) after every one.

Scheduling strategies:

``RandomWalk``
    seeded uniform choice among runnable workers at every decision point —
    the classic random stress, but over MODELED yield points, so one
    schedule covers an interleaving the OS might produce once a year.
``PCT``
    priority-based with ``depth`` change points (Burckhardt et al.'s
    probabilistic concurrency testing): workers get random priorities, the
    highest-priority runnable worker always runs, and at d random steps
    the running worker's priority drops below everyone — which provably
    finds any bug of "depth" d with useful probability, and in practice
    digs out the one-preemption-in-the-wrong-place bugs a uniform walk
    dilutes away.
``Replay``
    consumes a recorded decision list verbatim and diverges loudly if the
    execution does not offer the recorded choice — the deterministic
    replay contract behind the schedule artifact.

Pruning: after every schedule the executed trace (sequence of effectful
ops: acquire/release/wait/notify/point, labeled by lock/condition NAME so
keys are stable across runs) is reduced to its Foata normal form — the
canonical representative of its Mazurkiewicz equivalence class under the
independence relation "different workers AND different objects commute".
Schedules whose canonical forms collide explored the same
happens-before partial order; the report counts them as pruned, which is
the bounded DPOR-style measure of how much of the budget bought genuinely
new orderings.

A failing schedule yields a SCHEDULE ARTIFACT — scenario name, seed,
strategy, the decision list, and the failure — serializable to JSON.
``python -m tpusched.cmd.replay artifact.json`` re-executes it
deterministically; see doc/ops.md "Reproducing a race-smoke failure".
"""
from __future__ import annotations

import dataclasses
import json
import random
from typing import Dict, List, Optional, Sequence, Tuple

from ..util import locking
from .runtime import CoopRuntime, HarnessHang, Worker

ARTIFACT_VERSION = 1
DEFAULT_MAX_STEPS = 5000
DEFAULT_SCHEDULES = 64
# PCT change points are sampled inside the EXPECTED schedule length, not
# the step budget — a change point past the schedule's end never fires
# and PCT degenerates to fixed priorities.  explore() adapts the horizon
# to each scenario from the steps its schedules actually take.
DEFAULT_PCT_HORIZON = 48


class ReplayDivergence(RuntimeError):
    """A replayed decision list did not match the execution — the artifact
    and the code under test have drifted apart."""


# -- strategies ----------------------------------------------------------------


class RandomWalk:
    label = "random-walk"

    def __init__(self, rng: random.Random):
        self._rng = rng

    def choose(self, runnable: Sequence[str], fire: bool = False) -> str:
        return runnable[self._rng.randrange(len(runnable))]


class PCT:
    label = "pct"

    def __init__(self, rng: random.Random, depth: int = 3,
                 horizon: int = DEFAULT_PCT_HORIZON):
        self._rng = rng
        self._prio: Dict[str, float] = {}
        self._step = 0
        self._change_at = sorted(rng.randrange(1, max(2, horizon))
                                 for _ in range(depth))

    def choose(self, runnable: Sequence[str], fire: bool = False) -> str:
        for name in runnable:
            if name not in self._prio:
                self._prio[name] = self._rng.random()
        self._step += 1
        pick = max(runnable, key=lambda n: self._prio[n])
        if self._change_at and self._step >= self._change_at[0]:
            self._change_at.pop(0)
            self._prio[pick] = min(self._prio.values()) - 1.0
        return pick


class Replay:
    label = "replay"

    def __init__(self, decisions: Sequence[str]):
        self._decisions = list(decisions)
        self._pos = 0

    def choose(self, runnable: Sequence[str], fire: bool = False) -> str:
        if self._pos >= len(self._decisions):
            raise ReplayDivergence(
                f"decision list exhausted at step {self._pos} but workers "
                f"still need scheduling ({', '.join(runnable)}) — the "
                f"execution diverged from the recorded schedule")
        d = self._decisions[self._pos]
        self._pos += 1
        if d.startswith("~") != fire:
            raise ReplayDivergence(
                f"step {self._pos - 1}: recorded decision {d!r} is a "
                f"{'timeout-fire' if d.startswith('~') else 'grant'} but "
                f"the execution needs a {'timeout-fire' if fire else 'grant'}")
        name = d[1:] if d.startswith("~") else d
        if name not in runnable:
            raise ReplayDivergence(
                f"step {self._pos - 1}: recorded choice {d!r} is not "
                f"schedulable (candidates: {', '.join(runnable)})")
        return name


# -- results -------------------------------------------------------------------


@dataclasses.dataclass
class ScheduleResult:
    ok: bool
    failure: Optional[str]
    decisions: List[str]
    steps: int
    trace_key: tuple
    acquires: int          # C7 non-vacuity witness: instrumentation was on


@dataclasses.dataclass
class ExploreReport:
    scenario: str
    seed: int
    schedules: int
    failures: int
    distinct_traces: int
    pruned: int            # schedules that re-explored a known trace class
    first_failure: Optional[dict]   # schedule artifact, replayable

    @property
    def ok(self) -> bool:
        return self.failures == 0


# -- trace canonicalization (DPOR-style pruning measure) -----------------------


def canonical_trace_key(trace: Sequence[Tuple[str, str, str]]) -> tuple:
    """Foata normal form of the trace: layers of pairwise-independent ops,
    each op placed one past the deepest layer holding a dependent
    predecessor.  Unique per Mazurkiewicz equivalence class, so two
    schedules with equal keys explored the same happens-before partial
    order.  O(n): dependence is exactly "same worker or same object", and
    layers grow monotonically along each worker's program order and each
    object's conflict order, so the deepest dependent predecessor is
    always the LAST op of the same worker or the same object."""
    layers: List[List[Tuple[str, str, str]]] = []
    by_worker: Dict[str, int] = {}
    by_obj: Dict[str, int] = {}
    for op in trace:
        worker, _, obj = op
        li = max(by_worker.get(worker, -1), by_obj.get(obj, -1)) + 1
        while len(layers) <= li:
            layers.append([])
        layers[li].append(op)
        by_worker[worker] = li
        by_obj[obj] = li
    return tuple(tuple(sorted(layer)) for layer in layers)


# -- the explorer --------------------------------------------------------------


class Explorer:
    """Runs scenarios under cooperative schedules.  Stateless between
    calls except for configuration; every schedule constructs a FRESH
    scenario instance (fresh locks, fresh recorder state) so schedules
    cannot contaminate each other."""

    def __init__(self, max_steps: int = DEFAULT_MAX_STEPS,
                 hang_timeout_s: float = 20.0):
        self.max_steps = max_steps
        self.hang_timeout_s = hang_timeout_s

    def run_schedule(self, scenario, strategy) -> ScheduleResult:
        """One schedule: set up the scenario under lock debug mode, drive
        its threads per ``strategy``, then check invariants.  Restores all
        global state (debug flag, verify hook, recorder) before returning."""
        prev_debug = locking.set_debug(True)
        rec = locking.recorder()
        rec.reset()
        rt = CoopRuntime(hang_timeout_s=self.hang_timeout_s)
        prev_hook = locking.set_verify_hook(None)  # setup runs unexplored
        decisions: List[str] = []
        failure: Optional[str] = None
        try:
            ctx = scenario.setup()
            by_name: Dict[str, Worker] = {}
            for i, fn in enumerate(scenario.threads(ctx)):
                w = rt.add_worker(f"T{i}", fn)
                by_name[w.name] = w
            locking.set_verify_hook(rt)
            rt.start()
            try:
                failure = self._drive(rt, strategy, by_name, decisions)
            except (HarnessHang, ReplayDivergence) as e:
                failure = f"{type(e).__name__}: {e}"
            finally:
                locking.set_verify_hook(None)
                if not rt.all_done():
                    leaked = rt.kill_all()
                    if leaked:
                        failure = (
                            f"{failure or 'schedule aborted'}; workers "
                            f"{', '.join(leaked)} did not unwind — they "
                            f"are blocked outside the model and may "
                            f"pollute the recorder in later schedules "
                            f"(treat this whole run as suspect)")
            if failure is None:
                for w in rt.workers:
                    if w.error is not None:
                        failure = (f"worker {w.name} raised: "
                                   f"{type(w.error).__name__}: {w.error}")
                        break
            if failure is None:
                viol = rec.violations()
                if viol:
                    failure = f"lock discipline violated: {viol[0]}"
            if failure is None and rt.atomicity_violations:
                failure = rt.atomicity_violations[0]
            if failure is None:
                try:
                    scenario.check(ctx)
                except AssertionError as e:
                    failure = f"invariant violated: {e}"
            return ScheduleResult(ok=failure is None, failure=failure,
                                  decisions=decisions, steps=rt.steps,
                                  trace_key=canonical_trace_key(rt.trace),
                                  acquires=rec.acquires)
        finally:
            locking.set_verify_hook(prev_hook)
            rec.reset()
            locking.set_debug(prev_debug)

    def _drive(self, rt: CoopRuntime, strategy,
               by_name: Dict[str, Worker],
               decisions: List[str]) -> Optional[str]:
        """The scheduling loop: grant turns until every worker finishes or
        the schedule fails.  Returns a failure description or None."""
        while not rt.all_done():
            if rt.steps > self.max_steps:
                return (f"step budget exceeded ({self.max_steps}) — "
                        f"modeled livelock? ({rt.describe_states()})")
            runnable = rt.runnable_workers()
            if runnable:
                names = [w.name for w in runnable]
                pick = strategy.choose(names, fire=False)
                decisions.append(pick)
                rt.grant(by_name[pick])
                continue
            timed = rt.timed_waiters()
            if timed:
                # nothing can run: some timed wait must fire.  Which one is
                # a scheduling decision like any other (recorded as ~name).
                names = [w.name for w in timed]
                pick = strategy.choose(names, fire=True)
                decisions.append("~" + pick)
                rt.grant(by_name[pick], fire_timeout=True)
                continue
            return ("modeled deadlock: no runnable worker and no timed "
                    f"wait to fire ({rt.describe_states()})")
        return None

    def explore(self, scenario_factory, seed: int = 0,
                schedules: int = DEFAULT_SCHEDULES,
                stop_on_failure: bool = True) -> ExploreReport:
        """Seeded exploration: alternate RandomWalk and PCT schedules,
        dedupe by canonical trace, capture the first failure as a
        replayable artifact."""
        name = scenario_factory.name
        seen: set = set()
        failures = 0
        pruned = 0
        first_failure: Optional[dict] = None
        ran = 0
        horizon = DEFAULT_PCT_HORIZON
        for i in range(schedules):
            rng = random.Random(f"{seed}:{i}")
            strategy = PCT(rng, depth=3, horizon=horizon) \
                if i % 2 else RandomWalk(rng)
            res = self.run_schedule(scenario_factory(), strategy)
            ran += 1
            # adapt the change-point horizon to what this scenario's
            # schedules actually take (deterministic: derived from prior
            # results only), so PCT preemptions land inside the schedule
            horizon = max(8, res.steps)
            if res.trace_key in seen:
                pruned += 1
            else:
                seen.add(res.trace_key)
            if not res.ok:
                failures += 1
                if first_failure is None:
                    first_failure = make_artifact(
                        name, seed=f"{seed}:{i}", strategy=strategy.label,
                        decisions=res.decisions, failure=res.failure,
                        steps=res.steps)
                if stop_on_failure:
                    break
        return ExploreReport(scenario=name, seed=seed, schedules=ran,
                             failures=failures, distinct_traces=len(seen),
                             pruned=pruned, first_failure=first_failure)


# -- schedule artifacts --------------------------------------------------------


def make_artifact(scenario: str, seed: str, strategy: str,
                  decisions: List[str], failure: Optional[str],
                  steps: int) -> dict:
    return {"version": ARTIFACT_VERSION, "scenario": scenario,
            "seed": seed, "strategy": strategy, "decisions": list(decisions),
            "failure": failure, "steps": steps}


def validate_artifact(data: dict) -> dict:
    """Schema check for a loaded artifact; raises ValueError with the
    first problem found."""
    if not isinstance(data, dict):
        raise ValueError("artifact must be a JSON object")
    if data.get("version") != ARTIFACT_VERSION:
        raise ValueError(f"unsupported artifact version {data.get('version')!r}"
                         f" (want {ARTIFACT_VERSION})")
    for field, typ in (("scenario", str), ("seed", str), ("strategy", str),
                       ("decisions", list), ("steps", int)):
        if not isinstance(data.get(field), typ):
            raise ValueError(f"artifact field {field!r} must be "
                             f"{typ.__name__}")
    if not all(isinstance(d, str) for d in data["decisions"]):
        raise ValueError("artifact decisions must all be strings")
    if data.get("failure") is not None \
            and not isinstance(data["failure"], str):
        raise ValueError("artifact field 'failure' must be null or string")
    return data


def load_artifact(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return validate_artifact(json.load(f))


def dump_artifact(artifact: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")


def replay_artifact(artifact: dict, max_steps: int = DEFAULT_MAX_STEPS
                    ) -> ScheduleResult:
    """Re-execute a schedule artifact deterministically: same scenario,
    same decisions, nothing random.  Raises KeyError for an unknown
    scenario and ReplayDivergence (inside the result's failure) if the
    execution no longer matches the recorded decisions."""
    from .scenarios import SCENARIOS
    factory = SCENARIOS[artifact["scenario"]]
    explorer = Explorer(max_steps=max_steps)
    return explorer.run_schedule(factory(), Replay(artifact["decisions"]))
