"""tpuverify: systematic interleaving exploration with deterministic
replay.

The correctness scaffolding ROADMAP item 1's sharded dispatch lands on:
a cooperative deterministic scheduler (runtime.CoopRuntime) takes control
of scheduler-owned threads at the yield points the debug-mode locks
already mark, an explorer (explorer.Explorer) drives seeded random-walk
and PCT schedules over targeted critical-section scenarios
(scenarios.SCENARIOS), and any failure emits a replayable schedule
artifact that ``python -m tpusched.cmd.replay`` re-executes
deterministically.  ``make race-smoke`` runs the bounded budget as a
tier-1 gate.
"""
from .explorer import (ARTIFACT_VERSION, Explorer, ExploreReport, PCT,
                       RandomWalk, Replay, ReplayDivergence, ScheduleResult,
                       canonical_trace_key, dump_artifact, load_artifact,
                       make_artifact, replay_artifact, validate_artifact)
from .runtime import CoopRuntime, HarnessHang, KilledWorker, atomic_region
from .scenarios import (LIVE_SCENARIOS, SCENARIOS, SELFCHECK_BUGGY,
                        Scenario)

__all__ = [
    "ARTIFACT_VERSION", "CoopRuntime", "Explorer", "ExploreReport",
    "HarnessHang", "KilledWorker", "LIVE_SCENARIOS", "PCT", "RandomWalk",
    "Replay", "ReplayDivergence", "SCENARIOS", "SELFCHECK_BUGGY",
    "Scenario", "ScheduleResult", "atomic_region", "canonical_trace_key",
    "dump_artifact", "load_artifact", "make_artifact", "replay_artifact",
    "validate_artifact",
]
