"""Cooperative-scheduling kernel for the interleaving explorer.

This is the half of tpuverify that touches threads.  A ``CoopRuntime``
owns a set of WORKER threads (the scenario's actors) and a single TURN
token: exactly one worker runs at any moment, everything else is parked.
Workers hand the turn back at YIELD POINTS — the acquisition boundaries
the debug-mode locks already mark (util/locking installs this object as
its ``_VERIFY_HOOK``): before a ``GuardedLock`` acquire, after a full
release, across a ``GuardedCondition`` wait/notify, at every
``@guarded_by`` container mutation, and at the explicit
``locking.verify_point`` markers (the binding pool's plain-Queue
boundaries).  Between two yield points a worker runs REAL production code;
because nothing else runs concurrently, that stretch is atomic by
construction and the schedule is fully determined by the sequence of
grant decisions — which is what makes a recorded decision list a
deterministic replay artifact.

The runtime keeps a MODEL of lock ownership and condition waiters,
updated at the hooks while the mutating worker holds the turn (so the
model needs no synchronization of its own).  The scheduler (the explorer,
on the calling thread) only grants the turn to workers the model says can
make progress; a worker that would block on a modeled lock is parked
until the holder releases, so the real locks never block a running
worker.  Condition waits are modeled the same way: the waiter registers
in the model BEFORE the lock is released (the atomicity the real
Condition provides), parks, and is woken by a modeled notify — or, for
timed waits, by an explicit timeout-fire decision.  A state where no
worker is runnable and no timed wait can fire is a MODELED DEADLOCK and
is reported as a finding, long before any wall-clock hang.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..util import locking

# A worker that executes real code for this long without reaching a yield
# point (or finishing) has escaped the model — a real block on something
# the runtime cannot see.  Abort the schedule instead of hanging the run.
HANG_TIMEOUT_S = 20.0


class KilledWorker(BaseException):
    """Raised inside a worker to unwind it when the run aborts.  A
    BaseException on purpose: production code's broad ``except Exception``
    isolation (informer dispatch, binding workers) must not swallow the
    teardown."""


class HarnessHang(RuntimeError):
    """A worker ran past HANG_TIMEOUT_S without yielding — it is blocked on
    something outside the model (a real lock the hooks do not cover)."""


class Worker:
    __slots__ = ("name", "fn", "evt", "thread", "done", "error",
                 "blocked_on", "waiting_on", "wait_timed", "wait_seq",
                 "wake_pending", "wake_notified", "suppress_yield")

    def __init__(self, name: str, fn: Callable[[], None]):
        self.name = name
        self.fn = fn
        self.evt = threading.Event()           # turn grant
        self.thread: Optional[threading.Thread] = None
        self.done = False
        self.error: Optional[BaseException] = None
        self.blocked_on: Optional[Tuple[str, int]] = None   # modeled lock
        self.waiting_on: Optional[int] = None  # id(condition) while waiting
        self.wait_timed = False
        self.wait_seq = 0                      # FIFO order among waiters
        self.wake_pending = False              # notify/timeout delivered
        self.wake_notified = False             # wake reason (True = notify)
        self.suppress_yield = False            # release inside a cond wait


class CoopRuntime:
    """One schedule's worth of cooperative execution state.  Construct,
    ``add_worker`` the scenario's actors, install via
    ``locking.set_verify_hook``, ``start()``, then drive with
    ``grant()``/``runnable_workers()`` from the scheduling loop."""

    def __init__(self, hang_timeout_s: float = HANG_TIMEOUT_S):
        self.workers: List[Worker] = []
        self._by_ident: Dict[int, Worker] = {}
        self._sched_evt = threading.Event()
        # modeled lock table: (name, id) → [holder, reentry count]
        self._locks: Dict[Tuple[str, int], list] = {}
        # execution trace of effectful ops: (worker, kind, object-label).
        # Object labels are run-stable (lock NAMES, not ids) so canonical
        # trace keys compare across schedules.
        self.trace: List[Tuple[str, str, str]] = []
        # atomicity assertions (atomic_region) that observed a foreign
        # dependent op inside their span — checked after every schedule
        self.atomicity_violations: List[str] = []
        self.steps = 0
        self.aborted = False
        self.hang_timeout_s = hang_timeout_s
        self._wait_seq = 0           # stamps cond waiters in arrival order

    # -- lifecycle -------------------------------------------------------------

    def add_worker(self, name: str, fn: Callable[[], None]) -> Worker:
        w = Worker(name, fn)
        self.workers.append(w)
        return w

    def start(self) -> None:
        for w in self.workers:
            w.thread = threading.Thread(target=self._main, args=(w,),
                                        name=f"tpuverify-{w.name}",
                                        daemon=True)
            w.thread.start()

    def _main(self, w: Worker) -> None:
        self._by_ident[threading.get_ident()] = w
        w.evt.wait()                    # start gate: the first grant
        try:
            if not self.aborted:
                w.fn()
        except KilledWorker:
            pass
        except Exception as e:          # scenario assertion / real bug
            w.error = e
        finally:
            w.done = True
            self._sched_evt.set()

    def kill_all(self) -> List[str]:
        """Abort the schedule: every parked worker raises KilledWorker at
        its yield point and unwinds.  Model state is garbage afterwards —
        collect results BEFORE calling this.  Returns the names of
        workers that did NOT unwind within the join timeout (blocked on
        something outside the model): such a thread can wake later and
        feed the process-global recorder mid-unrelated-schedule, so the
        caller must mark the whole run suspect, not just this schedule."""
        self.aborted = True
        for w in self.workers:
            w.evt.set()
        leaked = []
        for w in self.workers:
            if w.thread is not None:
                w.thread.join(timeout=2.0)
                if w.thread.is_alive():
                    leaked.append(w.name)
        return leaked

    # -- scheduler side --------------------------------------------------------

    def all_done(self) -> bool:
        return all(w.done for w in self.workers)

    def runnable_workers(self) -> List[Worker]:
        return [w for w in self.workers
                if not w.done and w.blocked_on is None
                and (w.waiting_on is None or w.wake_pending)]

    def timed_waiters(self) -> List[Worker]:
        return [w for w in self.workers
                if not w.done and w.waiting_on is not None
                and not w.wake_pending and w.wait_timed]

    def grant(self, w: Worker, fire_timeout: bool = False) -> None:
        """Hand the turn to ``w``; returns when it yields again, finishes,
        or overruns the hang timeout.  ``fire_timeout`` wakes a timed
        condition waiter as if its wait timed out."""
        if fire_timeout:
            w.wake_pending = True
            w.wake_notified = False
        self._sched_evt.clear()
        w.evt.set()
        if not self._sched_evt.wait(self.hang_timeout_s):
            raise HarnessHang(
                f"worker {w.name} did not reach a yield point within "
                f"{self.hang_timeout_s:.0f}s — blocked outside the model?")

    # -- worker side -----------------------------------------------------------

    def _me(self) -> Optional[Worker]:
        return self._by_ident.get(threading.get_ident())

    def _pause(self, w: Worker) -> None:
        """Hand the turn back and park until granted again."""
        self.steps += 1
        w.evt.clear()
        self._sched_evt.set()
        w.evt.wait()
        if self.aborted:
            raise KilledWorker()

    # -- locking._VERIFY_HOOK protocol ----------------------------------------

    def on_acquire(self, name: str, ident: int, blocking: bool = True) -> bool:
        w = self._me()
        if w is None or self.aborted:
            return True
        key = (name, ident)
        self._pause(w)                  # decision point before the acquire
        while True:
            ent = self._locks.get(key)
            if ent is None:
                self._locks[key] = [w, 1]
            elif ent[0] is w:
                # Only reachable by re-acquiring a NON-reentrant lock the
                # worker already holds: a reentrant lock's re-acquire
                # short-circuits before this hook fires, and
                # _acquire_restore only runs after a full release.  The
                # real acquire would block forever — report it instead of
                # letting the schedule burn the hang timeout.
                if not blocking:
                    self.trace.append((w.name, "tryfail", name))
                    return False
                raise RuntimeError(
                    f"modeled self-deadlock: {w.name} re-acquires "
                    f"non-reentrant lock {name} it already holds")
            elif not blocking:
                self.trace.append((w.name, "tryfail", name))
                return False
            else:
                w.blocked_on = key      # granted again only after release
                self._pause(w)
                continue
            self.trace.append((w.name, "acquire", name))
            return True

    def on_release(self, name: str, ident: int) -> None:
        w = self._me()
        if w is None or self.aborted:
            return
        key = (name, ident)
        ent = self._locks.get(key)
        if ent is not None and ent[0] is w:
            ent[1] -= 1
            if ent[1] <= 0:
                del self._locks[key]
                for b in self.workers:
                    if b.blocked_on == key:
                        b.blocked_on = None
        self.trace.append((w.name, "release", name))
        if w.suppress_yield:            # release inside a modeled cond wait:
            w.suppress_yield = False    # the wait itself is the decision point
            return
        self._pause(w)                  # decision point after the release

    def on_cond_wait(self, cond, timeout) -> Optional[bool]:
        w = self._me()
        if w is None or self.aborted:
            return None                 # unmanaged thread: real wait
        lock = getattr(cond, "_lock", None)
        if not hasattr(lock, "_release_save") or not hasattr(lock, "name"):
            return None                 # not an instrumented GuardedLock
        if not lock._is_owned():
            raise RuntimeError("cannot wait() on an un-acquired condition")
        # Register as a waiter BEFORE releasing the lock — a notify issued
        # by the next lock holder must find us (no lost wakeups), exactly
        # as the real Condition's waiter list guarantees.
        w.waiting_on = id(cond)
        w.wait_timed = timeout is not None
        self._wait_seq += 1
        w.wait_seq = self._wait_seq
        w.wake_pending = False
        w.wake_notified = False
        w.suppress_yield = True
        state = lock._release_save()    # on_release updates the model, no yield
        self.trace.append((w.name, "wait", f"cond:{lock.name}"))
        self._pause(w)                  # parked until notify / timeout-fire
        w.waiting_on = None
        w.wake_pending = False
        lock._acquire_restore(state)    # on_acquire: contends like anyone else
        return w.wake_notified

    def on_cond_notify(self, cond, n: Optional[int] = None) -> None:
        """``n`` is the wake count (None = notify_all).  Waiters wake in
        arrival order, matching the stdlib Condition's FIFO waiter list —
        modeling notify(1) as notify-all would explore wakeups production
        cannot execute and hide lost-single-wake bugs."""
        w = self._me()
        if w is None or self.aborted:
            return
        waiters = sorted((b for b in self.workers
                          if b.waiting_on == id(cond)
                          and not b.wake_pending),
                         key=lambda b: b.wait_seq)
        if n is not None:
            waiters = waiters[:n]
        for b in waiters:
            b.wake_pending = True
            b.wake_notified = True
        lock = getattr(cond, "_lock", None)
        label = f"cond:{getattr(lock, 'name', 'condition')}"
        self.trace.append((w.name, "notify", label))
        self._pause(w)                  # decision point after the notify

    def on_point(self, label: str) -> None:
        w = self._me()
        if w is None or self.aborted:
            return
        self.trace.append((w.name, "point", label))
        self._pause(w)

    # -- reporting -------------------------------------------------------------

    def describe_states(self) -> str:
        parts = []
        for w in self.workers:
            if w.done:
                st = "done"
            elif w.blocked_on is not None:
                st = f"blocked on {w.blocked_on[0]}"
            elif w.waiting_on is not None:
                st = "in cond.wait" + (" (timed)" if w.wait_timed else "")
            else:
                st = "runnable"
            parts.append(f"{w.name}: {st}")
        return ", ".join(parts)


def install(rt: CoopRuntime):
    """Install ``rt`` as the process-global explorer hook.  Returns the
    previous hook for restoration."""
    return locking.set_verify_hook(rt)


@contextlib.contextmanager
def atomic_region(label: str, objects: Tuple[str, ...]):
    """Declare that the wrapped span must be atomic with respect to the
    named objects: if any OTHER worker's effectful op whose trace label
    contains one of the ``objects`` tokens lands inside the span, the
    schedule fails with an atomicity violation.  A no-op outside the
    explorer, so production code paths may carry the declaration."""
    h = locking.verify_hook()
    if not isinstance(h, CoopRuntime):
        yield
        return
    me = h._me()
    start = len(h.trace)
    yield
    if me is None:
        return
    for wname, kind, obj in h.trace[start:]:
        if wname != me.name and any(tok in obj for tok in objects):
            h.atomicity_violations.append(
                f"atomic region {label!r} ({me.name}) interleaved with "
                f"{wname}'s {kind} on {obj}")

