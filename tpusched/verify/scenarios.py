"""Interleaving scenarios: the critical-section pairs the sharded core
(ROADMAP item 1) will stress, each run under the explorer's full schedule
budget by ``make race-smoke`` (tests/test_verify_scenarios.py).

A Scenario is deliberately tiny: ``setup()`` builds REAL scheduler objects
(Cache, SchedulingQueue, Informer, _BindingPool — under lock debug mode,
so every acquisition boundary is an explorer yield point), ``threads()``
returns the actors (each a plain callable; everything an actor does
between two yield points is atomic by construction), and ``check()``
asserts the quiescence invariants after all actors finish.  The explorer
additionally asserts, on every schedule, that the lock-discipline
recorder saw zero violations (the chaos soaks' C7) and that any declared
``atomic_region`` really ran interleaving-free.

Scenarios must be DETERMINISTIC: injected counter clocks, no wall-time
branching, no unmanaged threads (the binding pool is constructed with
zero workers — its shutdown/submit hand-off is the race under test, not
its workers).  ``selfcheck-*`` scenarios carry deliberately seeded bugs;
the race-smoke meta-test proves the explorer finds them (non-vacuity)
and that their artifacts replay deterministically.
"""
from __future__ import annotations

from types import SimpleNamespace
from typing import Callable, Dict, List, Type

from ..apiserver import server as srv
from ..apiserver.informers import Informer
from ..fwk.interfaces import EVENT_ADD, RESOURCE_NODE
from ..sched.cache import Cache
from ..sched.equivcache import EquivEntry, EquivalenceCache
from ..sched.queue import SchedulingQueue
from ..testing import make_node, make_pod
from ..util import locking
from .runtime import atomic_region


class Scenario:
    """One interleaving scenario.  Subclasses set ``name`` and implement
    the three hooks; a fresh instance runs per schedule."""

    name = ""

    def setup(self):
        """Build the objects under test (runs unexplored, on the driving
        thread, with lock debug mode already on).  Returns the ctx handed
        to threads() and check()."""
        raise NotImplementedError

    def threads(self, ctx) -> List[Callable[[], None]]:
        raise NotImplementedError

    def check(self, ctx) -> None:
        """Quiescence invariants; raise AssertionError on violation."""


SCENARIOS: Dict[str, Type[Scenario]] = {}


def register(cls: Type[Scenario]) -> Type[Scenario]:
    assert cls.name and cls.name not in SCENARIOS
    SCENARIOS[cls.name] = cls
    return cls


def _counter_clock(ctx):
    """Deterministic injectable clock: reads ``ctx.now``."""
    return lambda: ctx.now


# -- the live-tree pairs -------------------------------------------------------


@register
class EquivcacheArming(Scenario):
    """Equivalence-cache arming guard vs. a foreign cache mutation.

    The dispatch actor replays the scheduler's exact arming protocol
    (scheduler._equiv_offer / _equiv_after_assume): snapshot, remember the
    snapshot cursor, assume its own pod, then arm the entry iff the
    mutation cursor advanced by EXACTLY its own assume.  The foreign actor
    is a watch-confirmed pod landing via the informer path.  Invariant: an
    ARMED entry implies the foreign mutation did not land inside the
    (snapshot, arm] window — the guard's whole job."""

    name = "equivcache-arming"

    # guard tweak point so the seeded-bug variant can break exactly one
    # comparison (see SelfcheckBrokenArming)
    def _guard(self, cur: int, cyc: int) -> bool:
        return cur == cyc + 1

    def setup(self):
        ctx = SimpleNamespace(now=0.0, events=[])
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.cache.add_node(make_node("n1"))
        ctx.cache.add_node(make_node("n2"))
        ctx.ec = EquivalenceCache()
        return ctx

    def threads(self, ctx):
        def dispatch():
            ctx.cache.snapshot()
            cyc = ctx.cache.snapshot_cursor()
            entry = EquivEntry("class-a", (), 0, {}, frozenset(), None,
                               ("n1",))
            ctx.cache.assume_pod(make_pod("own"), "n1")
            cur = ctx.cache.mutation_cursor()
            if self._guard(cur, cyc):
                # between the cursor read and the arm nothing foreign may
                # touch the cache — the guard's verdict is already cast
                with atomic_region("equiv-arm", ("sched.Cache",)):
                    ctx.ec.arm(entry, cyc + 1)
                ctx.events.append(("armed", cyc, cur))
            else:
                ctx.ec.drop(entry.key)
                ctx.events.append(("dropped", cyc, cur))

        def foreign():
            confirmed = make_pod("foreign", node_name="n2")
            # the add and its cursor read share one critical section (the
            # outer acquire makes the inner ones reentrant), so the
            # recorded cursor is EXACTLY the foreign mutation's — read
            # outside, the cursor could lag past dispatch's own assume
            # and indict an innocent interleaving
            with ctx.cache._lock:
                ctx.cache.add_pod(confirmed)
                ctx.events.append(("foreign", ctx.cache.mutation_cursor()))

        return [dispatch, foreign]

    def check(self, ctx):
        armed = [e for e in ctx.events if e[0] == "armed"]
        if not armed:
            return
        _, cyc, cur = armed[0]
        for e in ctx.events:
            if e[0] == "foreign":
                fcur = e[1]
                assert not (cyc < fcur <= cur), (
                    f"entry armed at cursor {cur} although a foreign "
                    f"mutation landed at cursor {fcur} inside the "
                    f"(snapshot={cyc}, arm] window — the arming guard "
                    f"let a concurrent mutation be laundered into a "
                    f"'valid' cache entry")


@register
class CacheAssumeConfirm(Scenario):
    """assume → {bind-commit finish_binding | watch-confirm add_pod |
    TTL-expiry sweep} in every order.

    setup() performs the assume (it happens-before both the bind commit
    and the watch confirm in the live system); the actors are the three
    threads that then race: the binding worker arming the TTL, the
    informer delivering the confirmed pod, and a scheduling cycle whose
    snapshot() runs the expiry sweep after the TTL would have lapsed.
    Invariant: exactly one attached copy of the pod, assume table empty,
    nothing leaked or double-attached."""

    name = "cache-assume-confirm"

    def setup(self):
        ctx = SimpleNamespace(now=0.0)
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.cache.add_node(make_node("n1"))
        ctx.pod = make_pod("p")
        ctx.confirmed = make_pod("p", node_name="n1")
        ctx.cache.assume_pod(ctx.pod, "n1")
        return ctx

    def threads(self, ctx):
        def bind_commit():
            ctx.cache.finish_binding(ctx.pod)

        def watch_confirm():
            ctx.cache.add_pod(ctx.confirmed)

        def expiry_sweep():
            ctx.now = 100.0          # beyond ASSUME_EXPIRATION_S
            ctx.cache.snapshot()     # runs _cleanup_expired_locked

        return [bind_commit, watch_confirm, expiry_sweep]

    def check(self, ctx):
        key = ctx.pod.key
        assert not ctx.cache.is_assumed(key), (
            "assume-table entry survived bind-commit + watch-confirm — "
            "the entry would leak its quorum count forever")
        snap = ctx.cache.snapshot()
        attached = [p for info in snap.list() for p in info.pods
                    if p.key == key]
        assert len(attached) == 1, (
            f"{len(attached)} attached copies of {key} after confirm "
            f"(want exactly 1): assume/confirm/expire interleaving "
            f"double-attached or lost the pod")


@register
class QueuePopVsMove(Scenario):
    """queue.pop() (including its Condition wait) vs. a coalesced
    move_all_to_active_or_backoff storm.  Invariant: the parked pod is
    delivered exactly once — either returned by pop or still pending —
    and never both or neither (the lost-wakeup / lost-pod wedge)."""

    name = "queue-pop-vs-move"

    def setup(self):
        ctx = SimpleNamespace(now=0.0, popped=[])

        def less(a, b):
            if a.pod.priority != b.pod.priority:
                return a.pod.priority > b.pod.priority
            return a.timestamp < b.timestamp

        # backoff 0: the pod is schedulable the moment the event moves it,
        # so modeled time never has to advance past a real backoff window
        ctx.q = SchedulingQueue(less, clock=_counter_clock(ctx),
                                initial_backoff_s=0, max_backoff_s=0)
        ctx.pod = make_pod("a")
        ctx.q.add(ctx.pod)
        info = ctx.q.pop(timeout=0)
        assert info is not None
        ctx.q.requeue_after_failure(info)    # parks in unschedulableQ
        return ctx

    def threads(self, ctx):
        def consumer():
            ctx.popped.append(ctx.q.pop(timeout=5.0))

        def informer_storm():
            ctx.q.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_ADD)

        return [consumer, informer_storm]

    def check(self, ctx):
        got = [i for i in ctx.popped if i is not None]
        pending = [p for p in ctx.q.pending_pods()
                   if p.key == ctx.pod.key]
        assert len(got) + len(pending) == 1, (
            f"pod delivered {len(got)} time(s) and pending "
            f"{len(pending)} time(s) — a queued pod must be in exactly "
            f"one place after a pop/move race")
        assert len(got) == 1, (
            "pop returned None although the move event made the pod "
            "schedulable and notified — lost wakeup")


@register
class InformerDeleteRace(Scenario):
    """Informer live DELETED delivery vs. resync() relist-and-diff vs. a
    dispatch-side reader, all feeding the scheduler cache.  Invariant: at
    quiescence the pod is gone from the informer cache AND the scheduler
    cache, with delete handlers tolerating the duplicate delivery the
    at-least-once contract allows."""

    name = "informer-delete-resync"

    def setup(self):
        ctx = SimpleNamespace(now=0.0, deletes=[])
        ctx.api = srv.APIServer()
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.cache.add_node(make_node("n1"))
        ctx.pod = make_pod("doomed", node_name="n1")
        ctx.api.create(srv.PODS, ctx.pod)
        ctx.inf = Informer(ctx.api, srv.PODS)

        def on_add(obj):
            if obj.spec.node_name:
                ctx.cache.add_pod(obj)

        def on_delete(obj):
            ctx.deletes.append(obj.meta.key)
            ctx.cache.remove_pod(obj)

        ctx.inf.add_event_handler(on_add=on_add, on_delete=on_delete)
        return ctx

    def threads(self, ctx):
        def deleter():
            ctx.api.delete(srv.PODS, ctx.pod.meta.key)

        def resyncer():
            ctx.inf.resync()

        def dispatch_reader():
            ctx.inf.items()
            ctx.cache.snapshot()

        return [deleter, resyncer, dispatch_reader]

    def check(self, ctx):
        assert ctx.inf.get(ctx.pod.meta.key) is None, (
            "informer cache still holds the deleted pod — a resync "
            "racing the live DELETED resurrected it")
        snap = ctx.cache.snapshot()
        left = [p for info in snap.list() for p in info.pods]
        assert not left, (
            f"scheduler cache still attaches {[p.key for p in left]} "
            f"after the delete — dispatch kept a pod the API server "
            f"no longer has")
        assert len(ctx.deletes) >= 1, (
            "delete handler never fired — the event was lost between "
            "the live watch and the resync diff")


@register
class FanoutFlushInformerOrdering(Scenario):
    """The coalesced fan-out batcher (ISSUE 16 tentpole b) racing the
    informer delivery plane.  A bind-confirm MODIFIED and the pod's
    DELETED commit in store order and are enqueued UNDER the store lock
    (commit order IS queue order); two racing flush threads — the daemon
    flusher is just one more calling thread — splice and deliver into a
    real Informer.  Invariants: the tombstoned pod is never resurrected
    in the informer cache, and no MODIFIED for the key is delivered
    after its DELETED (per-key RV staleness rejection is load-bearing
    when racing flushers split a batch)."""

    name = "fanout-flush-vs-informer-ordering"

    def setup(self):
        ctx = SimpleNamespace(now=0.0, seen=[])
        # batched mode with the daemon flusher deliberately parked
        # (stopped before any event exists): scenarios must not run
        # unmanaged threads, so flush delivery is driven only by the
        # explored actors below via fanout_flush()
        ctx.api = srv.APIServer(fanout_flush_window_s=3600.0)
        ctx.api._fanout.stop()
        ctx.pod = make_pod("doomed", node_name="n1")
        ctx.api.create(srv.PODS, ctx.pod)
        ctx.api.fanout_flush()            # pod visible pre-race
        ctx.key = ctx.pod.meta.key
        ctx.inf = Informer(ctx.api, srv.PODS)

        def on_update(_old, obj):
            ctx.seen.append(("MODIFIED", obj.meta.resource_version))

        def on_delete(obj):
            ctx.seen.append(("DELETED", obj.meta.resource_version))

        ctx.inf.add_event_handler(on_update=on_update, on_delete=on_delete)
        return ctx

    def threads(self, ctx):
        def writer():
            # bind-confirm then reap: two commits in strict store order,
            # enqueued under the store lock
            ctx.api.patch(srv.PODS, ctx.key,
                          lambda p: p.meta.annotations.update(bound="y"))
            ctx.api.delete(srv.PODS, ctx.key)

        def flusher_a():
            ctx.api.fanout_flush()

        def flusher_b():
            ctx.api.fanout_flush()

        def reader():
            ctx.inf.get(ctx.key)
            ctx.inf.items()

        return [writer, flusher_a, flusher_b, reader]

    def check(self, ctx):
        ctx.api.fanout_flush()            # drain whatever the race left
        assert ctx.inf.get(ctx.key) is None, (
            "informer cache still holds the deleted pod — batched "
            "dispatch resurrected tombstoned pod state")
        deleted_at = next((i for i, (t, _) in enumerate(ctx.seen)
                           if t == "DELETED"), None)
        assert deleted_at is not None, (
            f"DELETED never delivered (seen={ctx.seen}) — the flush "
            f"race lost the delete")
        late_mods = [e for e in ctx.seen[deleted_at + 1:]
                     if e[0] == "MODIFIED"]
        assert not late_mods, (
            f"MODIFIED delivered after DELETED ({ctx.seen}) — a split "
            f"batch defeated the per-key staleness rejection")
        rvs = [rv for _, rv in ctx.seen]
        assert rvs == sorted(rvs), (
            f"per-key delivery not RV-monotone: {ctx.seen}")


@register
class BindpoolShutdownDrain(Scenario):
    """_BindingPool shutdown-drain vs. a late permit resolution
    submitting its binding task.  Invariant: the task is executed XOR
    aborted, exactly once — a task that is neither would hold its pod's
    reservation forever (the leak the post-put re-check in submit()
    closes).  Zero workers keeps the schedule fully modeled; with no
    worker the task can never execute, so exactly one abort must happen."""

    name = "bindpool-shutdown-drain"

    def setup(self):
        from ..sched.scheduler import _BindingPool
        ctx = SimpleNamespace(executed=[], aborted=[])
        ctx.pool = _BindingPool(0)
        return ctx

    def threads(self, ctx):
        def late_permit():
            def run(task):
                ctx.executed.append(task)

            def abort(task):
                ctx.aborted.append(task)

            try:
                ctx.pool.submit(run, abort, "bind-task")
            except RuntimeError:
                # scheduler.on_permit_resolved's contract: the submitter
                # aborts inline when the pool already refused
                abort("bind-task")

        def stopper():
            ctx.pool.shutdown(timeout=0.1)

        return [late_permit, stopper]

    def check(self, ctx):
        total = len(ctx.executed) + len(ctx.aborted)
        assert total == 1, (
            f"binding task finished {len(ctx.executed)}x and aborted "
            f"{len(ctx.aborted)}x (want exactly one outcome) — a task "
            f"with no outcome leaks its pod's reservation; two outcomes "
            f"double-release it")


@register
class CondHandoff(Scenario):
    """GuardedCondition wait() hand-off: a notify delivered between the
    waiter's release and re-acquire must neither be lost nor corrupt the
    recorder's per-thread lock-stack accounting (C7 stays exact across
    _release_save/_acquire_restore).  The explorer's recorder check plus
    the post-wait re-acquire below are the witness."""

    name = "cond-handoff"

    def setup(self):
        ctx = SimpleNamespace(flag=False, wakes=[])
        ctx.lock = locking.GuardedLock("verify.handoff")
        ctx.cond = locking.GuardedCondition(ctx.lock)
        return ctx

    def threads(self, ctx):
        def waiter():
            with ctx.cond:
                while not ctx.flag:
                    ctx.wakes.append(bool(ctx.cond.wait(1.0)))
            # accounting witness: if the hand-off lost the per-thread
            # stack, this re-acquire/release pair records a violation
            with ctx.lock:
                pass

        def notifier():
            with ctx.cond:
                ctx.flag = True
                ctx.cond.notify_all()

        return [waiter, notifier]

    def check(self, ctx):
        assert ctx.flag, "notifier never ran"


# -- sharded dispatch core (sched/shards.py, ISSUE 11) -------------------------


def _pool_node(name: str, pool: str):
    node = make_node(name)
    from ..api.topology import LABEL_POOL
    node.meta.labels[LABEL_POOL] = pool
    return node


@register
class ShardCommitGuard(Scenario):
    """Two shard dispatch cycles racing the optimistic commit on ONE pool
    (the lost-update control of the sharded core).

    Each actor replays a shard lane's exact commit protocol: capture the
    pool's cursor atomically with the snapshot (``Cache.snapshot_view``),
    decide a placement against that epoch, then commit through the
    compare-and-assume (``Cache.assume_pod_guarded``).  Both target the
    same pool, so their assumes conflict by construction.  Invariant: at
    most ONE guarded commit may land per captured epoch — a schedule
    where both commits succeed against the same cursor is the lost-update
    the guard exists to stop (two placements computed against the same
    free capacity, both bound).  Progress is also pinned: at least one
    commit must land (the guard must not deadlock into mutual refusal)."""

    name = "shard-commit-guard"

    # commit tweak point: the seeded-bug variant bypasses the guard
    def _commit(self, cache: Cache, pod, node: str, expected: int):
        return cache.assume_pod_guarded(pod, node, expected) is not None

    def setup(self):
        ctx = SimpleNamespace(now=0.0, outcomes=[])
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.cache.add_node(_pool_node("a1", "pool-a"))
        ctx.cache.add_node(_pool_node("a2", "pool-a"))
        return ctx

    def threads(self, ctx):
        def lane(i: int):
            def run():
                view = ctx.cache.snapshot_view(["pool-a"])
                expected = view.pool_cursors["pool-a"]
                pod = make_pod(f"p{i}")
                ok = self._commit(ctx.cache, pod, f"a{i + 1}", expected)
                ctx.outcomes.append((i, expected, ok))
            return run

        return [lane(0), lane(1)]

    def check(self, ctx):
        committed = [(i, exp) for i, exp, ok in ctx.outcomes if ok]
        assert committed, (
            "neither lane's commit landed — the optimistic guard refused "
            "both cycles (mutual-refusal livelock shape)")
        by_epoch: Dict[int, int] = {}
        for _, exp in committed:
            by_epoch[exp] = by_epoch.get(exp, 0) + 1
        for epoch, n in by_epoch.items():
            assert n == 1, (
                f"{n} commits landed against the SAME pool epoch "
                f"{epoch} — a lost update: both cycles placed against "
                f"identical free capacity and both bound")


@register
class ShardSnapshotEpochSwap(Scenario):
    """Shard cycle vs. informer ingestion: a foreign mutation (a watch-
    confirmed pod landing in the shard's pool) racing the window between
    the shard's epoch capture and its commit.  Invariant: a guarded
    commit that LANDED implies the foreign mutation did not land inside
    the (capture, commit] window of that pool — i.e. the shard can never
    bind a placement computed against a superseded epoch (the epoch-swap
    analog of the equivalence cache's arming guard, applied to the
    commit)."""

    name = "shard-snapshot-epoch-swap"

    def setup(self):
        ctx = SimpleNamespace(now=0.0, events=[])
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.cache.add_node(_pool_node("n1", "pool-a"))
        ctx.cache.add_node(_pool_node("n2", "pool-a"))
        return ctx

    def threads(self, ctx):
        def shard():
            view = ctx.cache.snapshot_view(["pool-a"])
            expected = view.pool_cursors["pool-a"]
            ok = ctx.cache.assume_pod_guarded(
                make_pod("own"), "n1", expected) is not None
            # the commit verdict and the pool cursor it judged must be
            # read as one fact (reentrant outer lock, as in
            # EquivcacheArming's foreign actor)
            with ctx.cache._lock:
                ctx.events.append(
                    ("commit", expected, ok,
                     ctx.cache.pool_cursor("pool-a")))

        def informer():
            confirmed = make_pod("foreign", node_name="n2")
            with ctx.cache._lock:
                ctx.cache.add_pod(confirmed)
                ctx.events.append(
                    ("foreign", ctx.cache.pool_cursor("pool-a")))

        return [shard, informer]

    def check(self, ctx):
        commits = [e for e in ctx.events if e[0] == "commit"]
        assert commits, "shard actor never ran"
        _, expected, ok, after = commits[0]
        foreign = [e[1] for e in ctx.events if e[0] == "foreign"]
        if ok:
            for fcur in foreign:
                assert not (expected < fcur <= after - 1), (
                    f"guarded commit landed at cursor {after} although "
                    f"the informer's mutation reached the pool at cursor "
                    f"{fcur}, inside the (capture={expected}, commit] "
                    f"window — the shard bound a placement computed "
                    f"against a superseded epoch")
        else:
            assert foreign, (
                "guarded commit was refused although no foreign mutation "
                "ever touched the pool — a false conflict would serialize "
                "shard lanes for nothing")


@register
class CrossShardGangQuorum(Scenario):
    """Two shard lanes admitting members of ONE gang into DIFFERENT
    pools, racing a watch confirm.  Pins the two facts gang admission
    relies on under sharding: (1) commits into different pools never
    falsely conflict (cross-pool traffic must not serialize — the point
    of partitioning), and (2) the pg-assigned quorum index (the
    Coscheduling permit barrier's input, shard-agnostic process state)
    stays exact through any interleaving of guarded assumes and informer
    confirms."""

    name = "cross-shard-gang-quorum"

    def setup(self):
        ctx = SimpleNamespace(now=0.0, outcomes=[])
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.cache.add_node(_pool_node("a1", "pool-a"))
        ctx.cache.add_node(_pool_node("b1", "pool-b"))
        ctx.member_a = make_pod("m-a", pod_group="g")
        ctx.member_b = make_pod("m-b", pod_group="g")
        return ctx

    def threads(self, ctx):
        def lane_a():
            view = ctx.cache.snapshot_view(["pool-a"])
            ok = ctx.cache.assume_pod_guarded(
                ctx.member_a, "a1", view.pool_cursors["pool-a"])
            ctx.outcomes.append(("a", ok is not None))

        def lane_b():
            view = ctx.cache.snapshot_view(["pool-b"])
            ok = ctx.cache.assume_pod_guarded(
                ctx.member_b, "b1", view.pool_cursors["pool-b"])
            ctx.outcomes.append(("b", ok is not None))

        def confirm_a():
            # the watch-confirmed copy of member a (bind commit landing):
            # replaces the assumed entry, must not double-count quorum
            confirmed = make_pod("m-a", pod_group="g", node_name="a1")
            ctx.cache.add_pod(confirmed)

        return [lane_a, lane_b, confirm_a]

    def check(self, ctx):
        outcomes = dict(ctx.outcomes)
        # pool-b sees no foreign traffic in any schedule: a refusal there
        # would be a FALSE conflict (cross-pool serialization).  pool-a
        # may legitimately refuse lane a when the watch confirm raced its
        # (capture, commit] window — that is the guard doing its job.
        assert outcomes.get("b") is True, (
            f"lane b refused in a pool nothing else touched: {ctx.outcomes}"
            f" — cross-pool traffic must never serialize the lanes")
        snap = ctx.cache.snapshot()
        n = snap.assigned_count("g", "default")
        assert n == 2, (
            f"permit-quorum index counts {n} assigned members of gang g "
            f"(want exactly 2: member a — assumed or watch-confirmed, "
            f"whichever won — plus member b) — Coscheduling would "
            f"{'over' if n > 2 else 'under'}-admit the gang")


@register
class BindpoolMultiSubmitDrain(Scenario):
    """_BindingPool shutdown-drain vs. TWO lanes submitting binding tasks
    concurrently (the sharded core submits from every dispatch lane).
    Extends the PR 8 race fix's scenario: with N submitters the post-put
    re-check in submit() must guarantee EVERY task exactly one outcome —
    executed or aborted — no matter how the puts interleave with the
    drain."""

    name = "bindpool-multi-submit-drain"

    def setup(self):
        from ..sched.scheduler import _BindingPool
        ctx = SimpleNamespace(executed=[], aborted=[])
        ctx.pool = _BindingPool(0)
        return ctx

    def threads(self, ctx):
        def submitter(tag: str):
            def run():
                def fn(task):
                    ctx.executed.append(task)

                def abort(task):
                    ctx.aborted.append(task)

                try:
                    ctx.pool.submit(fn, abort, tag)
                except RuntimeError:
                    abort(tag)
            return run

        def stopper():
            ctx.pool.shutdown(timeout=0.1)

        return [submitter("lane-0"), submitter("lane-1"), stopper]

    def check(self, ctx):
        for tag in ("lane-0", "lane-1"):
            n = (ctx.executed.count(tag) + ctx.aborted.count(tag))
            assert n == 1, (
                f"task {tag} finished {ctx.executed.count(tag)}x and "
                f"aborted {ctx.aborted.count(tag)}x (want exactly one "
                f"outcome) — under multi-lane submission a task with no "
                f"outcome leaks its reservation; two outcomes double-"
                f"release it")


# -- ISSUE 14: the quota-aware optimistic commit protocol ----------------------


def _quota_pod(name: str, ns: str, chips: int):
    from ..api.resources import TPU
    return make_pod(name, namespace=ns, limits={TPU: chips})


def _quota_infos(raw):
    """Build the plugin's admission view from a cache quota_view payload —
    the same adoption path CapacityScheduling._snapshot_quotas uses."""
    from ..plugins.capacity.elasticquota_info import (ElasticQuotaInfo,
                                                      ElasticQuotaInfos,
                                                      LazyPodKeys)
    infos = ElasticQuotaInfos()
    for ns, (mn, mx, used, pods_loader) in (raw or {}).items():
        infos[ns] = ElasticQuotaInfo.from_parts(ns, mn, mx, used,
                                                LazyPodKeys(pods_loader))
    return infos


@register
class QuotaCommitGuard(Scenario):
    """Two shard lanes admitting pods of ONE ElasticQuota namespace into
    DIFFERENT pools, racing the semantic quota compare-and-reserve
    (Cache.assume_pod_guarded with a QuotaReserve — ISSUE 14).

    Each actor replays a lane's exact protocol: capture its pool epoch
    view, read the admission inputs in one critical section
    (Cache.quota_view), run the plugin's own max-bound arithmetic, and —
    only if admission passes — commit through the guarded assume with the
    request vectors it judged.  The pools differ, so the POOL cursors
    never conflict: every refusal is the QUOTA guard's.  min = max = 4
    chips and each pod asks 4, so admitting both is the overshoot the
    protocol exists to stop.  Invariants: ledger usage never exceeds max,
    exactly one pod lands (the loser either saw fresh usage and was
    rejected at admission, or was refused by the commit re-check), and a
    quota refusal implies another commit really consumed the room (the
    semantic guard never refuses on unrelated churn)."""

    name = "quota-commit-guard"
    NS = "team"
    CHIPS = 4

    # commit tweak point: the seeded-bug variant drops the quota guard
    def _commit(self, cache: Cache, pod, node: str, pool_cursor: int,
                req):
        from ..sched.cache import QUOTA_CONFLICT, QuotaReserve
        res = cache.assume_pod_guarded(
            pod, node, pool_cursor,
            quota_guard=QuotaReserve(self.NS, dict(req), dict(req)))
        if res is QUOTA_CONFLICT:
            return "quota-conflict"
        return "committed" if res is not None else "pool-conflict"

    def setup(self):
        from ..api.resources import TPU
        ctx = SimpleNamespace(now=0.0, outcomes=[])
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.cache.add_node(_pool_node("a1", "pool-a"))
        ctx.cache.add_node(_pool_node("b1", "pool-b"))
        ctx.cache.sync_quota_bounds(
            {self.NS: ({TPU: self.CHIPS}, {TPU: self.CHIPS})})
        return ctx

    def threads(self, ctx):
        def lane(i: int, pool: str, node: str):
            def run():
                from ..util.podutil import pod_effective_request
                view = ctx.cache.snapshot_view([pool])
                cursor = view.pool_cursors[pool]
                raw, _epoch = ctx.cache.quota_view()
                infos = _quota_infos(raw)
                pod = _quota_pod(f"q{i}", self.NS, self.CHIPS)
                req = pod_effective_request(pod)
                eq = infos.get(self.NS)
                if eq is not None and eq.used_over_max_with(req):
                    ctx.outcomes.append((i, "rejected"))
                    return
                ctx.outcomes.append(
                    (i, self._commit(ctx.cache, pod, node, cursor, req)))
            return run

        return [lane(0, "pool-a", "a1"), lane(1, "pool-b", "b1")]

    def check(self, ctx):
        from ..api.resources import TPU
        used = ctx.cache.quota_used_snapshot().get(self.NS, {})
        assert used.get(TPU, 0) <= self.CHIPS, (
            f"quota usage {used} exceeds max {self.CHIPS} chips — two "
            f"lanes reserved past the bound (the overshoot the "
            f"compare-and-reserve exists to stop)")
        committed = [o for o in ctx.outcomes if o[1] == "committed"]
        assert len(committed) == 1, (
            f"{len(committed)} commits landed (want exactly 1): "
            f"{ctx.outcomes}")
        for i, kind in ctx.outcomes:
            if kind == "pool-conflict":
                raise AssertionError(
                    f"lane {i} hit a POOL conflict in a pool nothing else "
                    f"touched — the quota guard must not leak into the "
                    f"pool compare")
            if kind == "quota-conflict":
                assert any(o != i and k == "committed"
                           for o, k in ctx.outcomes), (
                    f"lane {i} was quota-refused although no other commit "
                    f"consumed the room — the semantic guard must never "
                    f"refuse on unrelated churn")


@register
class QuotaBorrowAggregate(Scenario):
    """Cross-quota borrow vs a concurrent intra-min reserve: the
    aggregate gate (Σ used ≤ Σ min) spans BOTH quotas, so the two
    admissions are mutually invalidating even though they touch different
    namespaces AND different pools — exactly why the quota guard compares
    the fleet-wide epoch, not a per-namespace cursor.

    team-a (min 4 / max 8) admits a BORROWER asking 8 (over its min —
    legal while Σused + 8 ≤ Σmin = 8); team-b admits an intra-min pod
    asking 4.  Admitting both puts Σused = 12 > Σmin = 8: borrowed
    capacity that was promised to somebody's guarantee.  The commit's
    semantic re-check evaluates the aggregate bound against the LIVE
    fleet sums, which is exactly what a per-namespace check could not
    see.  Invariant: the aggregate bound holds at quiescence under every
    interleaving."""

    name = "quota-borrow-aggregate"

    def setup(self):
        from ..api.resources import TPU
        ctx = SimpleNamespace(now=0.0, outcomes=[])
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.cache.add_node(_pool_node("a1", "pool-a"))
        ctx.cache.add_node(_pool_node("b1", "pool-b"))
        ctx.cache.sync_quota_bounds({
            "team-a": ({TPU: 4}, {TPU: 8}),
            "team-b": ({TPU: 4}, {TPU: 8})})
        return ctx

    def threads(self, ctx):
        from ..sched.cache import QUOTA_CONFLICT, QuotaReserve

        def admit_and_commit(tag: str, ns: str, chips: int, pool: str,
                             node: str):
            def run():
                from ..util.podutil import pod_effective_request
                view = ctx.cache.snapshot_view([pool])
                cursor = view.pool_cursors[pool]
                raw, _epoch = ctx.cache.quota_view()
                infos = _quota_infos(raw)
                pod = _quota_pod(f"p-{tag}", ns, chips)
                req = pod_effective_request(pod)
                eq = infos.get(ns)
                if eq is None or eq.used_over_max_with(req) \
                        or infos.aggregated_used_over_min_with(req):
                    ctx.outcomes.append((tag, "rejected"))
                    return
                res = ctx.cache.assume_pod_guarded(
                    pod, node, cursor,
                    quota_guard=QuotaReserve(ns, dict(req), dict(req)))
                ctx.outcomes.append(
                    (tag, "quota-conflict" if res is QUOTA_CONFLICT
                     else "committed" if res is not None
                     else "pool-conflict"))
            return run

        return [admit_and_commit("borrow", "team-a", 8, "pool-a", "a1"),
                admit_and_commit("intra", "team-b", 4, "pool-b", "b1")]

    def check(self, ctx):
        from ..api.resources import TPU
        used = ctx.cache.quota_used_snapshot()
        total = sum(res.get(TPU, 0) for res in used.values())
        assert total <= 8, (
            f"Σ quota usage {total} chips exceeds Σ min 8 after a "
            f"borrow/intra-min race ({ctx.outcomes}) — the aggregate "
            f"borrow gate was overshot; the fleet-wide epoch compare "
            f"exists because per-namespace guards cannot see this")
        assert any(k == "committed" for _, k in ctx.outcomes), (
            f"no commit landed at all: {ctx.outcomes} — mutual refusal")


# -- seeded-bug self-checks (non-vacuity) --------------------------------------


@register
class SelfcheckLostUpdate(Scenario):
    """DELIBERATE BUG: a read-modify-write whose read and write sit in
    two separate critical sections — the textbook atomicity violation the
    flow-sensitive lint rule also catches statically.  The explorer must
    find a schedule where an increment is lost."""

    name = "selfcheck-lost-update"

    def setup(self):
        ctx = SimpleNamespace(val=0)
        ctx.lock = locking.GuardedLock("verify.selfcheck")
        return ctx

    def threads(self, ctx):
        def bump():
            with ctx.lock:
                v = ctx.val
            # lock released: the other actor's write can land here
            with ctx.lock:
                ctx.val = v + 1

        return [bump, bump]

    def check(self, ctx):
        assert ctx.val == 2, (
            f"lost update: val={ctx.val} after two increments")


@register
class SelfcheckAtomicUpdate(Scenario):
    """Soundness control for the self-check: the same increment with the
    read and write under ONE critical section.  No schedule may fail."""

    name = "selfcheck-atomic-update"

    def setup(self):
        ctx = SimpleNamespace(val=0)
        ctx.lock = locking.GuardedLock("verify.selfcheck")
        return ctx

    def threads(self, ctx):
        def bump():
            with ctx.lock:
                ctx.val = ctx.val + 1

        return [bump, bump]

    def check(self, ctx):
        assert ctx.val == 2, f"val={ctx.val} after two atomic increments"


@register
class SelfcheckBrokenArming(EquivcacheArming):
    """DELIBERATE BUG: the arming guard accepts ANY cursor advance
    (``>=`` instead of ``== +1``) — exactly the laundering the real guard
    exists to stop.  The explorer must find the schedule where the
    foreign mutation lands inside the window and the entry arms anyway."""

    name = "selfcheck-broken-arming"

    def _guard(self, cur: int, cyc: int) -> bool:
        return cur >= cyc + 1
    # check() is inherited: the parent invariant fires exactly when the
    # broken guard arms across an in-window foreign mutation


@register
class SelfcheckUnguardedCommit(ShardCommitGuard):
    """DELIBERATE BUG: the shard commit bypasses the optimistic guard and
    assumes unconditionally — exactly the stale-placement lost update the
    compare-and-assume exists to stop.  The explorer must find the
    schedule where both lanes capture the same pool epoch and both
    commit."""

    name = "selfcheck-unguarded-commit"

    def _commit(self, cache: Cache, pod, node: str, expected: int):
        cache.assume_pod(pod, node)     # no cursor compare: always "wins"
        return True
    # check() is inherited: the parent invariant fires exactly when two
    # commits land against one captured epoch


@register
class SelfcheckUnguardedQuotaReserve(QuotaCommitGuard):
    """DELIBERATE BUG: the commit drops the quota guard and compares only
    the pool cursor — the pools differ, so BOTH lanes' assumes land and
    the quota's max is overshot (the exact bug the quota epoch
    compare-and-reserve exists to stop).  The explorer must find the
    schedule where both lanes pass admission against the same epoch."""

    name = "selfcheck-unguarded-quota-reserve"

    def _commit(self, cache: Cache, pod, node: str, pool_cursor: int,
                req):
        # BUG: no quota_guard — the reserve is unguarded against
        # concurrent quota traffic
        res = cache.assume_pod_guarded(pod, node, pool_cursor)
        return "committed" if res is not None else "pool-conflict"
    # check() is inherited: the usage-over-max / two-commits invariants
    # fire exactly when both lanes reserve against one epoch


@register
class SelfcheckTimeoutWake(Scenario):
    """A timed wait with no notifier: the only way forward is the
    explorer's timeout-fire decision — pins that ~decisions are taken,
    recorded, and replayed."""

    name = "selfcheck-timeout-wake"

    def setup(self):
        ctx = SimpleNamespace(wakes=[])
        ctx.lock = locking.GuardedLock("verify.timeout")
        ctx.cond = locking.GuardedCondition(ctx.lock)
        return ctx

    def threads(self, ctx):
        def waiter():
            with ctx.cond:
                ctx.wakes.append(bool(ctx.cond.wait(0.01)))

        return [waiter]

    def check(self, ctx):
        assert ctx.wakes == [False], (
            f"timed wait with no notifier woke as {ctx.wakes} "
            f"(want one timeout wake)")


# -- ISSUE 13: the window index's cursor-consistency protocol ------------------


def _tiny_pool(pool: str):
    """A 2x2-host v5e pool (4x4 chips) + its nodes: the smallest grid with
    a non-trivial placement set."""
    from ..api.core import NodeCondition
    from ..api.resources import TPU
    from ..api.topology import (LABEL_POOL, ObjectMeta, TpuTopology,
                                TpuTopologySpec)
    hosts = {}
    nodes = []
    for i, chip_coord in enumerate(((0, 0), (0, 2), (2, 0), (2, 2))):
        name = f"{pool}-n{i}"
        hosts[name] = chip_coord
        node = make_node(name)
        node.meta.labels[LABEL_POOL] = pool
        node.status.allocatable[TPU] = 4
        node.status.conditions = [NodeCondition(type="Ready", status="True")]
        nodes.append(node)
    topo = TpuTopology(meta=ObjectMeta(name=pool, namespace=""),
                       spec=TpuTopologySpec(pool=pool, accelerator="tpu-v5e",
                                            dims=(4, 4), wrap=(False, False),
                                            hosts=hosts, chips_per_host=4))
    return topo, nodes


def _free_plane_oracle(snapshot, grid, mgrid) -> int:
    """TopologyMatch's ``free`` definition recomputed from a snapshot: a
    healthy host with zero TPU chip usage."""
    from ..api.core import node_health_error
    from ..plugins.tpuslice.chip_node import pod_tpu_limits
    free = 0
    for node, coord in grid.coord_of.items():
        info = snapshot.get(node)
        if info is None:
            continue
        used = sum(pod_tpu_limits(p)[0] for p in info.pods)
        if used or node_health_error(info.node) is not None:
            continue
        free |= 1 << mgrid.cell(coord)
    return free


@register
class WindowIndexEpoch(Scenario):
    """Index maintenance vs. snapshot-view capture vs. guarded assume
    (ISSUE 13's cursor-consistency rule).

    The dispatch actor replays a shard lane's exact read protocol: capture
    an epoch view (snapshot + pool cursor atomically), ask the window
    index for the pool's survivor count AT that cursor, then commit
    through the guarded assume.  The informer actor lands a foreign
    mutation (a node-health flip) that changes the free plane and bumps
    the cursor, racing the reader at every lock boundary.  Invariant: any
    answer the index serves for cursor C must equal the Python oracle
    recomputed from the SNAPSHOT captured at C — version-matched stale
    data is the one state the atomic stamp+apply protocol must make
    unreachable (the seeded selfcheck-stale-index variant breaks the
    atomicity and the explorer must catch it)."""

    name = "window-index-epoch"
    SHAPE = (2, 2)

    def _make_index(self):
        from ..topology.windowindex import TorusWindowIndex
        return TorusWindowIndex(publish=False)

    def setup(self):
        from ..topology.engine import MaskGrid, enumerate_placement_masks
        from ..topology.torus import HostGrid
        ctx = SimpleNamespace(now=0.0, observations=[], commits=0)
        ctx.topo, nodes = _tiny_pool("pool-w")
        ctx.cache = Cache(clock=_counter_clock(ctx))
        ctx.index = self._make_index()
        ctx.index.observe_topology(ctx.topo)
        ctx.cache.attach_window_index(ctx.index)
        for n in nodes:
            ctx.cache.add_node(n)
        ctx.sick = nodes[1].deepcopy()
        from ..api.core import NodeCondition
        ctx.sick.status.conditions = [
            NodeCondition(type="Ready", status="False")]
        ctx.grid = HostGrid.from_spec(ctx.topo.spec)
        ctx.mgrid = MaskGrid(ctx.grid)
        ctx.pset = enumerate_placement_masks(ctx.mgrid, self.SHAPE)
        # warm the shape index OUTSIDE exploration so enumeration cost
        # (and its lock holds) is not part of the schedule space
        ctx.index.ensure_shape("pool-w", self.SHAPE)
        return ctx

    def threads(self, ctx):
        def reader():
            view = ctx.cache.snapshot_view(["pool-w"])
            cursor = view.pool_cursors["pool-w"]
            q = ctx.index.query(ctx.topo, self.SHAPE, ("default", "gw"),
                                4, cursor)
            if q is not None:
                oracle_free = _free_plane_oracle(view.snapshot, ctx.grid,
                                                 ctx.mgrid)
                want = sum(1 for m in ctx.pset.masks
                           if not (m & ~oracle_free))
                ctx.observations.append((cursor, q.survivors, want))
            pod = make_pod("pw")
            if ctx.cache.assume_pod_guarded(pod, "pool-w-n0",
                                            cursor) is not None:
                ctx.commits += 1

        def informer():
            ctx.cache.update_node(ctx.sick)

        return [reader, informer]

    def check(self, ctx):
        for cursor, got, want in ctx.observations:
            assert got == want, (
                f"index served {got} survivors at cursor {cursor}; the "
                f"snapshot captured at that cursor says {want} — version-"
                f"matched STALE index data reached a dispatch cycle")


@register
class SelfcheckStaleIndex(WindowIndexEpoch):
    """DELIBERATE BUG: the informer applies the cache mutation + version
    stamp inside the cache critical section but the index's occupancy
    delta AFTER releasing it — exactly the protocol violation the real
    hooks prevent by updating the index inside the mutator's own critical
    section.  A reader capturing its epoch view in the window sees a
    version-matched plane with STALE data; the explorer must find that
    schedule (the parent invariant fires)."""

    name = "selfcheck-stale-index"

    def threads(self, ctx):
        reader, _ = super().threads(ctx)

        def buggy_informer():
            with ctx.cache._lock:
                cursor = ctx.cache._bump_locked("pool-w")
                ctx.cache._infos[ctx.sick.name].set_node(ctx.sick)
                # BUG: version published while the plane still shows the
                # node healthy...
                ctx.index.cache_note("pool-w", cursor)
            locking.verify_point("stale-index-window")
            # ...and the occupancy delta lands outside the critical section
            ctx.index.cache_node_upsert(ctx.sick, None,
                                        [("pool-w", cursor)])

        return [reader, buggy_informer]


@register
class SelfcheckFanoutResurrect(Scenario):
    """DELIBERATE BUG: the pre-batcher fan-out pairing — each mutator
    appends its watch event to the delivery queue AFTER releasing the
    store critical section (racing other mutators' appends) and the
    consumer applies events with NO per-key staleness defense.  The
    explorer must find the schedule where the delete's event overtakes
    the earlier update's append, so the flush re-applies the stale
    MODIFIED after the DELETED and resurrects tombstoned pod state —
    the exact reorder class the real batcher removes by enqueueing in
    commit order and the real informer rejects by RV."""

    name = "selfcheck-fanout-resurrect"

    def setup(self):
        # rv-1 object exists in the store and in the consumer cache
        ctx = SimpleNamespace(rv=1, store={"p": 1}, cache={"p": 1},
                              queue=[], mod_rv=None, del_rv=None)
        ctx.lock = locking.GuardedLock("verify.fanout-store")
        return ctx

    def threads(self, ctx):
        def updater():
            with ctx.lock:
                if "p" not in ctx.store:
                    return              # lost the race to the reaper
                ctx.rv += 1
                ctx.store["p"] = ctx.rv
                ctx.mod_rv = ctx.rv
                ev = ("MODIFIED", ctx.rv)
            # BUG: the append happens outside the critical section — the
            # reaper's commit AND append can both land in this window
            ctx.queue.append(ev)

        def reaper():
            with ctx.lock:
                if "p" not in ctx.store:
                    return
                ctx.rv += 1
                ctx.store.pop("p")
                ctx.del_rv = ctx.rv
                ev = ("DELETED", ctx.rv)
            ctx.queue.append(ev)        # same bug, same window

        return [updater, reaper]

    def check(self, ctx):
        # the flush: apply the queue to the defense-less consumer cache
        for typ, rv in ctx.queue:
            if typ == "MODIFIED":
                ctx.cache["p"] = rv
            else:
                ctx.cache.pop("p", None)
        # the reaper always wins the store (the updater declines once the
        # key is gone), so the pod must be gone downstream too
        assert ctx.del_rv is not None
        assert "p" not in ctx.cache, (
            f"resurrected: stale MODIFIED(rv={ctx.mod_rv}) applied after "
            f"DELETED(rv={ctx.del_rv}) — queue order {ctx.queue} inverted "
            f"commit order")


LIVE_SCENARIOS = tuple(n for n in SCENARIOS if not n.startswith("selfcheck-"))
SELFCHECK_BUGGY = ("selfcheck-lost-update", "selfcheck-broken-arming",
                   "selfcheck-unguarded-commit", "selfcheck-stale-index",
                   "selfcheck-unguarded-quota-reserve",
                   "selfcheck-fanout-resurrect")
