from .whatif import WhatIfReport, simulate_gang, simulate_plan  # noqa: F401
from .defrag import MigrationSuggestion, suggest_migrations  # noqa: F401
