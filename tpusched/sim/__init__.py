from .whatif import WhatIfReport, simulate_gang, simulate_plan  # noqa: F401
