from .whatif import WhatIfReport, simulate_gang  # noqa: F401
