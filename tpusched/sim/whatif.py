"""What-if capacity simulation: dry-run gang admission against a SHADOW copy
of cluster state.

The question a TPU fleet operator asks before submitting (or promising) a
job: *would this slice gang fit right now — and if not, what would it cost
to make it fit?* The reference world answers it with spreadsheets or by
submitting and watching; nothing in the reference tree simulates admission.
Here the whole control plane is in-process and cheap to fork, so the
simulator IS the real scheduler: clone the state (from a live APIServer or
a ``--state-dir`` WAL/snapshot), start a real scheduling loop over the
clone with the real profile, inject the hypothetical gang, and report what
happened. Placement decisions are exactly production decisions — same
plugins, same scoring, same preemption machinery — and the source cluster
is never touched.

With ``allow_preemption=True`` the full-stack profile runs, so the report
also answers the second question: *which running pods would window-wise
slice preemption evict to admit this gang* (KEP-119 addendum semantics,
quota floors and toleration exemptions included).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from ..api.resources import TPU, make_resources
from ..api.scheduling import PodGroup, PodGroupSpec
from ..api.meta import ObjectMeta
from ..api.core import Pod
from ..api.topology import LABEL_ACCELERATOR
from ..apiserver import APIServer
from ..apiserver import server as srv
from ..config import profiles as canned
from ..obs.goodput import GoodputMatrix, workload_fingerprint_of
from ..plugins import default_registry
from ..plugins.topologymatch import COORD_ANNOTATION, POOL_ANNOTATION
from ..sched import Scheduler
from ..util.podutil import assigned

# state copied into the shadow (everything the scheduler consumes; Leases
# deliberately excluded — the shadow runs its own world)
_SHADOW_KINDS = (srv.NODES, srv.PODS, srv.POD_GROUPS, srv.ELASTIC_QUOTAS,
                 srv.PRIORITY_CLASSES, srv.PDBS, srv.TPU_TOPOLOGIES)


@dataclasses.dataclass
class WhatIfReport:
    feasible: bool
    placements: Dict[str, str]          # pod key → node name
    pool: str                           # pool the gang landed in ("" if none)
    coords: Dict[str, str]              # pod key → chip coordinate annotation
    victims: List[str]                  # REAL pre-existing pods evicted
    elapsed_s: float
    reason: str                         # FailedScheduling detail if infeasible
    # plan mode only: pods of EARLIER hypothetical plan jobs this job
    # displaced (simulation artifacts, never real workloads — kept separate
    # from victims so a script acting on evictions cannot confuse them)
    displaced_plan_pods: List[str] = dataclasses.field(default_factory=list)
    # goodput annotation (set when simulate_gang is given a measured
    # GoodputMatrix, ISSUE 10 / ROADMAP item 3): the gang's workload
    # fingerprint, the generation(s) of the hardware it landed on, the
    # matrix's measured goodput-per-chip for that cell (None =
    # unmeasured — never "zero throughput"), and the generation the
    # matrix would PREFER for this workload (the Gavel question; may
    # differ from where topology-only scoring put it)
    workload: str = ""
    generation: str = ""
    goodput_per_chip: Optional[float] = None
    best_generation: Optional[str] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _shadow_of(source_api: Optional[APIServer],
               state_dir: Optional[str]) -> APIServer:
    shadow = APIServer()
    if source_api is not None:
        dump, rv = source_api.dump_for_snapshot(_SHADOW_KINDS)
        for kind, objs in dump.items():
            shadow.restore(kind, [o.deepcopy() for o in objs])
        shadow.restore_resource_version(rv)
    elif state_dir is not None:
        from ..apiserver.persistence import load_into
        load_into(shadow, state_dir)
    else:
        raise ValueError("simulate_gang needs source_api or state_dir")
    return shadow


def _set_gang_names(name: str, slices: int) -> List[str]:
    """THE derived-name scheme for a set job's member gangs — shared by
    plan validation, creation, withdrawal, and the defrag advisor's
    collision checks, so they can never desynchronize."""
    if slices <= 1:
        return [name]
    return [f"{name}-s{idx}" for idx in range(slices)]


def _run_one(shadow: APIServer, *, name: str, namespace: str, members: int,
             slice_shape: str, accelerator: str, chips_per_pod: int,
             cpu_per_pod: int, memory_per_pod: str, priority: int,
             timeout_s: float, scheduler_name: str,
             slices: int = 1,
             hypothetical: frozenset = frozenset()
             ) -> "tuple[WhatIfReport, List[str]]":
    """Inject one hypothetical gang into a live shadow. Returns the report
    plus the exact pod keys created (for plan-mode withdrawal).
    ``hypothetical``: pod keys belonging to earlier plan jobs — evictions
    of those are reported as displaced_plan_pods, not victims.

    ``slices > 1`` simulates an ATOMIC multislice set: N member gangs of
    ``members`` pods each sharing ``multislice_set=name`` with the
    declared set size, so the shadow exercises the real set barrier —
    feasible means the WHOLE set binds. The set must be one job (its
    slices barrier on each other; split across plan jobs, the first would
    wait forever for siblings the plan hasn't submitted yet)."""
    pre_existing = {p.meta.key for p in shadow.list(srv.PODS)}
    pods: List[Pod] = []
    from ..testing.wrappers import make_pod
    gang_names = _set_gang_names(name, slices)
    for idx, gname in enumerate(gang_names):
        shadow.create(srv.POD_GROUPS, PodGroup(
            meta=ObjectMeta(name=gname, namespace=namespace),
            spec=PodGroupSpec(min_member=members,
                              tpu_slice_shape=slice_shape,
                              tpu_accelerator=accelerator,
                              multislice_set=name if slices > 1 else "",
                              multislice_index=idx,
                              multislice_set_size=slices if slices > 1
                              else 0)))
        for i in range(members):
            pods.append(make_pod(
                f"{gname}-{i:03d}", namespace=namespace, pod_group=gname,
                limits={TPU: chips_per_pod},
                requests=make_resources(cpu=cpu_per_pod,
                                        memory=memory_per_pod),
                priority=priority,
                # must match the shadow profile or it ignores every pod
                scheduler_name=scheduler_name))
    start = time.perf_counter()
    for p in pods:
        shadow.create(srv.PODS, p)

    keys = [p.key for p in pods]
    deadline = time.monotonic() + timeout_s
    feasible = False
    while time.monotonic() < deadline:
        live = [shadow.peek(srv.PODS, k) for k in keys]
        if all(p is not None and assigned(p) for p in live):
            feasible = True
            break
        time.sleep(0.02)
    elapsed = time.perf_counter() - start

    placements: Dict[str, str] = {}
    coords: Dict[str, str] = {}
    pools = set()
    if feasible:
        for k in keys:
            p = shadow.peek(srv.PODS, k)
            placements[k] = p.spec.node_name
            coords[k] = p.meta.annotations.get(COORD_ANNOTATION, "")
            pl = p.meta.annotations.get(POOL_ANNOTATION, "")
            if pl:
                pools.add(pl)
    # one gang lands in one pool; a multislice set deliberately spans
    # pools — report every pool it touched, sorted and comma-joined, so
    # "pool" never names just whichever pod iterated last
    pool = ",".join(sorted(pools))
    gone = pre_existing - {p.meta.key for p in shadow.list(srv.PODS)}
    victims = sorted(gone - hypothetical)
    displaced = sorted(gone & hypothetical)
    reason = ""
    if not feasible:
        # the scheduler's own diagnosis, newest first
        for ev in reversed(shadow.events()):
            if ev.reason == "FailedScheduling" and ev.object_key in keys:
                reason = ev.message
                break
    return WhatIfReport(feasible=feasible, placements=placements,
                        pool=pool, coords=coords, victims=victims,
                        elapsed_s=round(elapsed, 4), reason=reason,
                        displaced_plan_pods=displaced), keys


def _profile_may_evict(profile) -> bool:
    """Whether this profile's PostFilter chain can EVICT pods. Coscheduling's
    PostFilter only denies gangs; every other shipped PostFilter
    (CapacityScheduling, TopologyMatch slice preemption,
    PreemptionToleration, CrossNodePreemption) drives an evictor — the
    plan-mode restore/barrier machinery must key off THIS, not off the CLI
    flag, or a --config profile with preemption enabled silently skips the
    unwind."""
    return any(name != "Coscheduling" for name in profile.post_filter)


def _make_profile(allow_preemption: bool, timeout_s: float,
                  config_path: Optional[str] = None,
                  scheduler_name: Optional[str] = None):
    """The shadow's profile: a canned one by default, or — so the simulator
    answers with EXACTLY the wiring production runs — the profile decoded
    from a TpuSchedulerConfiguration YAML (``config_path``; with several
    profiles, ``scheduler_name`` picks one)."""
    if config_path is not None:
        from ..config import versioned
        cfg = versioned.load_file(config_path)
        if scheduler_name:
            return cfg.profile(scheduler_name)  # raises ConfigError if absent
        if len(cfg.profiles) > 1:
            raise ValueError(
                f"{config_path} declares {len(cfg.profiles)} profiles; "
                "pass scheduler_name to pick one")
        return cfg.profiles[0]
    return (canned.full_stack_profile(permit_wait_s=int(timeout_s),
                                      denied_s=1)
            if allow_preemption else
            canned.tpu_gang_profile(permit_wait_s=int(timeout_s),
                                    denied_s=1))


def annotate_with_goodput(report: WhatIfReport, shadow: APIServer,
                          matrix: GoodputMatrix) -> WhatIfReport:
    """Fold the measured workload×generation throughput matrix (ISSUE
    10's goodput plane, ``obs.goodput``) into a feasibility report: what
    goodput-per-chip has this workload MEASURED on the hardware the
    shadow placed it on, and which generation would the matrix prefer?
    This is the consumption path ROADMAP item 3's Gavel-style Score
    plugin will productionize; here it lets an operator see "fits, but
    on the slow generation for this workload" before submitting."""
    if not report.placements:
        return report
    first_key = sorted(report.placements)[0]
    pod = shadow.peek(srv.PODS, first_key)
    if pod is None:
        return report
    from ..api.scheduling import pod_group_full_name
    pg_name = pod_group_full_name(pod)
    pg = shadow.try_get(srv.POD_GROUPS, pg_name) if pg_name else None
    report.workload = workload_fingerprint_of(pod, pg)
    generations = set()
    node_gen = {n.meta.name: n.meta.labels.get(LABEL_ACCELERATOR, "")
                for n in shadow.list(srv.NODES)}
    for node_name in report.placements.values():
        gen = node_gen.get(node_name, "")
        if gen:
            generations.add(gen)
    report.generation = ",".join(sorted(generations))
    if len(generations) == 1:
        report.goodput_per_chip = matrix.peek(report.workload,
                                              next(iter(generations)))
    report.best_generation = matrix.best_generation(report.workload)
    return report


def simulate_gang(source_api: Optional[APIServer] = None,
                  state_dir: Optional[str] = None, *,
                  name: str = "whatif-gang",
                  namespace: str = "default",
                  members: int,
                  slice_shape: str = "",
                  accelerator: str = "",
                  chips_per_pod: int = 1,
                  cpu_per_pod: int = 4,
                  memory_per_pod: str = "8Gi",
                  priority: int = 0,
                  slices: int = 1,
                  allow_preemption: bool = False,
                  timeout_s: float = 30.0,
                  config_path: Optional[str] = None,
                  scheduler_name: Optional[str] = None,
                  goodput_matrix: Optional[GoodputMatrix] = None
                  ) -> WhatIfReport:
    """Dry-run one hypothetical gang against a shadow of the given state.

    ``slices > 1`` asks the set question instead: would this ATOMIC
    multislice set (N slice gangs of ``members`` pods each, all-or-nothing
    barrier) fully land?

    ``config_path``/``scheduler_name`` run the shadow with a production
    TpuSchedulerConfiguration profile instead of the canned one.

    ``goodput_matrix``: a measured workload×generation throughput matrix
    (``obs.GoodputAggregator.matrix_snapshot()``, ``obs.load_matrix`` on
    an exported artifact, or ``obs.matrix_from_trace`` on a recorded
    fleet trace) — the report is then annotated with the measured
    goodput-per-chip of the placement and the matrix-preferred
    generation (``annotate_with_goodput``).

    Returns once the gang is fully bound in the shadow (feasible=True) or
    ``timeout_s`` elapses (feasible=False, with the scheduler's own
    FailedScheduling diagnosis as ``reason``)."""
    shadow = _shadow_of(source_api, state_dir)
    profile = _make_profile(allow_preemption, timeout_s,
                            config_path, scheduler_name)
    sched = Scheduler(shadow, default_registry(), profile,
                      telemetry=False)
    sched.run()
    try:
        report, _ = _run_one(shadow, name=name, namespace=namespace,
                             members=members, slice_shape=slice_shape,
                             accelerator=accelerator,
                             chips_per_pod=chips_per_pod,
                             cpu_per_pod=cpu_per_pod,
                             memory_per_pod=memory_per_pod,
                             priority=priority, slices=slices,
                             timeout_s=timeout_s,
                             scheduler_name=profile.scheduler_name)
        if goodput_matrix is not None:
            annotate_with_goodput(report, shadow, goodput_matrix)
        return report
    finally:
        sched.stop()


def simulate_plan(source_api: Optional[APIServer] = None,
                  state_dir: Optional[str] = None, *,
                  jobs: List[dict],
                  allow_preemption: bool = False,
                  timeout_s: float = 30.0,
                  config_path: Optional[str] = None,
                  scheduler_name: Optional[str] = None) -> List[WhatIfReport]:
    """Plan a QUEUE of gangs on ONE shared shadow: job N is admitted into
    the capacity jobs 0..N-1 already consumed — the "will my whole batch
    land, and in what order does it stop fitting" question. Each ``jobs``
    entry is a dict of gang kwargs (members required; name, namespace,
    slice_shape, accelerator, chips_per_pod, cpu_per_pod, memory_per_pod,
    priority optional); an unnamed job gets ``plan-<index>``. The whole
    plan is validated before anything runs (non-dict entries, unknown keys,
    duplicate or colliding names/pod keys, missing members fail fast with
    a ValueError naming the job). An infeasible job is withdrawn — its own
    pods/PodGroup deleted by exact key AND any pre-existing pods its
    preemption attempt evicted restored behind a scheduler-stop barrier —
    so one oversized job does not poison the rest of the plan. A feasible
    job's pods later displaced by a preempting job show up in that job's
    ``displaced_plan_pods`` (never ``victims``)."""
    gang_keys = {"name", "namespace", "members", "slice_shape",
                 "accelerator", "chips_per_pod", "cpu_per_pod",
                 "memory_per_pod", "priority", "slices"}
    if not isinstance(jobs, list):
        raise ValueError(f"jobs must be a list of job objects, "
                         f"got {type(jobs).__name__}")
    shadow = _shadow_of(source_api, state_dir)
    seen_names = set()
    normalized: List[dict] = []
    for i, job in enumerate(jobs):
        if not isinstance(job, dict):
            raise ValueError(f"plan job {i}: expected an object of gang "
                             f"kwargs, got {type(job).__name__}")
        bad = set(job) - gang_keys
        if bad:
            raise ValueError(f"plan job {i}: unknown keys {sorted(bad)} "
                             f"(allowed: {sorted(gang_keys)})")
        if not isinstance(job.get("members"), int) or job["members"] < 1:
            raise ValueError(f"plan job {i}: 'members' must be a positive "
                             f"integer, got {job.get('members')!r}")
        slices = job.get("slices", 1)
        if not isinstance(slices, int) or slices < 1:
            raise ValueError(f"plan job {i}: 'slices' must be a positive "
                             f"integer, got {slices!r}")
        kw = dict(name=f"plan-{i:02d}", namespace="default",
                  slice_shape="", accelerator="", chips_per_pod=1,
                  cpu_per_pod=4, memory_per_pod="8Gi", priority=0,
                  slices=1)
        kw.update(job)
        full = f"{kw['namespace']}/{kw['name']}"
        if full in seen_names:
            raise ValueError(f"plan job {i}: duplicate name {full!r}")
        for gname in _set_gang_names(kw["name"], kw["slices"]):
            gfull = f"{kw['namespace']}/{gname}"
            # cross-job check covers DERIVED names too: job "a" with
            # slices=2 creates gangs a-s0/a-s1 — a later job literally
            # named "a-s0" must fail fast here, not as a mid-plan
            # apiserver Conflict
            if gfull in seen_names:
                raise ValueError(f"plan job {i}: gang name {gfull!r} "
                                 "collides with an earlier plan job")
            if shadow.try_get(srv.POD_GROUPS, gfull) is not None:
                raise ValueError(f"plan job {i}: name {gfull!r} collides "
                                 "with an existing PodGroup in the source "
                                 "state")
            seen_names.add(gfull)
            for j in range(kw["members"]):
                pk = f"{kw['namespace']}/{gname}-{j:03d}"
                if shadow.peek(srv.PODS, pk) is not None:
                    raise ValueError(f"plan job {i}: pod key {pk!r} "
                                     "collides with an existing pod in the "
                                     "source state")
        seen_names.add(full)
        normalized.append(kw)

    profile = _make_profile(allow_preemption, timeout_s,
                            config_path, scheduler_name)
    # the restore/barrier machinery keys off what the RESOLVED profile can
    # do — a --config profile may enable preemption without the flag
    may_evict = allow_preemption or _profile_may_evict(profile)
    sched = Scheduler(shadow, default_registry(), profile,
                      telemetry=False)
    sched.run()
    reports: List[WhatIfReport] = []
    plan_pods: set = set()
    try:
        for kw in normalized:
            # `before` is only needed to undo a failed PREEMPTING job's
            # evictions; without preemption nothing can be evicted, so the
            # O(pods) deepcopy per iteration is skipped
            before = ({p.meta.key: p for p in shadow.list(srv.PODS)}
                      if may_evict else {})
            r, keys = _run_one(shadow, timeout_s=timeout_s,
                               scheduler_name=profile.scheduler_name,
                               hypothetical=frozenset(plan_pods), **kw)
            reports.append(r)
            if r.feasible:
                plan_pods.update(keys)
                plan_pods -= set(r.displaced_plan_pods)
                continue
            if may_evict:
                # hard quiescence barrier: an in-flight retry cycle could
                # otherwise evict victims AFTER the restore below, leaving
                # phantom free capacity for later jobs
                sched.stop()
            # withdraw the failed gang by EXACT key...
            for k in keys:
                try:
                    shadow.delete(srv.PODS, k)
                except srv.NotFound:
                    pass
            for gname in _set_gang_names(kw["name"], kw["slices"]):
                try:
                    shadow.delete(srv.POD_GROUPS,
                                  f"{kw['namespace']}/{gname}")
                except srv.NotFound:
                    pass
            if may_evict:
                # ...restore anything its preemption attempt evicted, then
                # bring a fresh scheduler up over the repaired state
                live = {p.meta.key for p in shadow.list(srv.PODS)}
                own = set(keys)
                restored = 0
                for k, obj in before.items():
                    if k not in live and k not in own:
                        obj.meta.resource_version = 0   # fresh write
                        shadow.create(srv.PODS, obj)
                        restored += 1
                # the report describes the PLANNED state: nothing this
                # failed attempt evicted stays evicted, so nothing may be
                # listed as a victim (the count survives in the reason)
                if restored:
                    r.reason = (f"{r.reason} [attempt evicted {restored} "
                                "pods; all restored]").strip()
                r.victims = []
                r.displaced_plan_pods = []
                sched = Scheduler(shadow, default_registry(), profile,
                                  telemetry=False)
                sched.run()
        return reports
    finally:
        sched.stop()
