"""What-if capacity simulation: dry-run gang admission against a SHADOW copy
of cluster state.

The question a TPU fleet operator asks before submitting (or promising) a
job: *would this slice gang fit right now — and if not, what would it cost
to make it fit?* The reference world answers it with spreadsheets or by
submitting and watching; nothing in the reference tree simulates admission.
Here the whole control plane is in-process and cheap to fork, so the
simulator IS the real scheduler: clone the state (from a live APIServer or
a ``--state-dir`` WAL/snapshot), start a real scheduling loop over the
clone with the real profile, inject the hypothetical gang, and report what
happened. Placement decisions are exactly production decisions — same
plugins, same scoring, same preemption machinery — and the source cluster
is never touched.

With ``allow_preemption=True`` the full-stack profile runs, so the report
also answers the second question: *which running pods would window-wise
slice preemption evict to admit this gang* (KEP-119 addendum semantics,
quota floors and toleration exemptions included).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from ..api.resources import TPU, make_resources
from ..api.scheduling import PodGroup, PodGroupSpec
from ..api.meta import ObjectMeta
from ..api.core import Pod
from ..apiserver import APIServer
from ..apiserver import server as srv
from ..config import profiles as canned
from ..plugins import default_registry
from ..plugins.topologymatch import COORD_ANNOTATION, POOL_ANNOTATION
from ..sched import Scheduler
from ..util.podutil import assigned

# state copied into the shadow (everything the scheduler consumes; Leases
# deliberately excluded — the shadow runs its own world)
_SHADOW_KINDS = (srv.NODES, srv.PODS, srv.POD_GROUPS, srv.ELASTIC_QUOTAS,
                 srv.PRIORITY_CLASSES, srv.PDBS, srv.TPU_TOPOLOGIES)


@dataclasses.dataclass
class WhatIfReport:
    feasible: bool
    placements: Dict[str, str]          # pod key → node name
    pool: str                           # pool the gang landed in ("" if none)
    coords: Dict[str, str]              # pod key → chip coordinate annotation
    victims: List[str]                  # pre-existing pods evicted to fit
    elapsed_s: float
    reason: str                         # FailedScheduling detail if infeasible

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _shadow_of(source_api: Optional[APIServer],
               state_dir: Optional[str]) -> APIServer:
    shadow = APIServer()
    if source_api is not None:
        dump, rv = source_api.dump_for_snapshot(_SHADOW_KINDS)
        for kind, objs in dump.items():
            shadow.restore(kind, [o.deepcopy() for o in objs])
        shadow.restore_resource_version(rv)
    elif state_dir is not None:
        from ..apiserver.persistence import load_into
        load_into(shadow, state_dir)
    else:
        raise ValueError("simulate_gang needs source_api or state_dir")
    return shadow


def simulate_gang(source_api: Optional[APIServer] = None,
                  state_dir: Optional[str] = None, *,
                  name: str = "whatif-gang",
                  namespace: str = "default",
                  members: int,
                  slice_shape: str = "",
                  accelerator: str = "",
                  chips_per_pod: int = 1,
                  cpu_per_pod: int = 4,
                  memory_per_pod: str = "8Gi",
                  priority: int = 0,
                  allow_preemption: bool = False,
                  timeout_s: float = 30.0) -> WhatIfReport:
    """Dry-run one hypothetical gang against a shadow of the given state.

    Returns once the gang is fully bound in the shadow (feasible=True) or
    ``timeout_s`` elapses (feasible=False, with the scheduler's own
    FailedScheduling diagnosis as ``reason``)."""
    shadow = _shadow_of(source_api, state_dir)
    pre_existing = {p.meta.key for p in shadow.list(srv.PODS)}

    profile = (canned.full_stack_profile(permit_wait_s=int(timeout_s),
                                         denied_s=1)
               if allow_preemption else
               canned.tpu_gang_profile(permit_wait_s=int(timeout_s),
                                       denied_s=1))
    sched = Scheduler(shadow, default_registry(), profile)
    sched.run()
    try:
        shadow.create(srv.POD_GROUPS, PodGroup(
            meta=ObjectMeta(name=name, namespace=namespace),
            spec=PodGroupSpec(min_member=members,
                              tpu_slice_shape=slice_shape,
                              tpu_accelerator=accelerator)))
        pods: List[Pod] = []
        from ..testing.wrappers import make_pod
        for i in range(members):
            pods.append(make_pod(
                f"{name}-{i:03d}", namespace=namespace, pod_group=name,
                limits={TPU: chips_per_pod},
                requests=make_resources(cpu=cpu_per_pod,
                                        memory=memory_per_pod),
                priority=priority))
        start = time.perf_counter()
        for p in pods:
            shadow.create(srv.PODS, p)

        keys = [p.key for p in pods]
        deadline = time.monotonic() + timeout_s
        feasible = False
        while time.monotonic() < deadline:
            live = [shadow.peek(srv.PODS, k) for k in keys]
            if all(p is not None and assigned(p) for p in live):
                feasible = True
                break
            time.sleep(0.02)
        elapsed = time.perf_counter() - start

        placements: Dict[str, str] = {}
        coords: Dict[str, str] = {}
        pool = ""
        if feasible:
            for k in keys:
                p = shadow.peek(srv.PODS, k)
                placements[k] = p.spec.node_name
                coords[k] = p.meta.annotations.get(COORD_ANNOTATION, "")
                pool = p.meta.annotations.get(POOL_ANNOTATION, pool)
        victims = sorted(pre_existing
                         - {p.meta.key for p in shadow.list(srv.PODS)})
        reason = ""
        if not feasible:
            # the scheduler's own diagnosis, newest first
            for ev in reversed(shadow.events()):
                if ev.reason == "FailedScheduling" and ev.object_key in keys:
                    reason = ev.message
                    break
        return WhatIfReport(feasible=feasible, placements=placements,
                            pool=pool, coords=coords, victims=victims,
                            elapsed_s=round(elapsed, 4), reason=reason)
    finally:
        sched.stop()
