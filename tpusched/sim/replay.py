"""Deterministic fleet-trace replay: re-feed a recorded workload into a
fresh scheduler and report what it did differently.

The problem this solves (doc/performance.md): this class of box cannot
resolve small wall-clock deltas by A/B because the *workload generator*
and the ambient load are part of every measurement.  A recorded fleet
trace (tpusched/obs/fleetrace.py) removes the first variable entirely —
two replays of the same trace pose the scheduler the byte-identical
problem, so comparisons become placement diffs, bind counts, cycle
counts and profiler attribution instead of noisy seconds.  The same
machinery answers the policy questions ROADMAP items 3 and 4 ask:
replay yesterday's arrivals under a DIFFERENT profile (score weights,
preemption policy, defrag strategy) and diff the outcome against the
recorded reality.

Mechanics: the trace's snapshot seeds a fresh in-memory APIServer, a
SHADOW scheduler (``telemetry=False`` — trial binds must never pollute
live telemetry, and the replay driver must never reach the process-global
surfaces) schedules over it, and the feeder applies the recorded workload
events in capture order:

- ``lockstep`` pace (default): apply one event, wait for the scheduler to
  quiesce (store cursor stable + active queue empty), apply the next.
  Wall time disappears from the equation — with the determinism profile
  overrides (``parallelism=1``, full node sweeps) two replays of the same
  trace into the same config yield byte-identical placement sequences;
- ``timed`` pace: sleep the recorded inter-event gaps (divided by
  ``speedup``) — the realistic-rate mode ``bench.py --replay`` measures
  sustained throughput with.

VIRTUAL TIME (the ISSUE 15 tentpole): deterministic lockstep now runs on
a ``util/clock.VirtualClock`` — the scheduler, its queues, the permit
barrier, Coscheduling's denial window, the watchdog, escalation TTLs and
the flush windows all read the injected clock, and every wall-window
gate ARMS its expiry on it.  The driver advances the clock along the
trace's own recorded timeline (each event is applied at its recorded
mono instant), and whenever the system is quiescent before the next
event it jumps straight to the earliest armed deadline and fires the
gates due there (``Scheduler.run_timers_once``).  Recorded hours
compress into wall seconds while every timeout fires in faithful order —
which is what makes policy evaluation honest: the pre-ISSUE-15 mode
ZEROED every gate (pod backoff, denial window, watchdog off), erasing
exactly the retry/timeout dynamics a round-based policy study measures.
That mode survives as ``legacy_zeroed_gates=True``
(``cmd.trace replay --legacy-zeroed-gates``), the A/B arm the
replay-smoke divergence gate compares against.

What is and is not re-applied: workload events (arrivals, deletes, node
add/health/delete, quota and PodGroup changes) are re-fed; recorded
``bind-commit``/``bind-decision`` events are NOT — they are the recorded
reality the replay's own decisions are diffed against.  Scheduler-owned
derived state is stripped before injection (a recorded preemption
nomination or PodGroup phase forced into the replay would smuggle the
recorded scheduler's decisions into the new one's inputs).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..api.resources import TPU
from ..api.topology import LABEL_POOL
from ..apiserver import APIServer
from ..apiserver import server as srv
from ..apiserver.persistence import KIND_CLASSES, decode_object
from ..obs.fleetrace import FleetTrace, load_trace
from ..plugins import default_registry
from ..sched import Scheduler
from ..util import klog
from ..util.podutil import pod_effective_request
from .whatif import _make_profile

__all__ = ["ReplayReport", "run_replay", "apply_event", "diff_placements",
           "recorded_reality"]

# event kinds the feeder applies; everything else (bind-commit,
# bind-decision, capture/segment/snapshot markers) is recorded reality or
# framing, never re-fed
_APPLIED_KINDS = frozenset((
    "pod-arrival", "pod-update", "pod-delete",
    "node-add", "node-update", "node-health", "node-delete",
    "podgroup-add", "podgroup-update", "podgroup-phase", "podgroup-delete",
    "quota-add", "quota-update", "quota-delete",
    "topology-add", "topology-update", "topology-delete",
))

_KIND_BY_STEM = {
    "pod": srv.PODS, "node": srv.NODES, "podgroup": srv.POD_GROUPS,
    "quota": srv.ELASTIC_QUOTAS, "topology": srv.TPU_TOPOLOGIES,
}

# Virtual-time drain bound: consecutive deadline fires that release no new
# bind before the driver concedes (a fleet whose gangs retry forever —
# watchdog reactivation → fail → park → watchdog — would otherwise walk
# virtual time indefinitely at zero wall cost per step).
_MAX_DRAIN_FIRES = 200

# Report-size bound for the per-pod retry-ordinal record.
_RETRIES_CAP = 2000

# lockstep pays its settle wait only after events that change what the
# scheduler can DO.  podgroup-update IS such an event — apply_event
# carries its SPEC changes (a lowered min_member unblocks a parked gang)
# even though the derived status is stripped.  podgroup-phase events are
# pure status mirrors (phase is re-derived by the replay's own
# Coscheduling), cannot unblock or re-block a pod, and a storm trace
# carries hundreds of them — they alone skip the barrier.
_QUIESCE_KINDS = frozenset((
    "pod-arrival", "pod-update", "pod-delete",
    "node-add", "node-update", "node-health", "node-delete",
    "podgroup-add", "podgroup-update", "podgroup-delete",
    "quota-add", "quota-update", "quota-delete",
    "topology-add", "topology-update", "topology-delete",
))


@dataclasses.dataclass
class ReplayReport:
    """One replay's outcome, structured for diffing and for the
    differential report ``cmd.trace replay``/``diff`` render."""
    trace_dir: str
    scheduler_name: str
    pace: str
    deterministic: bool
    workload_fingerprint: str
    events_applied: int
    events_skipped: int
    # [pod key, node] ordered by the pod's ARRIVAL sequence — bind-commit
    # order races across bind-pool threads, arrival order does not, so
    # this is the canonical (byte-comparable) placement sequence
    placements: List[List[str]]
    binds: int
    unbound: List[str]
    pod_e2e: Dict[str, float]           # replay-clock p50/p99/attainment
    pool_utilization: List[dict]        # [{"event": i, "pools": {p: chips}}]
    feed_window_s: float
    elapsed_s: float
    # sharded-dispatch attribution inputs (sched/shards.py): the lane
    # count the replay ran with, and every unit the router escalated to
    # the global lane — shards.attribute_placement_diff consumes these to
    # separate policy-explained placement moves from real divergences
    dispatch_shards: int = 1
    escalated_units: List[str] = dataclasses.field(default_factory=list)
    escalations_truncated: bool = False
    # -- virtual-time + scheduling-quality evaluation plane (ISSUE 15) --
    # which clock governed the gates: "virtual" (discrete-event replay
    # time, the default deterministic mode), "zeroed" (the legacy
    # zeroed-gate lockstep), or "wall" (timed / production-fidelity runs)
    clock_mode: str = "wall"
    # the virtual↔wall mapping stamp: recorded span, the wall seconds the
    # replay actually took, their ratio, and the fired-deadline census —
    # an operator (and the smoke gate) tells a compressed evaluation from
    # a timed one at a glance
    virtual_time: dict = dataclasses.field(default_factory=dict)
    # arrival → first scheduling attempt, per pod (p50/p99); the queueing
    # component the JCT (pod_e2e) number folds in
    queueing_delay: dict = dataclasses.field(default_factory=dict)
    # pods that needed >1 scheduling attempt: pod key → attempts at
    # resolution.  The retry-ordinal record the virtual-vs-zeroed
    # divergence gate attributes against (bounded; see retries_truncated)
    retries: Dict[str, int] = dataclasses.field(default_factory=dict)
    retries_truncated: bool = False
    # the shadow scheduler's own SLO tracker summary, observed on replay
    # time (obs/slo.SLOTracker.summary(): attainment/burn/p50/p99/span)
    slo: dict = dataclasses.field(default_factory=dict)
    # -- the incident plane in virtual time (ISSUE 20) --
    # the shadow scheduler's private health-timeline census (sample/
    # overflow counts + family set): two virtual replays of one trace
    # must render this byte-identically — the determinism smoke pins it
    timeline: dict = dataclasses.field(default_factory=dict)
    # sentinel firings by detector + incident-bundle census from the
    # shadow's in-memory ring: a policy that wedges gangs surfaces here,
    # and cmd.trace evaluate fails the arm on it
    incidents: dict = dataclasses.field(default_factory=dict)
    # per-sample fragmentation trajectory rides in pool_utilization
    # (each sample carries a "frag" map when topologies are present)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _upsert(api: APIServer, kind: str, obj: Any) -> None:
    """Idempotent apply (the journal-replay put=upsert discipline): a
    compaction snapshot can run slightly ahead of the event stream, so an
    event may re-describe an object the snapshot already carried."""
    obj.meta.resource_version = 0       # fresh write, no precondition
    try:
        api.create(kind, obj)
    except srv.Conflict:
        api.update(kind, obj)


def _delete(api: APIServer, kind: str, key: str) -> None:
    try:
        api.delete(kind, key)
    except srv.NotFound:
        pass


def _decode(ev: dict):
    cls = KIND_CLASSES.get(ev.get("objkind", ""))
    if cls is None or "object" not in ev:
        return None
    return decode_object(cls, ev["object"])


def apply_event(api: APIServer, ev: dict, *,
                rename_scheduler: str = "") -> bool:
    """Apply one recorded workload event to ``api``.  Returns False for
    event kinds that are never re-fed (recorded reality / framing).

    ``rename_scheduler``: rewrite arriving pods' ``spec.scheduler_name``
    so a workload recorded under one profile name replays into a config
    that names its profile differently (policy evaluation)."""
    kind = ev.get("kind", "")
    if kind not in _APPLIED_KINDS:
        return False
    stem = kind.split("-", 1)[0]
    store_kind = _KIND_BY_STEM[stem]

    if kind == "pod-delete":
        _delete(api, store_kind, ev["pod"])
        return True
    if kind in ("node-delete", "podgroup-delete"):
        _delete(api, store_kind, ev.get("node") or ev.get("gang"))
        return True
    if kind in ("quota-delete", "topology-delete"):
        _delete(api, store_kind, ev["name"])
        return True

    obj = _decode(ev)
    if obj is None:
        return False
    if store_kind == srv.PODS:
        # scheduler-owned derived state must not leak into the replay's
        # inputs: a recorded preemption nomination is the RECORDED
        # scheduler's decision, not part of the workload
        obj.status.nominated_node_name = ""
        if rename_scheduler and not obj.spec.node_name:
            obj.spec.scheduler_name = rename_scheduler
        while True:
            live = api.peek(srv.PODS, obj.meta.key)
            if live is not None and live.spec.node_name \
                    and not obj.spec.node_name:
                # the capture snapshot runs on the writer thread and can
                # land slightly AHEAD of the event stream: this arrival/
                # update re-describes a pod the snapshot already carried —
                # possibly bound by the replay scheduler by now.  Upserting
                # the stale pending view would UN-bind it, a transition the
                # scheduler cache has no path for (phantom chip occupancy
                # forever).  The bound view is newer; the event is old news.
                return True
            # conditional write on the rv the guard judged: the scheduler's
            # bind thread can commit between peek and PUT, and an
            # unconditional upsert would un-bind the pod it just placed —
            # a Conflict re-runs the guard instead
            obj.meta.resource_version = \
                0 if live is None else live.meta.resource_version
            try:
                if live is None:
                    api.create(srv.PODS, obj)
                else:
                    api.update(srv.PODS, obj)
                return True
            except srv.Conflict:
                continue
    if store_kind == srv.POD_GROUPS and kind != "podgroup-add":
        # same discipline for gangs: spec changes replay, but phase/counts
        # are derived by the replay's own scheduler and controllers
        live = api.try_get(store_kind, obj.meta.key)
        if live is not None:
            live.spec = obj.spec
            _upsert(api, store_kind, live)
        else:
            _upsert(api, store_kind, obj)
        return True
    _upsert(api, store_kind, obj)
    return True


def _quiesce(api: APIServer, sched: Scheduler, settle_s: float,
             timeout_s: float, include_backoff: bool = True) -> bool:
    """Lockstep barrier: the store cursor has not moved, the active queue
    is empty, and NO scheduling cycle is in flight or newly started, for a
    settle window.  Pods parked at a permit barrier (gang waiting for
    siblings) or in unschedulableQ are quiescent by design — the next
    recorded event is what un-sticks them.  The cycle counters matter: a
    popped pod mid-cycle is invisible to queue depths and (until a bind
    lands) to the store, so without them the barrier could release while
    a cycle is still deciding — the next event would then race that
    cycle's snapshot, and whether the sweep sees the event varies run to
    run (the divergence gets MORE likely the faster cycles get; the torus
    window index made it reproducible)."""
    deadline = time.monotonic() + timeout_s
    last_rv = -1
    last_started = -1
    stable_since: Optional[float] = None
    while time.monotonic() < deadline:
        rv = api.current_resource_version()
        pending = sched.queue.pending_counts()
        # backoff counts as active in ZEROED-gate mode (a backoffQ
        # resident is imminently poppable there, so releasing the barrier
        # over it lets the next event race the pod's flush+pop).  Under
        # VIRTUAL time backoff windows are real: a backoff resident is
        # parked until the driver advances the clock — counting it as
        # active would spin the barrier against a pod that cannot move.
        active = pending.get("active", 0) \
            + (pending.get("backoff", 0) if include_backoff else 0)
        started = sched.cycles_started
        # queue-side mid-cycle census (counted inside pop()'s critical
        # section): gap-free where the scheduler-side counters have a
        # pop→increment window
        in_flight = (started - sched.cycles_finished
                     + sched.queue.in_cycle())
        now = time.monotonic()
        if rv == last_rv and active == 0 and in_flight == 0 \
                and started == last_started:
            if stable_since is None:
                stable_since = now
            elif now - stable_since >= settle_s:
                return True
        else:
            last_rv = rv
            last_started = started
            stable_since = None
        time.sleep(0.002)
    return False


def _percentiles(values: List[float]) -> Tuple[float, float]:
    if not values:
        return 0.0, 0.0
    s = sorted(values)
    p50 = s[min(len(s) - 1, int(0.50 * (len(s) - 1) + 0.5))]
    p99 = s[min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.5))]
    return p50, p99


def run_replay(trace_dir: str, *,
               trace: Optional[FleetTrace] = None,
               config_path: Optional[str] = None,
               scheduler_name: Optional[str] = None,
               allow_preemption: bool = False,
               profile=None,
               deterministic: bool = True,
               legacy_zeroed_gates: bool = False,
               pace: str = "lockstep",
               speedup: float = 1.0,
               settle_s: float = 0.02,
               event_timeout_s: float = 15.0,
               drain_timeout_s: float = 120.0,
               util_sample_every: int = 50,
               fragmentation_curve: bool = True,
               dispatch_shards: int = 0) -> ReplayReport:
    """Replay a recorded trace into a fresh shadow scheduler.

    ``deterministic`` (default) overrides the profile to ``parallelism=1``
    and full node sweeps: the threaded Filter sweep's rotating start index
    advances by a thread-timing-dependent visited count, which on sampled
    sweeps (>100 hosts) makes feasible sets run-dependent — exactly the
    nondeterminism a replay exists to remove.  Pass
    ``deterministic=False`` to measure with production parallelism
    (timed-pace throughput runs).

    Deterministic lockstep runs on VIRTUAL time by default: the shadow
    scheduler gets a ``util/clock.VirtualClock`` anchored on the trace's
    recorded timeline, every permit/backoff/denial/watchdog/flush window
    keeps its production value, and the driver jumps the clock between
    armed deadlines and recorded event instants (module docstring).
    ``legacy_zeroed_gates=True`` restores the pre-ISSUE-15 behavior —
    wall clock with every retry gate zeroed — as the A/B escape hatch.

    ``pace``: ``lockstep`` (apply → quiesce → apply; the diffable mode) or
    ``timed`` (recorded inter-event gaps divided by ``speedup``).

    ``dispatch_shards`` > 0 overrides the profile's lane count — the
    sharded-vs-single lockstep equivalence gate (make replay-smoke) runs
    the same trace at shards=1 and shards=N and diffs the placements.
    Lockstep pacing keeps a sharded replay deterministic: each applied
    event settles before the next, so at most one unit is in flight and
    exactly one lane (its router-assigned one) processes it."""
    if trace is None:
        trace = load_trace(trace_dir)
    prof = profile if profile is not None else _make_profile(
        allow_preemption, 30.0, config_path, scheduler_name)
    if dispatch_shards > 0:
        prof = dataclasses.replace(prof, dispatch_shards=dispatch_shards)
    virtual = deterministic and pace == "lockstep" \
        and not legacy_zeroed_gates
    if virtual:
        # determinism WITHOUT gate surgery: single-threaded full sweeps
        # make the cycle pure; the windows stay at production values and
        # fire on the virtual clock in recorded-timeline order.
        prof = dataclasses.replace(prof, parallelism=1,
                                   percentage_of_nodes_to_score=100)
    elif deterministic:
        # parallelism=1 + full sweeps: thread-timing-dependent visited
        # counts and sampled feasible sets out.  The WALL-clock retry
        # gates are ZEROED, not merely shortened: lockstep packs recorded
        # seconds into milliseconds, so any nonzero pod backoff or
        # Coscheduling denied-gang window turns into a race between the
        # window's wall expiry and the event pacing — whether a woken pod
        # retries now or next event would vary run to run, and one
        # divergent cycle cascades into a different placement sequence.
        # Zero means purely event-driven retries (both knobs document 0
        # as a supported value), which is exactly deterministic.
        plugin_args = dict(prof.plugin_args)
        cos = plugin_args.get("Coscheduling")
        if cos is not None:
            # denied-window 0: purely event-driven gang retries.
            # pg_status_flush 0: per-bind PG status patches — a coalesced
            # flush landing a window later would move the store's resource
            # version at a wall instant the lockstep barrier cannot order.
            plugin_args["Coscheduling"] = dataclasses.replace(
                cos, denied_pg_expiration_time_seconds=0,
                pg_status_flush_seconds=0.0)
        # the stuck-gang watchdog is a wall-clock retry gate too: its
        # force-reactivation of parked members fires at a wall instant
        # that lands on a run-dependent event boundary (a ~30 s replay
        # straddles the 30 s default), giving pods extra retries whose
        # outcomes race the event pacing — the faster the cycles (the
        # torus window index), the more visibly two runs diverge.  0
        # disables it; replay retries stay purely event-driven.
        # unschedulable_flush 0: the last wall-clock retry gate.  The
        # queue's move drains are now EVENT-LOGICAL (ISSUE 14 satellite:
        # cycle-scoped move masks + the park-time check in
        # sched/queue.add_unschedulable_if_not_present), so sharded
        # lockstep replays no longer pin the pre-index sweep path — the
        # window index stays ON and the shards=1-vs-N equivalence gate
        # exercises exactly the production read surface.
        # escalation_ttl pinned past any replay length: a unit escalated to
        # the global lane stays there — a wall TTL lapsing mid-replay would
        # re-route it at a run-dependent event boundary (and the escalated
        # set the attribution gate reads already covers it either way).
        prof = dataclasses.replace(prof, parallelism=1,
                                   percentage_of_nodes_to_score=100,
                                   pod_initial_backoff_s=0.0,
                                   pod_max_backoff_s=0.0,
                                   stuck_gang_after_s=0.0,
                                   unschedulable_flush_s=0.0,
                                   escalation_ttl_s=1e9,
                                   plugin_args=plugin_args)

    api = APIServer()
    for kind, objs in trace.objects.items():
        if not objs:
            continue
        seeded = [o.deepcopy() for o in objs]
        # the compaction snapshot carries the RECORDED scheduler's derived
        # state: the same discipline apply_event enforces on streamed
        # events applies here, or a compacted trace replays differently
        # from the identical uncompacted one (nominations/phases inherited
        # as if they were the replay's own decisions)
        if kind == srv.PODS:
            for o in seeded:
                o.status.nominated_node_name = ""
                if not o.spec.node_name:
                    o.spec.scheduler_name = prof.scheduler_name
        elif kind == srv.POD_GROUPS:
            for o in seeded:
                o.status = type(o.status)()
        # restore() advances the store's resource_version to the max
        # restored rv itself
        api.restore(kind, seeded)

    # -- the replay clock -----------------------------------------------------
    # Virtual mode anchors a discrete-event clock on the trace's own
    # timeline: now() starts at the first recorded mono stamp (so armed
    # deadlines and event instants share one scale) and wall() at the
    # first recorded wall stamp (so wall-flavored math — queue
    # timestamps, SLO clocks, creation-timestamp intervals — reads
    # recorded-epoch time).  Other modes keep the zero-overhead default.
    from ..util.clock import VirtualClock, WALL
    event_monos = [e["mono"] for e in trace.events if "mono" in e]
    anchor_mono = min(event_monos) if event_monos else 0.0
    anchor_wall = next((e["wall"] for e in trace.events if "wall" in e),
                       anchor_mono)
    clk = VirtualClock(start=anchor_mono, wall0=anchor_wall) if virtual \
        else WALL

    # placement observer: arrival sequence assigned at injection, bind
    # transitions observed at the watch boundary (the same boundary the
    # capture recorded reality at)
    arrival_seq: Dict[str, int] = {}
    # pods PENDING in the seeding snapshot are workload too — compaction
    # discarded their pod-arrival events, but the replay schedules them
    # and the recorded stream carries their post-snapshot bind-commits;
    # leaving them out of the sequence would make every compacted trace
    # diff as only-in-recorded.  Snapshot order is the capture's write
    # order, so it is stable across replays.
    for pod in trace.objects.get(srv.PODS, ()):
        if not pod.spec.node_name:
            arrival_seq.setdefault(pod.meta.key, len(arrival_seq))
    seq_lock = threading.Lock()
    bound: Dict[str, Tuple[str, float]] = {}      # pod → (node, mono)
    inject_ts: Dict[str, float] = {}

    def on_pod_event(ev: srv.WatchEvent) -> None:
        if ev.type != srv.MODIFIED:
            return
        old, new = ev.old_object, ev.object
        if new.spec.node_name and (old is None or not old.spec.node_name):
            with seq_lock:
                # stamped on the REPLAY clock (virtual wall under virtual
                # time): JCT/e2e deltas then measure replay-timeline
                # latency, not the wall seconds the replay compressed into
                bound[new.meta.key] = (new.spec.node_name, clk.wall())
    api.add_watch(srv.PODS, on_pod_event, replay=False)

    # node → pool map for the utilization curve (snapshot + node-add feed)
    pool_of: Dict[str, str] = {}
    chips_of: Dict[str, int] = {}

    def note_pod(ev: dict) -> None:
        obj = _decode(ev)
        if obj is not None:
            chips_of[ev["pod"]] = int(
                pod_effective_request(obj).get(TPU, 0))

    for node in trace.objects.get(srv.NODES, ()):
        pool_of[node.meta.name] = node.meta.labels.get(LABEL_POOL, "")

    # teardown coupling (lockstep): a recorded pod-delete happened AFTER
    # that pod finished running — its timing depends on the recorded
    # run's bind times.  The replay makes its own placements, so applying
    # teardowns at raw stream position lets them overtake the replay's
    # in-flight work and starve it of the recycled capacity the recorded
    # run had.  Gate each recorded-bound pod's delete on the replay
    # having bound it too (or the system being provably stable — a pod
    # the replay cannot place must not stall the stream forever).
    ever_bound = {p for p, _ in trace.recorded_binds()}

    # SERIAL lane multiplexing (deterministic sharded replays): lockstep
    # pacing makes event order logical; driving cycles from THIS thread —
    # one pod per lane, canonical lane order, via drive_dispatch_once —
    # makes cycle order logical too.  Physical lane threads racing each
    # other bind into different pools in either order and score each
    # other's occupancy differently, which at window-index cycle speeds
    # made two identical sharded replays diverge (the reason the pre-14
    # core pinned the index OFF here).  The routing, partitioning,
    # escalation and guarded-commit semantics are byte-identical to the
    # threaded core — only the interleaving is canonicalized.
    serial = virtual or (deterministic and pace == "lockstep"
                         and prof.effective_dispatch_shards() > 1)
    sched = Scheduler(api, default_registry(), prof, telemetry=False,
                      clock=clk if virtual else time.time)
    # per-cycle tap: first-attempt instants (→ queueing delay) and the
    # per-pod retry-ordinal record (→ the virtual-vs-zeroed divergence
    # attribution in make replay-smoke)
    first_attempt: Dict[str, float] = {}
    attempts_of: Dict[str, int] = {}

    def _on_cycle(key: str, attempts: int, now_wall: float) -> None:
        first_attempt.setdefault(key, now_wall)
        if attempts > attempts_of.get(key, 0):
            attempts_of[key] = attempts
    sched.cycle_observer = _on_cycle
    if not serial:
        sched.run()

    def settle(window_s: float, timeout_s: float) -> bool:
        if not serial:
            return _quiesce(api, sched, window_s, timeout_s)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if sched.drive_dispatch_once():
                continue
            # no lane had poppable work: wait for async tails (bind pool,
            # watch fan-out) to stabilize, re-driving if they wake pods
            if _quiesce(api, sched, window_s, min(0.25, timeout_s),
                        include_backoff=not virtual):
                if not sched.drive_dispatch_once():
                    return True
        return False

    def advance_until(v_target: float) -> None:
        """The virtual-time driver core: fire every armed deadline BEFORE
        ``v_target`` in order — settle the system at its current instant,
        jump the clock to the deadline, run the due gates
        (``run_timers_once``) — then jump to ``v_target`` itself.
        Faithful order is the whole point: a backoff release at t+3 runs
        its retry before the denial window lapsing at t+5, exactly as a
        live fleet would have.  Cost discipline: when nothing is armed
        before the target (the overwhelmingly common per-event case, and
        every recorded quiet gap) this is a few clock reads and ONE jump
        — no settle, no sweep."""
        settled = False
        while True:
            nxt = clk.next_deadline()
            if nxt is None or nxt >= v_target:
                break
            if not settled:
                # quiesce the current instant before the first gate
                # fires: work released by the last applied event must
                # finish deciding at its own time first
                settle(settle_s, event_timeout_s)
                settled = True
            if clk.advance_to_next_deadline(limit=v_target) is None:
                break
            expired = sched.run_timers_once()
            # cheap released-work probe: pop() flushes due backoff
            # internally, so one drive pass sees everything a fired gate
            # could have woken — except expired permit barriers, whose
            # failure paths hand off to the bind pool asynchronously
            # (run_timers_once reports those, and they force a settle).
            # Most fires are stale (a flush window that already drained,
            # a permit that already resolved) — they release nothing and
            # skip the full settle entirely, which is what keeps a
            # deadline-dense recorded hour cheap.
            if expired or sched.drive_dispatch_once():
                settle(settle_s, event_timeout_s)
        clk.advance_to(v_target)
    start = time.monotonic()
    applied = skipped = 0
    samples: List[dict] = []
    prev_mono: Optional[float] = None

    def await_bound(key: str) -> None:
        """Progress-gated wait: keep holding the teardown while the fleet
        is still binding SOMETHING (the target may be next); a no-binds
        window means the replay cannot place it with current capacity —
        recorded reality's teardown schedule resumes.  Cheap for the
        common cases: an already-bound target returns immediately, a
        stuck one costs a fraction of a second.

        Virtual time adds one move: when the system is stable but the
        target is parked behind an armed gate (its backoff, its gang's
        denial window, a permit deadline), the driver fires deadlines
        forward — bounded — instead of concluding "unplaceable"."""
        deadline = time.monotonic() + event_timeout_s
        last_binds = len(bound)
        last_progress = time.monotonic()
        fires = 0
        while time.monotonic() < deadline:
            live = api.peek(srv.PODS, key)
            if live is None or live.spec.node_name:
                return
            if serial:
                sched.drive_dispatch_once()
            now = time.monotonic()
            if len(bound) != last_binds:
                last_binds = len(bound)
                last_progress = now
            elif now - last_progress > max(0.15, settle_s * 3):
                if not virtual:
                    return
                # stable and unbound: fire the next armed gate (if any)
                # and give the retry it releases a chance to bind
                settle(settle_s, event_timeout_s)
                fired = clk.advance_to_next_deadline() \
                    if fires < _MAX_DRAIN_FIRES else None
                if fired is None:
                    return
                fires += 1
                sched.run_timers_once()
                last_progress = time.monotonic()
            time.sleep(0.0 if serial else 0.005)
    try:
        for i, ev in enumerate(trace.events):
            kind = ev.get("kind", "")
            if pace == "lockstep" and kind == "pod-delete" \
                    and ev.get("pod") in ever_bound:
                await_bound(ev["pod"])
            if pace == "timed" and prev_mono is not None and "mono" in ev:
                gap = (ev["mono"] - prev_mono) / max(speedup, 1e-6)
                if gap > 0:
                    time.sleep(min(gap, 10.0))
            if virtual and "mono" in ev:
                # recorded-timeline pacing: settle, fire every armed gate
                # due BEFORE this event's recorded instant (in order),
                # then jump the clock to the instant itself — the event
                # applies at its recorded time, after every timeout that
                # preceded it
                advance_until(ev["mono"])
            prev_mono = ev.get("mono", prev_mono)
            if kind == "node-add":
                obj = _decode(ev)
                if obj is not None:
                    pool_of[obj.meta.name] = obj.meta.labels.get(
                        LABEL_POOL, "")
            if not apply_event(api, ev,
                               rename_scheduler=prof.scheduler_name):
                skipped += 1
                continue
            applied += 1
            if kind == "pod-arrival":
                with seq_lock:
                    arrival_seq.setdefault(ev["pod"], len(arrival_seq))
                inject_ts[ev["pod"]] = clk.wall()
                note_pod(ev)
            if pace == "lockstep" and kind in _QUIESCE_KINDS:
                settle(settle_s, event_timeout_s)
            if util_sample_every > 0 and applied % util_sample_every == 0 \
                    and len(samples) < 200:
                samples.append(_sample(i, api, sched, pool_of, chips_of,
                                       fragmentation_curve, clk))
        feed_window = time.monotonic() - start

        # the recorded span is over: stop the shadow timeline re-arming
        # its tick deadline.  Left armed, the drain loop below could
        # never hit its "nothing armed -> genuinely unplaceable" exit,
        # and post-span tick counts would be bounded by WALL timeouts —
        # nondeterministic across two replays of the same trace (the
        # incident-plane determinism gate pins the sample census)
        sched._timeline.disarm()

        # drain: give in-flight gangs a bounded chance to finish binding.
        # Virtual time drains by firing armed gates forward (a gang held
        # by its denial window or backoff ladder needs the clock, not
        # wall patience); the fire budget bounds a fleet that retries
        # forever without ever binding.
        deadline = time.monotonic() + drain_timeout_s
        drain_fires = 0
        while time.monotonic() < deadline:
            with seq_lock:
                outstanding = [k for k in arrival_seq
                               if k not in bound
                               and api.peek(srv.PODS, k) is not None]
            if not outstanding:
                break
            stable = settle(settle_s * 4, 1.0)
            if stable and virtual:
                binds_before = len(bound)
                fired = clk.advance_to_next_deadline() \
                    if drain_fires < _MAX_DRAIN_FIRES else None
                if fired is None:
                    # nothing armed (or fire budget spent): no gate will
                    # ever release more work — genuinely unplaceable
                    break
                sched.run_timers_once()
                settle(settle_s, event_timeout_s)
                drain_fires = 0 if len(bound) > binds_before \
                    else drain_fires + 1
                continue
            if stable \
                    and not sched.queue.pending_counts().get("backoff", 0):
                # stable store, empty active/backoff queues, outstanding
                # pods: genuinely unplaceable without further events — stop
                break
            time.sleep(0.0 if serial else 0.01)
        samples.append(_sample(len(trace.events), api, sched, pool_of,
                               chips_of, fragmentation_curve, clk))
    finally:
        sched.stop()
    elapsed = time.monotonic() - start

    with seq_lock:
        placed = sorted(
            ((arrival_seq[k], k, node) for k, (node, _) in bound.items()
             if k in arrival_seq), key=lambda t: t[0])
        unbound = sorted(
            (k for k in arrival_seq
             if k not in bound and api.peek(srv.PODS, k) is not None),
            key=lambda k: arrival_seq[k])
        e2e = [bound[k][1] - inject_ts[k] for k in bound
               if k in inject_ts]
    p50, p99 = _percentiles(e2e)
    objective = getattr(prof, "slo_pod_e2e_s", 0.0) or 0.0
    attainment = (sum(1 for v in e2e if v <= objective) / len(e2e)
                  if e2e and objective else 1.0 if e2e else 0.0)
    qdelay = [first_attempt[k] - inject_ts[k] for k in first_attempt
              if k in inject_ts]
    qd50, qd99 = _percentiles(qdelay)
    retried = sorted((k for k, a in attempts_of.items() if a > 1),
                     key=lambda k: (arrival_seq.get(k, 1 << 30), k))
    recorded_span = trace.window_s()
    # "zeroed" keys on deterministic alone: the gate-zeroing overrides
    # apply to every non-virtual deterministic run (timed pace included),
    # and the label exists so nobody reads a zeroed-gate measurement as a
    # production-window one
    mode = "virtual" if virtual else \
        ("zeroed" if deterministic else "wall")
    vt = {
        "mode": mode,
        "recorded_span_s": round(recorded_span, 3),
        "replay_wall_s": round(elapsed, 3),
        "compression_ratio": round(recorded_span / elapsed, 2)
        if elapsed > 0 else 0.0,
    }
    if virtual:
        vt["virtual_span_s"] = round(clk.now() - anchor_mono, 3)
        vt["deadlines_fired"] = clk.fired_total()
        vt["fired_by_label"] = clk.fired_by_label()
    from ..obs.fleetrace import workload_fingerprint
    return ReplayReport(
        trace_dir=trace_dir,
        scheduler_name=prof.scheduler_name,
        pace=pace,
        deterministic=deterministic,
        dispatch_shards=sched.dispatch_shards,
        escalated_units=sched.shard_router().escalated_units(),
        escalations_truncated=sched.shard_router().escalated_truncated(),
        workload_fingerprint=workload_fingerprint(trace.events),
        events_applied=applied,
        events_skipped=skipped,
        placements=[[k, node] for _, k, node in placed],
        binds=len(placed),
        unbound=unbound,
        pod_e2e={"p50_s": round(p50, 4), "p99_s": round(p99, 4),
                 "events": len(e2e), "objective_s": objective,
                 "attainment": round(attainment, 4)},
        pool_utilization=samples,
        feed_window_s=round(feed_window, 3),
        elapsed_s=round(elapsed, 3),
        clock_mode=mode,
        virtual_time=vt,
        queueing_delay={"p50_s": round(qd50, 4), "p99_s": round(qd99, 4),
                        "events": len(qdelay)},
        retries={k: attempts_of[k] for k in retried[:_RETRIES_CAP]},
        retries_truncated=len(retried) > _RETRIES_CAP,
        slo=sched._slo.summary() if sched._slo is not None else {},
        timeline=sched._timeline.census(),
        incidents={"sentinel": sched._sentinel.census(),
                   "bundles": sched._incidents.census()})


def _pool_usage(api: APIServer, pool_of: Dict[str, str],
                chips_of: Dict[str, int]) -> Dict[str, int]:
    usage: Dict[str, int] = {}
    for pod in api.list(srv.PODS):
        if not pod.spec.node_name:
            continue
        pool = pool_of.get(pod.spec.node_name, "")
        usage[pool] = usage.get(pool, 0) + chips_of.get(pod.meta.key, 0)
    return {p: c for p, c in sorted(usage.items())}


def _sample(event_index: int, api: APIServer, sched: Scheduler,
            pool_of: Dict[str, str], chips_of: Dict[str, int],
            fragmentation: bool, clk) -> dict:
    """One utilization-trajectory sample: per-pool in-flight chip demand
    (the pre-existing curve), stamped with the replay-clock instant, plus
    — when topologies are present and ``fragmentation`` is on — the
    capacity collector's own arithmetic (obs/capacity: free / capacity /
    largest contiguous placeable window) so the evaluation plane can
    render a fragmentation trajectory without the live gauge pipeline
    (shadow schedulers register no collector by design)."""
    out = {"event": event_index,
           "t": round(clk.wall(), 3),
           "pools": _pool_usage(api, pool_of, chips_of)}
    if not fragmentation:
        return out
    try:
        from ..obs.capacity import largest_placeable_chips
        from ..topology.torus import HostGrid
        snapshot = sched.cache.shared_snapshot()
        frag: Dict[str, dict] = {}
        for topo in api.list(srv.TPU_TOPOLOGIES):
            grid = HostGrid.from_spec(topo.spec)
            if grid is None:
                continue
            largest, free, capacity = largest_placeable_chips(grid,
                                                              snapshot)
            frag[topo.spec.pool] = {
                "free": free, "capacity": capacity, "largest": largest,
                "fragmentation": round(1.0 - min(largest, free)
                                       / free, 4) if free else 0.0}
        if frag:
            out["frag"] = frag
    except Exception as e:  # noqa: BLE001 — trajectory samples are
        # advisory; a geometry/snapshot hiccup must not fail the replay
        klog.V(4).info_s("fragmentation sample failed", err=str(e))
    return out


def recorded_reality(trace: FleetTrace) -> dict:
    """The recorded run rendered in report shape, so ``diff_placements``
    can compare a replay against what the live fleet actually did.  The
    recorded pod-e2e is arrival-wall → bind-commit-wall per pod."""
    arrivals: Dict[str, float] = {}
    order: Dict[str, int] = {}
    binds: List[Tuple[str, str]] = []
    e2e: List[float] = []
    decisions = trace.bind_decisions()
    # mirror run_replay's sequence seeding: pods pending in the snapshot
    # precede every streamed arrival (their own arrivals were compacted
    # away), so both report shapes order and count the same pod set
    for pod in trace.objects.get(srv.PODS, ()):
        if not pod.spec.node_name:
            order.setdefault(pod.meta.key, len(order))
    for ev in trace.events:
        kind = ev.get("kind")
        if kind == "pod-arrival":
            order.setdefault(ev["pod"], len(order))
            arrivals[ev["pod"]] = ev.get("wall", 0.0)
        elif kind == "bind-commit":
            binds.append((ev["pod"], ev["node"]))
            if ev["pod"] in arrivals:
                e2e.append(max(0.0, ev.get("wall", 0.0)
                               - arrivals[ev["pod"]]))
    placed = sorted(((order.get(p, 1 << 30), p, n) for p, n in binds),
                    key=lambda t: t[0])
    bound_keys = {p for p, _ in binds}
    p50, p99 = _percentiles(e2e)
    return {
        "trace_dir": trace.directory,
        "scheduler_name": next(
            (d.get("scheduler", "") for d in decisions.values()), ""),
        "pace": "recorded",
        "placements": [[p, n] for _, p, n in placed],
        "binds": len(binds),
        "unbound": sorted(p for p in order if p not in bound_keys),
        "pod_e2e": {"p50_s": round(p50, 4), "p99_s": round(p99, 4),
                    "events": len(e2e)},
    }


def diff_placements(a: dict, b: dict, *,
                    gang_of: Optional[Dict[str, str]] = None) -> dict:
    """Differential placement report between two replay reports (or a
    report and ``recorded_reality``): per-pod node differences with
    attribution, pods placed in only one run, and bind-count deltas.
    ``identical`` is the replay-smoke gate's predicate."""
    pa = {p: n for p, n in a.get("placements", [])}
    pb = {p: n for p, n in b.get("placements", [])}
    moved = [{"pod": p, "a": pa[p], "b": pb[p],
              **({"gang": gang_of[p]} if gang_of and p in gang_of else {})}
             for p in sorted(set(pa) & set(pb)) if pa[p] != pb[p]]
    only_a = sorted(set(pa) - set(pb))
    only_b = sorted(set(pb) - set(pa))
    return {
        "identical": not moved and not only_a and not only_b
                     and a.get("binds") == b.get("binds"),
        "binds_a": a.get("binds", len(pa)),
        "binds_b": b.get("binds", len(pb)),
        "placement_diff": moved,
        "moved": len(moved),
        "only_in_a": only_a,
        "only_in_b": only_b,
        "pod_e2e_a": a.get("pod_e2e"),
        "pod_e2e_b": b.get("pod_e2e"),
    }
