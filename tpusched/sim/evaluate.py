"""The policy-evaluation plane over recorded traces (ISSUE 15).

``evaluate_arms`` replays the SAME recorded trace once per configuration
arm — each on virtual time, so a recorded day costs wall seconds and
every retry gate fires deterministically — and renders an attributed
two-arm (or N-arm) comparison of scheduling quality:

- **JCT** (pod arrival → bind, on replay time: p50/p99 + SLO attainment)
- **queueing delay** (arrival → first scheduling attempt)
- **SLO attainment / burn** from the shadow scheduler's own tracker
- **utilization + fragmentation trajectory** per pool (mean in-flight
  chip demand over capacity; mean and final 1 − largest/free)
- **goodput** — placements priced through the measured
  workload×generation throughput matrix (PR 10): a pod landing on a
  generation its workload runs faster on scores higher, which is exactly
  the "fits, but on the slow generation" signal a goodput-aware policy
  is supposed to move

plus the raw placement diff between arms.  This is the substrate ROADMAP
item 3's policy rounds, item 4's defrag controller and item 5's
autoscaler evaluate against; ``python -m tpusched.cmd.trace evaluate``
is the operator surface (exit-code contract: 0 comparable / 1 regression
vs budget / 2 usage).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ..obs.fleetrace import FleetTrace, load_trace
from ..obs.goodput import (GoodputMatrix, matrix_from_trace, pod_chips,
                           workload_fingerprint_of)
from .replay import diff_placements, run_replay

__all__ = ["ArmSpec", "evaluate_arms", "goodput_estimate",
           "compare_arms"]


@dataclasses.dataclass
class ArmSpec:
    """One configuration arm: a TpuSchedulerConfiguration YAML (None =
    the canned default profile), the profile to pick from it, and a
    display name."""
    name: str
    config_path: Optional[str] = None
    scheduler_name: Optional[str] = None


def _mean(xs: List[float]) -> float:
    return sum(xs) / len(xs) if xs else 0.0


def _node_generations(trace: FleetTrace) -> Dict[str, str]:
    """node name → accelerator generation label, from the snapshot plus
    streamed node events (same join matrix_from_trace performs)."""
    from ..api.topology import LABEL_ACCELERATOR
    from ..apiserver import server as srv
    from ..apiserver.persistence import KIND_CLASSES, decode_object
    gen: Dict[str, str] = {}
    for node in trace.objects.get(srv.NODES, ()):
        gen[node.meta.name] = node.meta.labels.get(LABEL_ACCELERATOR, "")
    for e in trace.events:
        if e.get("kind") in ("node-add", "node-update") \
                and e.get("object") is not None:
            node = decode_object(KIND_CLASSES[srv.NODES], e["object"])
            gen[node.meta.name] = node.meta.labels.get(LABEL_ACCELERATOR,
                                                       "")
    return gen


def _trace_pods(trace: FleetTrace) -> Dict[str, Any]:
    """pod key → decoded Pod (snapshot + arrivals) and its PodGroup."""
    from ..api.scheduling import pod_group_full_name
    from ..apiserver import server as srv
    from ..apiserver.persistence import KIND_CLASSES, decode_object
    pods: Dict[str, Any] = {p.meta.key: p
                            for p in trace.objects.get(srv.PODS, ())}
    groups: Dict[str, Any] = {g.meta.key: g
                              for g in trace.objects.get(srv.POD_GROUPS,
                                                         ())}
    for e in trace.events:
        if e.get("kind") == "pod-arrival" and e.get("object") is not None:
            p = decode_object(KIND_CLASSES[srv.PODS], e["object"])
            pods[p.meta.key] = p
        elif e.get("kind") in ("podgroup-add", "podgroup-update") \
                and e.get("object") is not None:
            g = decode_object(KIND_CLASSES[srv.POD_GROUPS], e["object"])
            groups[g.meta.key] = g
    out: Dict[str, Any] = {}
    for key, pod in pods.items():
        pg = groups.get(pod_group_full_name(pod) or "")
        out[key] = (pod, pg)
    return out


def goodput_estimate(trace: FleetTrace, placements: List[List[str]],
                     matrix: Optional[GoodputMatrix] = None,
                     generations: Optional[Dict[str, str]] = None,
                     pods: Optional[Dict[str, Any]] = None) -> dict:
    """Price a placement sequence through the measured matrix: for each
    (pod, node), chips × measured goodput-per-chip of (pod's workload
    fingerprint, node's generation).  Pods whose cell was never measured
    are counted (``unpriced``) but contribute nothing — an estimate must
    not invent throughput for hardware nobody measured.  Returns zeros
    (``cells: 0``) when the trace carries no goodput reports at all.

    ``generations``/``pods``: the arm-invariant trace joins — pass them
    (``evaluate_arms`` does) so an N-arm evaluation decodes the event
    stream once, not once per arm."""
    if matrix is None:
        matrix = matrix_from_trace(trace)
    if matrix.size() == 0:
        return {"cells": 0, "total_units_per_s": 0.0, "priced_pods": 0,
                "unpriced_pods": len(placements)}
    if generations is None:
        generations = _node_generations(trace)
    if pods is None:
        pods = _trace_pods(trace)
    total = 0.0
    priced = unpriced = 0
    for pod_key, node in placements:
        entry = pods.get(pod_key)
        if entry is None:
            unpriced += 1
            continue
        pod, pg = entry
        per_chip = matrix.peek(workload_fingerprint_of(pod, pg) or
                               "unlabeled", generations.get(node, ""))
        chips = pod_chips(pod)
        if per_chip is None or chips <= 0:
            unpriced += 1
            continue
        total += per_chip * chips
        priced += 1
    return {"cells": matrix.size(),
            "total_units_per_s": round(total, 4),
            "priced_pods": priced, "unpriced_pods": unpriced}


def _utilization_summary(report: dict) -> dict:
    """Mean fleet utilization + fragmentation trajectory digest from the
    replay's pool samples (each sample: in-flight chips per pool, and —
    when topologies exist — the free/capacity/largest triple)."""
    samples = report.get("pool_utilization") or []
    util: List[float] = []
    frag_means: List[float] = []
    final_frag: Dict[str, float] = {}
    for s in samples:
        frag = s.get("frag") or {}
        cap = sum(f.get("capacity", 0) for f in frag.values())
        if cap > 0:
            # numerator restricted to the pools the denominator covers:
            # on a mixed fleet (some pools without a TpuTopology CR)
            # counting topology-less in-flight chips against
            # topology-only capacity would invent utilization
            used = sum(c for p, c in (s.get("pools") or {}).items()
                       if p in frag)
            util.append(min(1.0, used / cap))
        per_pool = [f.get("fragmentation", 0.0) for f in frag.values()]
        if per_pool:
            frag_means.append(_mean(per_pool))
            final_frag = {p: f.get("fragmentation", 0.0)
                          for p, f in frag.items()}
    return {"samples": len(samples),
            "mean_utilization": round(_mean(util), 4) if util else None,
            "mean_fragmentation": round(_mean(frag_means), 4)
            if frag_means else None,
            "final_fragmentation": final_frag or None}


def summarize_arm(trace: FleetTrace, report: dict,
                  matrix: Optional[GoodputMatrix] = None,
                  generations: Optional[Dict[str, str]] = None,
                  pods: Optional[Dict[str, Any]] = None) -> dict:
    """One arm's scheduling-quality digest from its replay report."""
    slo = report.get("slo") or {}
    incidents = report.get("incidents") or {}
    sentinel_census = incidents.get("sentinel") or {}
    return {
        "binds": report.get("binds", 0),
        "unbound": len(report.get("unbound", ())),
        "jct": report.get("pod_e2e"),
        "queueing_delay": report.get("queueing_delay"),
        "slo": slo,
        "retried_pods": len(report.get("retries", {})),
        "utilization": _utilization_summary(report),
        "goodput": goodput_estimate(trace,
                                    report.get("placements", []),
                                    matrix=matrix,
                                    generations=generations, pods=pods),
        "virtual_time": report.get("virtual_time"),
        # the incident plane in virtual time (ISSUE 20): the shadow
        # sentinel's per-detector firing census + the shadow bundle ring.
        # A policy that wedges gangs does not just lose JCT points — it
        # FAILS its evaluation, with the bundle census attached.
        "timeline": report.get("timeline") or {},
        "incidents": incidents,
        "incidents_fired": sum(sentinel_census.values()),
    }


def _pct_delta(a: Optional[float], b: Optional[float]) -> Optional[float]:
    """(b - a) / a as a percentage; None when undefined."""
    if a is None or b is None or a == 0:
        return None
    return round(100.0 * (b - a) / a, 2)


def compare_arms(base: dict, cand: dict, placement_diff: dict) -> dict:
    """The attributed two-arm comparison: per-metric deltas (positive =
    candidate larger) plus the raw placement divergence."""
    b_jct, c_jct = base.get("jct") or {}, cand.get("jct") or {}
    b_qd, c_qd = (base.get("queueing_delay") or {},
                  cand.get("queueing_delay") or {})
    b_slo = (base.get("slo") or {}).get("pod_e2e") or {}
    c_slo = (cand.get("slo") or {}).get("pod_e2e") or {}
    b_gp, c_gp = base.get("goodput") or {}, cand.get("goodput") or {}
    return {
        "jct_p50_pct": _pct_delta(b_jct.get("p50_s"), c_jct.get("p50_s")),
        "jct_p99_pct": _pct_delta(b_jct.get("p99_s"), c_jct.get("p99_s")),
        "queueing_p50_pct": _pct_delta(b_qd.get("p50_s"),
                                       c_qd.get("p50_s")),
        "queueing_p99_pct": _pct_delta(b_qd.get("p99_s"),
                                       c_qd.get("p99_s")),
        "attainment_delta": round(
            (c_jct.get("attainment") or 0.0)
            - (b_jct.get("attainment") or 0.0), 4),
        "slo_attainment_delta": round(
            (c_slo.get("attainment") or 0.0)
            - (b_slo.get("attainment") or 0.0), 4)
        if b_slo or c_slo else None,
        "binds_delta": cand.get("binds", 0) - base.get("binds", 0),
        "unbound_delta": cand.get("unbound", 0) - base.get("unbound", 0),
        "goodput_pct": _pct_delta(b_gp.get("total_units_per_s"),
                                  c_gp.get("total_units_per_s")),
        "mean_utilization_delta": round(
            (cand["utilization"].get("mean_utilization") or 0.0)
            - (base["utilization"].get("mean_utilization") or 0.0), 4),
        "mean_fragmentation_delta": round(
            (cand["utilization"].get("mean_fragmentation") or 0.0)
            - (base["utilization"].get("mean_fragmentation") or 0.0), 4),
        "placements_moved": placement_diff.get("moved", 0),
        "only_in_base": len(placement_diff.get("only_in_a", ())),
        "only_in_candidate": len(placement_diff.get("only_in_b", ())),
        "identical_placements": placement_diff.get("identical", False),
        "incidents_fired_delta": (cand.get("incidents_fired", 0)
                                  - base.get("incidents_fired", 0)),
    }


def evaluate_arms(trace_dir: str, arms: List[ArmSpec], *,
                  trace: Optional[FleetTrace] = None,
                  legacy_zeroed_gates: bool = False,
                  event_timeout_s: float = 15.0,
                  drain_timeout_s: float = 120.0) -> dict:
    """Replay every arm over the same trace (virtual time) and compare
    each later arm against the FIRST (the base).  Returns the full
    evaluation document ``cmd.trace evaluate`` renders."""
    if trace is None:
        trace = load_trace(trace_dir)
    # the arm-invariant trace joins, computed once for all arms: the
    # matrix, the node→generation map and the pod/PodGroup decode
    matrix = matrix_from_trace(trace)
    generations = _node_generations(trace) if matrix.size() else {}
    pods = _trace_pods(trace) if matrix.size() else {}
    arm_docs: List[dict] = []
    reports: List[dict] = []
    for arm in arms:
        report = run_replay(
            trace_dir, trace=trace, config_path=arm.config_path,
            scheduler_name=arm.scheduler_name,
            legacy_zeroed_gates=legacy_zeroed_gates,
            event_timeout_s=event_timeout_s,
            drain_timeout_s=drain_timeout_s).to_dict()
        reports.append(report)
        arm_docs.append({"name": arm.name,
                         "config": arm.config_path,
                         "scheduler_name": report.get("scheduler_name"),
                         "summary": summarize_arm(
                             trace, report, matrix=matrix,
                             generations=generations, pods=pods)})
    comparisons = []
    for i in range(1, len(arm_docs)):
        diff = diff_placements(reports[0], reports[i])
        comparisons.append({
            "base": arm_docs[0]["name"],
            "candidate": arm_docs[i]["name"],
            "deltas": compare_arms(arm_docs[0]["summary"],
                                   arm_docs[i]["summary"], diff),
        })
    # The closed incident loop in virtual time: an arm whose replay fired
    # the anomaly sentinel is a wedge failure — the policy produced a
    # fleet state bad enough that the live plane would have cut a black
    # box.  Attach the detector census so the verdict names the failure
    # mode, not just a JCT delta.
    incident_failures = []
    for doc in arm_docs:
        census = (doc["summary"].get("incidents") or {})
        fired = doc["summary"].get("incidents_fired", 0)
        if fired:
            incident_failures.append({
                "arm": doc["name"],
                "firings": fired,
                "detectors": census.get("sentinel") or {},
                "bundles": census.get("bundles") or {},
            })
    return {
        "trace": trace_dir,
        "workload_fingerprint": reports[0].get("workload_fingerprint")
        if reports else "",
        "recorded_span_s": round(trace.window_s(), 3),
        "matrix_cells": matrix.size(),
        "arms": arm_docs,
        "comparisons": comparisons,
        "incident_failures": incident_failures,
    }
