"""Defragmentation advisor: which gang migration(s) would admit a blocked job.

A torus fleet fragments: enough free chips exist in total, but no
CONTIGUOUS window fits the next slice gang, and quota/priority rules make
preemption unavailable (the victims are entitled to their capacity). The
operator's question becomes: *which running gang(s) should I migrate
(delete and resubmit) so the blocked job fits — without losing the
migrated gangs?*

The reference world has no answer short of trial-and-error on production.
Here the advisor reuses the shadow machinery (KEP-302): fork a fresh
shadow, remove the candidate gang(s), schedule the TARGET job first, then
resubmit the candidates (largest footprint first — the safest re-packing
order). A suggestion is only returned when EVERYONE lands — a migration
that admits the target by orphaning a migrated gang is not a plan, it's an
outage. Every placement decision is the real scheduler's.

Search is cheapest-first and bounded: all single moves (smallest chip
footprint first), then — when ``max_moves >= 2`` — pairs ordered by
combined footprint, capped at ``max_pair_trials`` shadow runs (a fleet
fragmented enough to need 2-step plans has O(gangs²) pairs; the cap keeps
the advisor interactive).

This is deliberately an ADVISOR, not an actuator: it prints the plan (who
moves, where everyone ends up); executing the migration stays a human/
higher-level-controller decision, exactly like the reference ecosystem
splits descheduling from scheduling.
"""
from __future__ import annotations

import dataclasses
import itertools
import time as _time
from typing import Dict, List, Optional, Tuple

from ..api.scheduling import POD_GROUP_LABEL
from ..apiserver import APIServer
from ..apiserver import server as srv
from ..plugins import default_registry
from ..plugins.topologymatch import COORD_ANNOTATION, POOL_ANNOTATION
from ..plugins.tpuslice import CHIP_INDEX_ANNOTATION
from ..sched import Scheduler
from ..api.core import Pod
from .whatif import (WhatIfReport, _make_profile, _run_one,
                     _set_gang_names, _shadow_of)

# sentinel for peek() misses in the post-resubmission check: a vanished
# target pod must read as "not bound"
_GONE = Pod()


@dataclasses.dataclass
class MigrationMove:
    """One migrated gang within a plan and where it re-lands."""
    gang: str                           # gang full name
    chips: int                          # its chip footprint (migration cost)
    resubmitted: WhatIfReport           # where it re-lands

    def to_dict(self) -> dict:
        return {"gang": self.gang, "chips": self.chips,
                "resubmitted": self.resubmitted.to_dict()}


@dataclasses.dataclass
class MigrationSuggestion:
    """One workable plan: migrate every gang in ``moves`` (in order) and the
    target fits. Single-move plans keep the legacy accessors
    (``migrate``/``migrate_chips``/``resubmitted``)."""
    moves: List[MigrationMove]
    target: WhatIfReport                # where the target job lands

    @property
    def migrate(self) -> str:
        return "+".join(m.gang for m in self.moves)

    @property
    def migrate_chips(self) -> int:
        return sum(m.chips for m in self.moves)

    @property
    def resubmitted(self) -> WhatIfReport:
        if len(self.moves) != 1:
            # silently returning one gang's report would hand a runbook
            # half a plan; multi-move callers must read .moves
            raise ValueError(
                f"plan migrates {len(self.moves)} gangs; read .moves")
        return self.moves[0].resubmitted

    def to_dict(self) -> dict:
        out = {"migrate": self.migrate,
               "migrate_chips": self.migrate_chips,
               "target": self.target.to_dict(),
               "moves": [m.to_dict() for m in self.moves]}
        if len(self.moves) == 1:   # legacy single-move shape, kept stable
            out["resubmitted"] = self.moves[0].resubmitted.to_dict()
        return out


def sanitize_for_resubmit(p: Pod) -> Pod:
    """An unbound copy of a (possibly bound) pod, stripped of every
    placement artifact — THE one definition, shared by the advisor's
    shadow resubmission and the defrag controller's plan trial and
    actuation, so shadow verification can never diverge from what
    actuation actually submits."""
    q = p.deepcopy()
    q.meta.resource_version = 0
    q.meta.creation_timestamp = 0   # re-stamped on create: a migrant must
    #                                 not inherit its old age (it would
    #                                 instantly read as "long-blocked")
    q.spec.node_name = ""
    q.meta.annotations.pop(COORD_ANNOTATION, None)
    q.meta.annotations.pop(POOL_ANNOTATION, None)
    q.meta.annotations.pop(CHIP_INDEX_ANNOTATION, None)
    q.status.conditions = []
    return q


def _resident_units(api: APIServer) -> List[Tuple[Tuple[str, int, int], ...]]:
    """Migration UNITS over the resident gangs, smallest combined footprint
    first: a plain gang is a unit of one; an ATOMIC multislice set
    (multislice_set_size > 1) is one unit containing every member gang —
    suggesting half a set would be suggesting an outage (the surviving
    slices strand; disruption must be all-or-nothing like admission). A
    set whose members are not all fully bound yields no unit."""
    resident = {full: (members, chips)
                for full, members, chips in _resident_gangs(api)}
    units: Dict[Tuple[str, ...], Tuple[Tuple[str, int, int], ...]] = {}
    for full, (members, chips) in resident.items():
        ns = full.split("/", 1)[0]
        pg = api.try_get(srv.POD_GROUPS, full)
        if pg is not None and pg.spec.multislice_set                 and pg.spec.multislice_set_size > 1:
            names = tuple(sorted(
                g.key for g in api.list(srv.POD_GROUPS, ns)
                if g.spec.multislice_set == pg.spec.multislice_set))
            if any(m not in resident for m in names):
                continue
            units[names] = tuple((m, *resident[m]) for m in names)
        else:
            units[(full,)] = ((full, members, chips),)
    return sorted(units.values(),
                  key=lambda u: (sum(g[2] for g in u), u[0][0]))


def _resident_gangs(api: APIServer) -> List[Tuple[str, int, int]]:
    """(full name, member count, chip footprint) of every FULLY-bound gang,
    smallest footprint first. Partially-bound gangs (members still pending)
    are excluded: they are in flux, and a migration-cost number that counts
    only the bound half would mis-rank candidates while the plan would
    actually move every member."""
    from ..plugins.tpuslice.chip_node import pod_tpu_limits
    members: Dict[str, int] = {}
    bound: Dict[str, int] = {}
    chips: Dict[str, int] = {}
    for p in api.list(srv.PODS):
        name = p.meta.labels.get(POD_GROUP_LABEL)
        if not name:
            continue
        full = f"{p.meta.namespace}/{name}"
        members[full] = members.get(full, 0) + 1
        c, _, _, _ = pod_tpu_limits(p)
        chips[full] = chips.get(full, 0) + c
        if p.spec.node_name:
            bound[full] = bound.get(full, 0) + 1
    out = [(full, members[full], chips[full]) for full in members
           if bound.get(full, 0) == members[full]]
    out.sort(key=lambda t: (t[2], t[0]))
    return out


def _try_moves(base: APIServer, profile, moves: List[Tuple[str, int, int]],
               job_kw: dict, timeout_s: float
               ) -> Optional[Tuple[WhatIfReport, List[MigrationMove]]]:
    """One shadow trial: remove every gang in ``moves``, schedule the
    target, resubmit the gangs largest-footprint-first. Returns the plan's
    reports, or None when anyone ends up homeless or a third party pays."""
    fork = _shadow_of(base, None)
    captured = []   # (full, chips, moved_pg, moved_pods)
    for full, _, n_chips in moves:
        ns, gname = full.split("/", 1)
        moved_pods = [p for p in fork.list(srv.PODS, ns)
                      if p.meta.labels.get(POD_GROUP_LABEL) == gname]
        moved_pg = fork.try_get(srv.POD_GROUPS, full)
        for p in moved_pods:
            fork.delete(srv.PODS, p.meta.key)
        if moved_pg is not None:
            fork.delete(srv.POD_GROUPS, full)
        captured.append((full, n_chips, moved_pg, moved_pods))
    # big gangs are the hardest to re-home: place them first
    captured.sort(key=lambda t: (-t[1], t[0]))

    sched = Scheduler(fork, default_registry(), profile, telemetry=False)
    sched.run()
    try:
        pre_resident = {p.meta.key for p in fork.list(srv.PODS)}
        target, target_keys = _run_one(
            fork, timeout_s=timeout_s,
            scheduler_name=profile.scheduler_name, **job_kw)
        if not target.feasible:
            return None
        # resubmit EVERY migrated gang (largest-footprint creation order
        # biases packing), then wait for all of them together: member
        # gangs of an atomic multislice set barrier on EACH OTHER — a
        # per-gang wait would deadlock on the first slice waiting for a
        # sibling the loop had not resubmitted yet
        keys_by_gang: List[Tuple[str, int, List[str]]] = []
        for full, n_chips, moved_pg, moved_pods in captured:
            if moved_pg is not None:
                moved_pg.meta.resource_version = 0
                fork.create(srv.POD_GROUPS, moved_pg)
            keys = []
            for p in moved_pods:
                q = sanitize_for_resubmit(p)
                fork.create(srv.PODS, q)
                keys.append(q.meta.key)
            keys_by_gang.append((full, n_chips, keys))
        all_keys = [k for _, _, ks in keys_by_gang for k in ks]
        deadline = _time.monotonic() + timeout_s
        ok = False
        while _time.monotonic() < deadline:
            live = [fork.peek(srv.PODS, k) for k in all_keys]
            if all(x is not None and x.spec.node_name for x in live):
                ok = True
                break
            _time.sleep(0.02)
        if not ok:
            return None   # target fits but a migrated gang is homeless
        plan_moves: List[MigrationMove] = []
        for full, n_chips, keys in keys_by_gang:
            placements = {}
            coords = {}
            pool = ""
            for k in keys:
                p = fork.peek(srv.PODS, k)
                placements[k] = p.spec.node_name
                coords[k] = p.meta.annotations.get(COORD_ANNOTATION, "")
                pool = p.meta.annotations.get(POOL_ANNOTATION, pool)
            plan_moves.append(MigrationMove(
                gang=full, chips=n_chips,
                resubmitted=WhatIfReport(
                    feasible=True, placements=placements, pool=pool,
                    coords=coords, victims=[], elapsed_s=0.0, reason="")))
        # the resubmissions must not have undone the plan: with an evicting
        # profile they could have preempted the target's own pods or
        # uninvolved residents to bind — either invalidates the "everyone
        # lands, nobody else pays" contract
        target_still = all(
            (fork.peek(srv.PODS, k) or _GONE).spec.node_name
            for k in target_keys)
        after = {p.meta.key for p in fork.list(srv.PODS)}
        if not target_still or (pre_resident - after):
            return None
        return target, plan_moves
    finally:
        sched.stop()


def _unit_could_open_window(index, api: APIServer, unit,
                            job_kw: dict) -> bool:
    """Window-index pre-gate (ISSUE 13): a migration unit whose vacated
    hosts PLUS the fleet's currently-free hosts still contain no window
    for the target's slice shape in ANY pool cannot possibly admit the
    target — skip its shadow trial.  Strictly advisory and conservative:
    any doubt (no index, multislice target, a pool the index cannot
    answer for) keeps the trial.  The index reflects the LIVE fleet; the
    advisor's fork is taken from the same state, and every surviving
    candidate is still verified by the full shadow trial."""
    from ..api.topology import parse_shape
    if index is None or job_kw.get("slices", 1) != 1:
        return True
    shape_s = job_kw.get("slice_shape")
    if not shape_s:
        return True
    try:
        shape = parse_shape(shape_s)
    except ValueError:
        return True
    want_acc = job_kw.get("accelerator") or ""
    vacated = set()
    for full, _, _ in unit:
        ns, gname = full.split("/", 1)
        for p in api.list(srv.PODS, ns):
            if (p.meta.labels.get(POD_GROUP_LABEL) == gname
                    and p.spec.node_name):
                vacated.add(p.spec.node_name)
    saw_pool = False
    for topo in api.list(srv.TPU_TOPOLOGIES):
        if want_acc and topo.spec.accelerator != want_acc:
            continue
        saw_pool = True
        verdict = index.window_exists_with(topo, shape, vacated)
        if verdict is None or verdict:
            return True
    return not saw_pool


def suggest_migrations(source_api: Optional[APIServer] = None,
                       state_dir: Optional[str] = None, *,
                       job: dict,
                       max_suggestions: int = 1,
                       max_moves: int = 1,
                       max_pair_trials: int = 24,
                       candidates: Optional[List[str]] = None,
                       timeout_s: float = 20.0,
                       config_path: Optional[str] = None,
                       scheduler_name: Optional[str] = None,
                       window_index=None
                       ) -> List[MigrationSuggestion]:
    """Migration plans that admit ``job`` (simulate_gang gang kwargs;
    ``members`` required). Candidates default to every fully-bound gang,
    tried smallest-chip-footprint first; pass ``candidates`` (gang full
    names) to restrict — e.g. to gangs a team is willing to move.

    Candidates are migration UNITS: a plain gang, or an ATOMIC multislice
    set as one unit (half a set is never suggested — the survivors would
    strand). ``max_moves=1`` (default) searches single units only;
    ``max_moves=2`` falls through to a bounded pair-of-units search
    (combined footprint ascending, at most ``max_pair_trials`` shadow
    runs) when the quota of single-unit plans isn't met — the fleet
    regime where no one migration opens a window but two do.

    ``window_index``: the live scheduler's torus window index (ISSUE 13),
    when available — units whose vacated hosts provably cannot open a
    window for the target's slice shape skip their shadow trial entirely
    (the pre-gate is mask math over maintained planes; every surviving
    candidate still runs the full verified trial).

    Returns up to ``max_suggestions`` plans, cheapest-first; empty list =
    no plan within the search bounds (the job needs more moves, preemption,
    or more capacity)."""
    if not isinstance(job, dict) or not isinstance(job.get("members"), int):
        raise ValueError("job must be a dict with integer 'members'")
    if max_moves not in (1, 2):
        raise ValueError("max_moves must be 1 or 2")
    base = _shadow_of(source_api, state_dir)
    profile = _make_profile(False, timeout_s, config_path, scheduler_name)
    units = _resident_units(base)
    if candidates is not None:
        want = set(candidates)
        known = {full for full, _, _ in _resident_gangs(base)}
        unknown = want - known
        if unknown:
            raise ValueError(f"unknown candidate gangs: {sorted(unknown)}")
        # a unit is eligible only when EVERY member gang was named: naming
        # one slice of an atomic set does not consent the whole set
        units = [u for u in units if all(g[0] in want for g in u)]

    job_kw = dict(name="defrag-target", namespace="default", slice_shape="",
                  accelerator="", chips_per_pod=1, cpu_per_pod=4,
                  memory_per_pod="8Gi", priority=0, slices=1)
    job_kw.update(job)
    # collision checks over the DERIVED gang names (a slices>1 target
    # creates name-s0..; checking only the base name would let the shadow
    # die on an apiserver Conflict mid-search)
    for gname in _set_gang_names(job_kw["name"], job_kw["slices"]):
        gfull = f"{job_kw['namespace']}/{gname}"
        if base.try_get(srv.POD_GROUPS, gfull) is not None:
            raise ValueError(f"target name {gfull!r} collides with an "
                             "existing PodGroup; pass job['name']")
        for j in range(job_kw["members"]):
            pk = f"{gfull}-{j:03d}"
            if base.peek(srv.PODS, pk) is not None:
                raise ValueError(f"target pod key {pk!r} collides with an "
                                 "existing pod; pass job['name']")

    suggestions: List[MigrationSuggestion] = []
    for unit in units:
        if len(suggestions) >= max_suggestions:
            return suggestions
        if not _unit_could_open_window(window_index, base, unit, job_kw):
            continue   # provably hopeless: skip the shadow trial
        result = _try_moves(base, profile, list(unit), job_kw, timeout_s)
        if result is not None:
            suggestions.append(MigrationSuggestion(moves=result[1],
                                                   target=result[0]))
    if max_moves < 2:
        return suggestions
    pairs = sorted(
        itertools.combinations(units, 2),
        key=lambda pr: (sum(g[2] for g in pr[0]) + sum(g[2] for g in pr[1]),
                        pr[0][0][0], pr[1][0][0]))
    trials = 0
    for pair in pairs:
        if len(suggestions) >= max_suggestions or trials >= max_pair_trials:
            break
        if not _unit_could_open_window(window_index, base,
                                       list(pair[0]) + list(pair[1]),
                                       job_kw):
            continue   # gate does not burn the bounded trial budget
        trials += 1
        result = _try_moves(base, profile, list(pair[0]) + list(pair[1]),
                            job_kw, timeout_s)
        if result is not None:
            suggestions.append(MigrationSuggestion(moves=result[1],
                                                   target=result[0]))
    return suggestions
