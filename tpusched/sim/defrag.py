"""Defragmentation advisor: which gang migration would admit a blocked job.

A torus fleet fragments: enough free chips exist in total, but no
CONTIGUOUS window fits the next slice gang, and quota/priority rules make
preemption unavailable (the victims are entitled to their capacity). The
operator's question becomes: *which running gang should I migrate (delete
and resubmit) so the blocked job fits — without losing the migrated gang?*

The reference world has no answer short of trial-and-error on production.
Here the advisor reuses the shadow machinery (KEP-302): for each candidate
resident gang (smallest chip footprint first — cheapest migration first),
fork a fresh shadow, remove the candidate, schedule the TARGET job first,
then resubmit the candidate. A suggestion is only returned when BOTH land —
a migration that admits the target by orphaning the migrated gang is not a
plan, it's an outage. Every placement decision is the real scheduler's.

This is deliberately an ADVISOR, not an actuator: it prints the plan (who
moves, where everyone ends up); executing the migration stays a human/
higher-level-controller decision, exactly like the reference ecosystem
splits descheduling from scheduling.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..api.scheduling import POD_GROUP_LABEL
from ..apiserver import APIServer
from ..apiserver import server as srv
from ..plugins import default_registry
from ..plugins.topologymatch import COORD_ANNOTATION, POOL_ANNOTATION
from ..plugins.tpuslice import CHIP_INDEX_ANNOTATION
from ..sched import Scheduler
from ..api.core import Pod
from .whatif import WhatIfReport, _make_profile, _run_one, _shadow_of

# sentinel for peek() misses in the post-resubmission check: a vanished
# target pod must read as "not bound"
_GONE = Pod()


@dataclasses.dataclass
class MigrationSuggestion:
    """One workable plan: migrate ``migrate`` and the target fits."""
    migrate: str                        # gang full name to migrate
    migrate_chips: int                  # its chip footprint (migration cost)
    target: WhatIfReport                # where the target job lands
    resubmitted: WhatIfReport           # where the migrated gang re-lands

    def to_dict(self) -> dict:
        return {"migrate": self.migrate,
                "migrate_chips": self.migrate_chips,
                "target": self.target.to_dict(),
                "resubmitted": self.resubmitted.to_dict()}


def _resident_gangs(api: APIServer) -> List[Tuple[str, int, int]]:
    """(full name, member count, chip footprint) of every FULLY-bound gang,
    smallest footprint first. Partially-bound gangs (members still pending)
    are excluded: they are in flux, and a migration-cost number that counts
    only the bound half would mis-rank candidates while the plan would
    actually move every member."""
    from ..plugins.tpuslice.chip_node import pod_tpu_limits
    members: Dict[str, int] = {}
    bound: Dict[str, int] = {}
    chips: Dict[str, int] = {}
    for p in api.list(srv.PODS):
        name = p.meta.labels.get(POD_GROUP_LABEL)
        if not name:
            continue
        full = f"{p.meta.namespace}/{name}"
        members[full] = members.get(full, 0) + 1
        c, _, _, _ = pod_tpu_limits(p)
        chips[full] = chips.get(full, 0) + c
        if p.spec.node_name:
            bound[full] = bound.get(full, 0) + 1
    out = [(full, members[full], chips[full]) for full in members
           if bound.get(full, 0) == members[full]]
    out.sort(key=lambda t: (t[2], t[0]))
    return out


def suggest_migrations(source_api: Optional[APIServer] = None,
                       state_dir: Optional[str] = None, *,
                       job: dict,
                       max_suggestions: int = 1,
                       candidates: Optional[List[str]] = None,
                       timeout_s: float = 20.0,
                       config_path: Optional[str] = None,
                       scheduler_name: Optional[str] = None
                       ) -> List[MigrationSuggestion]:
    """Single-move migration plans that admit ``job`` (simulate_gang gang
    kwargs; ``members`` required). Candidates default to every fully-bound
    gang, tried smallest-chip-footprint first; pass ``candidates`` (gang
    full names) to restrict — e.g. to gangs a team is willing to move.
    Returns up to ``max_suggestions`` plans; empty list = no single
    migration helps (the job needs >1 move, preemption, or more capacity).
    """
    if not isinstance(job, dict) or not isinstance(job.get("members"), int):
        raise ValueError("job must be a dict with integer 'members'")
    base = _shadow_of(source_api, state_dir)
    profile = _make_profile(False, timeout_s, config_path, scheduler_name)
    gangs = _resident_gangs(base)
    if candidates is not None:
        want = set(candidates)
        unknown = want - {full for full, _, _ in gangs}
        if unknown:
            raise ValueError(f"unknown candidate gangs: {sorted(unknown)}")
        gangs = [g for g in gangs if g[0] in want]

    job_kw = dict(name="defrag-target", namespace="default", slice_shape="",
                  accelerator="", chips_per_pod=1, cpu_per_pod=4,
                  memory_per_pod="8Gi", priority=0)
    job_kw.update(job)
    target_full = f"{job_kw['namespace']}/{job_kw['name']}"
    if base.try_get(srv.POD_GROUPS, target_full) is not None:
        raise ValueError(f"target name {target_full!r} collides with an "
                         "existing PodGroup; pass job['name']")
    for j in range(job_kw["members"]):
        pk = f"{job_kw['namespace']}/{job_kw['name']}-{j:03d}"
        if base.peek(srv.PODS, pk) is not None:
            raise ValueError(f"target pod key {pk!r} collides with an "
                             "existing pod; pass job['name']")

    suggestions: List[MigrationSuggestion] = []
    for full, n_members, n_chips in gangs:
        if len(suggestions) >= max_suggestions:
            break
        ns, gname = full.split("/", 1)
        fork = _shadow_of(base, None)
        # capture the candidate's pods (for resubmission), then remove them
        moved_pods = [p for p in fork.list(srv.PODS, ns)
                      if p.meta.labels.get(POD_GROUP_LABEL) == gname]
        moved_pg = fork.try_get(srv.POD_GROUPS, full)
        for p in moved_pods:
            fork.delete(srv.PODS, p.meta.key)
        if moved_pg is not None:
            fork.delete(srv.POD_GROUPS, full)

        sched = Scheduler(fork, default_registry(), profile)
        sched.run()
        try:
            pre_resident = {p.meta.key for p in fork.list(srv.PODS)}
            target, target_keys = _run_one(
                fork, timeout_s=timeout_s,
                scheduler_name=profile.scheduler_name, **job_kw)
            if not target.feasible:
                continue
            # resubmit the migrated gang: its PodGroup, then unbound copies
            # of its pods — the real scheduler re-places it
            if moved_pg is not None:
                moved_pg.meta.resource_version = 0
                fork.create(srv.POD_GROUPS, moved_pg)
            keys = []
            for p in moved_pods:
                q = p.deepcopy()
                q.meta.resource_version = 0
                q.spec.node_name = ""
                q.meta.annotations.pop(COORD_ANNOTATION, None)
                q.meta.annotations.pop(POOL_ANNOTATION, None)
                q.meta.annotations.pop(CHIP_INDEX_ANNOTATION, None)
                q.status.conditions = []
                fork.create(srv.PODS, q)
                keys.append(q.meta.key)
            import time as _time
            deadline = _time.monotonic() + timeout_s
            ok = False
            while _time.monotonic() < deadline:
                live = [fork.peek(srv.PODS, k) for k in keys]
                if all(x is not None and x.spec.node_name for x in live):
                    ok = True
                    break
                _time.sleep(0.02)
            if not ok:
                continue   # target fits but the migrated gang is homeless
            # the resubmission must not have undone the plan: with an
            # evicting profile it could have preempted the target's own
            # pods or uninvolved residents to bind — either invalidates
            # the "everyone lands, nobody else pays" contract
            target_still = all(
                (fork.peek(srv.PODS, k) or _GONE).spec.node_name
                for k in target_keys)
            after = {p.meta.key for p in fork.list(srv.PODS)}
            third_party_evicted = (pre_resident - after)
            if not target_still or third_party_evicted:
                continue
            placements = {}
            coords = {}
            pool = ""
            for k in keys:
                p = fork.peek(srv.PODS, k)
                placements[k] = p.spec.node_name
                coords[k] = p.meta.annotations.get(COORD_ANNOTATION, "")
                pool = p.meta.annotations.get(POOL_ANNOTATION, pool)
            resub = WhatIfReport(feasible=True, placements=placements,
                                 pool=pool, coords=coords, victims=[],
                                 elapsed_s=0.0, reason="")
            suggestions.append(MigrationSuggestion(
                migrate=full, migrate_chips=n_chips, target=target,
                resubmitted=resub))
        finally:
            sched.stop()
    return suggestions
