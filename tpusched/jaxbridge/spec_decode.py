"""Speculative decoding: a small draft model proposes, the target verifies.

Single-token decode is HBM-bandwidth-bound (measure.decode_bytes_per_token):
every step streams the full parameter set to produce ONE token per sequence.
Speculative decoding converts one target weight stream into up to k+1
accepted tokens — the draft (a much smaller model) decodes k tokens
autoregressively, then the target scores them all in ONE span forward whose
weight streaming costs the same as a single decode step. Greedy acceptance
makes the output EXACTLY the target's greedy decode (accept draft token i
iff it equals the target's argmax at that position; on the first mismatch
emit the target's token; on full acceptance emit the target's bonus k+1th
token) — pinned against ``decode.generate`` by tests/test_spec_decode.py,
the same parity bar every other inference path here meets.

TPU-first shape discipline (why this composes out of existing pieces):

- **Span scoring is the decode step's shape family**, not a fresh path:
  ``score_span`` runs ``decode._layer_decode`` with s_q = span length — the
  SAME position-masked cached attention and in-layer write-then-attend
  ordering the single-token step uses (s_q = 1 IS ``decode_step``).
- **Static shapes**: the target always scores k+1 rows; the draft feeds
  spans of length 1 or 2 (2 = the full-acceptance catch-up merged into the
  next round's first feed). jit caches one program per span length —
  three compiled shapes total, independent of acceptance behavior, which
  lives on the host as a tiny logits fetch per round.
- **Rejected rows need no rollback.** A rejected draft token leaves stale
  K/V above the accepted position; every later query's causal mask hides
  rows above its own position, and each row is rewritten by a
  write-then-attend pass before any query can attend it — the serving
  arena's pad-pollution argument, carried over verbatim (cursors only move
  forward through accepted positions).

The reference schedules serving pods; this is the latency optimization the
pods it places actually run.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .decode import (adjusted_logits, draft_rollout, init_kv_cache, prefill,
                     sampling_draft_rollout, score_span)
from .workload import ModelConfig, Params

# module-level jitted wrappers with cfg STATIC: jit's cache keys on the
# function identity + static args, so repeated speculative_generate calls
# (or several engines over the same configs) reuse the compiled programs
# instead of paying XLA again — the whole module exists to cut decode
# latency (ModelConfig is a frozen dataclass, hence hashable)
_span = jax.jit(score_span, static_argnames="cfg", donate_argnums=(1,))
_prefill = jax.jit(prefill, static_argnames="cfg", donate_argnums=(1,))


# decode.draft_rollout is the single definition of the draft phase (one
# ingest + lax.scan rollout, one host transfer); jitted here with cfg/k
# static so repeated calls reuse the compiled program
_draft = jax.jit(draft_rollout, static_argnames=("cfg", "k"),
                 donate_argnums=(1,))


def speculative_generate(target_params: Params, target_cfg: ModelConfig,
                         draft_params: Params, draft_cfg: ModelConfig,
                         prompt: jax.Array, steps: int,
                         k: int = 4) -> Tuple[np.ndarray, dict]:
    """Greedy speculative decoding for one sequence (prompt (1, s0)):
    generates ``steps + 1`` tokens (``decode.generate``'s contract) that
    are EXACTLY the target model's greedy continuation. Returns
    (tokens (1, steps+1), stats); stats carries the acceptance telemetry
    that decides whether the draft pays for itself — ``target_calls``
    (each streams the target weights once; plain decode makes
    ``plain_calls`` of them) and ``accept_rate``.

    Both models must share a vocabulary; the draft is typically much
    smaller (same tokenizer, fewer layers/width)."""
    if prompt.shape[0] != 1:
        raise ValueError("speculative_generate is single-sequence (b=1); "
                         "batched speculation belongs in the serve engine")
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if k < 1:
        raise ValueError("k must be >= 1")
    total = int(steps) + 1
    s0 = prompt.shape[1]
    max_seq = s0 + total + k + 2          # headroom for the last overshoot
    t_cache = init_kv_cache(target_cfg, 1, max_seq)
    d_cache = init_kv_cache(draft_cfg, 1, max_seq)

    t_logits, t_cache = _prefill(target_params, t_cache, prompt,
                                 cfg=target_cfg)
    _, d_cache = _prefill(draft_params, d_cache, prompt, cfg=draft_cfg)
    out = [int(jnp.argmax(t_logits[0, s0 - 1]))]

    # cursors: next write row of each cache. Invariant at every round
    # start: rows [0, t_pos) of the target cache and [0, d_pos) of the
    # draft cache hold the ACCEPTED stream (prompt + out, minus its last
    # t_pos-or-d_pos-relative suffix); out's last (t_pos - d_pos + 1)
    # tokens are exactly what the draft has not ingested yet.
    t_pos = d_pos = s0
    target_calls = 1                      # the prefill produced out[0]
    drafted = accepted = 0
    while len(out) < total:
        # 1) draft phase: ingest the catch-up suffix (ends with the last
        #    emitted token), then propose k tokens autoregressively. The
        #    local cursor walks every fed row; d_pos itself advances only
        #    through rows that turn out VALID (catch-up + accepted
        #    proposals) — rejected rows are re-written next round.
        feed = out[len(out) - (t_pos - d_pos) - 1:]
        catch_up = len(feed)
        span_dev, d_cache = _draft(draft_params, d_cache,
                                   jnp.asarray([feed], dtype=jnp.int32),
                                   jnp.int32(d_pos), cfg=draft_cfg, k=k)
        span = [int(t) for t in np.asarray(span_dev)[0]]  # ONE host transfer
        drafted += k
        # 2) ONE target stream scores [last_emitted] + span (k+1 rows) at
        #    positions t_pos..t_pos+k; row i's argmax answers position
        #    t_pos+i+1 — compare row i to span[i], row k is the bonus
        scored = jnp.asarray([[out[-1]] + span], dtype=jnp.int32)
        t_logits, t_cache = _span(target_params, t_cache, scored,
                                  jnp.int32(t_pos), cfg=target_cfg)
        target_calls += 1
        t_arg = np.asarray(jnp.argmax(t_logits[0], axis=-1))   # (k+1,)
        n_ok = 0
        while n_ok < k and span[n_ok] == int(t_arg[n_ok]):
            n_ok += 1
        accepted += n_ok
        if n_ok == k:
            out.extend(span)
            out.append(int(t_arg[k]))     # bonus: target's own next token
        else:
            out.extend(span[:n_ok])
            out.append(int(t_arg[n_ok]))  # the target's correction
        # accepted rows now reach t_pos + n_ok (inputs [out[-...], span[:n_ok]]
        # were all fed); the newly emitted token sits one past them, unfed
        t_pos += n_ok + 1
        # draft's valid rows: the catch-up plus accepted proposals it fed
        # (it never fed span[k-1], hence the min with k-1)
        d_pos += catch_up + min(n_ok, k - 1)
    tokens = np.asarray([out[:total]], dtype=np.int32)
    stats = {"target_calls": target_calls,
             "plain_calls": total,
             "drafted": drafted,
             "accepted": accepted,
             "accept_rate": accepted / max(drafted, 1)}
    return tokens, stats


# -- distribution-preserving speculative SAMPLING -----------------------------

# Key-stream salts: a position's proposal draw, its acceptance uniform, and
# its residual draw must be three independent streams (the acceptance test
# may not reuse the randomness that generated the proposal). Positions are
# < 2^29 in any realistic context, so the salted ranges cannot collide.
# canonical definition lives with sample_position_keyed (decode.py); the
# serve engine's batched sampled speculation shares the same streams
from .decode import ACCEPT_SALT as _ACCEPT_SALT          # noqa: E402
from .decode import RESIDUAL_SALT as _RESIDUAL_SALT      # noqa: E402


def residual_distribution(p: np.ndarray, q: np.ndarray) -> np.ndarray:
    """The rejection-path distribution norm(max(q - p, 0)): what makes
    speculative sampling EXACTLY distribution-preserving —
    P(emit y) = p(y)·min(1, q(y)/p(y)) + P(reject)·residual(y) = q(y)
    (tests/test_spec_decode.py verifies that identity numerically against
    THIS function). Degenerate guard: when q ≤ p everywhere (q == p),
    rejection is impossible, but a caller that lands here anyway gets q."""
    r = np.maximum(np.asarray(q, np.float64) - np.asarray(p, np.float64), 0)
    s = float(r.sum())
    if s <= 1e-12:
        qq = np.asarray(q, np.float64)
        return qq / max(float(qq.sum()), 1e-30)
    return r / s


def accept_span(span, p_mat: np.ndarray, q_mat: np.ndarray,
                acc_u: np.ndarray, res_u: np.ndarray
                ) -> "Tuple[int, Optional[int]]":
    """THE acceptance/residual decision over one proposal span: proposals
    ``span`` (k,), draft distributions ``p_mat`` (k, V), target
    distributions ``q_mat`` (k, V) — float64, computed host-side from the
    adjusted logits — and the round's accept/residual uniforms.
    Returns (n_accepted, rejection_token-or-None). ONE definition shared
    by solo ``speculative_sample`` and the engine's batched sampled tick:
    the engine-vs-solo parity law depends on this math never drifting."""
    k = len(span)
    n_ok = 0
    while n_ok < k:
        x = int(span[n_ok])
        ratio = q_mat[n_ok, x] / max(p_mat[n_ok, x], 1e-30)
        if float(acc_u[n_ok]) < min(1.0, ratio):
            n_ok += 1
            continue
        res = residual_distribution(p_mat[n_ok], q_mat[n_ok])
        return n_ok, int(np.searchsorted(
            np.cumsum(res), float(res_u[n_ok]),
            side="right").clip(0, len(res) - 1))
    return k, None


def probs_from_adjusted(adj: np.ndarray) -> np.ndarray:
    """Adjusted logits (…, V) → float64 distributions, the EXACT host
    softmax both speculation paths divide in (a float32 device softmax
    would shift min(1, q/p) by ~1e-7 — enough to flip a token on an
    unlucky uniform and break engine-vs-solo parity)."""
    a = np.asarray(adj, np.float64)
    q = np.exp(a - a.max(axis=-1, keepdims=True))
    return q / q.sum(axis=-1, keepdims=True)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "temperature", "top_k", "top_p"),
                   donate_argnums=(1,))
def _span_adjusted(params, cache, scored, pos, cfg, temperature, top_k,
                   top_p):
    """Verify phase for sampling: ONE target stream over the k+1 span rows,
    returning the ADJUSTED logits (the acceptance distributions) and the
    cache (donated, like every sibling wrapper — the arena updates in
    place instead of copying a full-context cache per round)."""
    logits, cache = score_span(params, cache, scored, pos, cfg)
    adj = adjusted_logits(logits[0], temperature, top_k, top_p)
    return adj, cache


@functools.partial(jax.jit, static_argnames=("k",))
def _round_uniforms(key, t_pos, k):
    """All of one round's acceptance + residual uniforms in ONE dispatch
    (per-token scalar fetches would add up to 2k host syncs to the
    latency-critical loop). Value-identical to drawing
    uniform(fold_in(key, SALT + position)) one at a time."""
    pos = t_pos + 1 + jnp.arange(k)
    au = jax.vmap(lambda p: jax.random.uniform(
        jax.random.fold_in(key, _ACCEPT_SALT + p)))(pos)
    ru = jax.vmap(lambda p: jax.random.uniform(
        jax.random.fold_in(key, _RESIDUAL_SALT + p)))(pos)
    return au, ru


_sampling_draft = jax.jit(
    sampling_draft_rollout,
    static_argnames=("cfg", "k", "temperature", "top_k", "top_p"),
    donate_argnums=(1,))


def speculative_sample(target_params: Params, target_cfg: ModelConfig,
                       draft_params: Params, draft_cfg: ModelConfig,
                       prompt: jax.Array, steps: int, key: jax.Array,
                       k: int = 4, temperature: float = 1.0,
                       top_k: int = 0, top_p: float = 1.0
                       ) -> Tuple[np.ndarray, dict]:
    """Distribution-preserving speculative SAMPLING (the standard
    accept-with-min(1, q/p), resample-from-residual algorithm): generates
    ``steps + 1`` tokens whose distribution is EXACTLY the target's
    adjusted sampling distribution — the draft can only change speed,
    never statistics. Greedy speculation (`speculative_generate`) is the
    temperature→0 special case and stays its own path (argmax comparison,
    no keys).

    Randomness is position-keyed (`decode.sample_position_keyed` is the
    canonical definition): the token occupying absolute row ``p`` draws
    ``fold_in(key, p)``; acceptance uniforms and residual draws use salted
    streams of the same position. Consequences worth the discipline:
    a re-proposed position after a rejection re-draws the SAME key (no
    key double-spend skew), and a perfect draft (draft == target) accepts
    everything and reproduces ``sample_position_keyed``'s stream
    token-for-token — the deterministic contract the tests pin, standing
    in for a statistical test of the acceptance math (which is verified
    as an exact numpy identity separately).
    """
    if prompt.shape[0] != 1:
        raise ValueError("speculative_sample is single-sequence (b=1)")
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if k < 1:
        raise ValueError("k must be >= 1")
    if temperature <= 0.0:
        raise ValueError("temperature must be > 0 (use "
                         "speculative_generate for greedy)")
    total = int(steps) + 1
    s0 = prompt.shape[1]
    max_seq = s0 + total + k + 2
    t_cache = init_kv_cache(target_cfg, 1, max_seq)
    d_cache = init_kv_cache(draft_cfg, 1, max_seq)

    t_logits, t_cache = _prefill(target_params, t_cache, prompt,
                                 cfg=target_cfg)
    _, d_cache = _prefill(draft_params, d_cache, prompt, cfg=draft_cfg)
    first_adj = adjusted_logits(t_logits[:, s0 - 1], temperature, top_k,
                                top_p)
    out = [int(jax.random.categorical(jax.random.fold_in(key, s0),
                                      first_adj, axis=-1)[0])]

    t_pos = d_pos = s0
    target_calls = 1
    drafted = accepted = 0
    while len(out) < total:
        feed = out[len(out) - (t_pos - d_pos) - 1:]
        catch_up = len(feed)
        span_dev, probs_dev, d_cache = _sampling_draft(
            draft_params, d_cache, jnp.asarray([feed], dtype=jnp.int32),
            jnp.int32(d_pos), cfg=draft_cfg, k=k, key=key,
            temperature=temperature, top_k=top_k, top_p=top_p)
        span = [int(t) for t in np.asarray(span_dev)[0]]
        p_mat = np.asarray(probs_dev[0], np.float64)        # (k, vocab)
        drafted += k
        scored = jnp.asarray([[out[-1]] + span], dtype=jnp.int32)
        adj_dev, t_cache = _span_adjusted(
            target_params, t_cache, scored, jnp.int32(t_pos),
            cfg=target_cfg, temperature=temperature, top_k=top_k,
            top_p=top_p)
        target_calls += 1
        adj = np.asarray(adj_dev, np.float64)               # (k+1, vocab)
        q_mat = probs_from_adjusted(adj)
        acc_u, res_u = (np.asarray(a) for a in _round_uniforms(
            key, jnp.int32(t_pos), k))
        n_ok, emitted_rejection = accept_span(span, p_mat, q_mat[:k],
                                              acc_u, res_u)
        accepted += n_ok
        if emitted_rejection is None:
            # full acceptance: the bonus token at row t_pos+k+1 draws its
            # own position key from the target's adjusted distribution —
            # exactly what sample_position_keyed would do there
            bonus = int(jax.random.categorical(
                jax.random.fold_in(key, t_pos + k + 1),
                jnp.asarray(adj[k])[None, :], axis=-1)[0])
            out.extend(span)
            out.append(bonus)
        else:
            out.extend(span[:n_ok])
            out.append(emitted_rejection)
        t_pos += n_ok + 1
        d_pos += catch_up + min(n_ok, k - 1)
    tokens = np.asarray([out[:total]], dtype=np.int32)
    stats = {"target_calls": target_calls,
             "plain_calls": total,
             "drafted": drafted,
             "accepted": accepted,
             "accept_rate": accepted / max(drafted, 1)}
    return tokens, stats
