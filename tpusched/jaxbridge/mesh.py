"""Slice assignment → jax.sharding.Mesh.

The scheduler reserves chip coordinates for a gang (topologymatch plugin
annotations); this module turns that assignment into the device mesh a JAX
job would build on those hosts. Off-cluster (tests, dry-runs) the same
factorization runs over virtual CPU devices.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def factor_mesh(n_devices: int, max_tp: int = 4) -> Tuple[int, int]:
    """(dp, tp) with tp the largest power-of-two divisor of n ≤ max_tp — tp
    rides ICI within a host (4 chips/host on v5e/v5p), dp spans hosts.
    Power-of-two keeps tp dividing the model dims (all sized in multiples
    of 4)."""
    tp = max_tp
    while tp > 1 and (n_devices % tp or tp & (tp - 1)):
        tp -= 1
    return n_devices // tp, tp


def build_mesh(n_devices: int, devices: Optional[Sequence] = None,
               axis_names: Tuple[str, str] = ("dp", "tp")):
    """A dp×tp Mesh over the first n devices (CPU-virtual or TPU)."""
    dp, tp = factor_mesh(n_devices)
    return build_named_mesh({axis_names[0]: dp, axis_names[1]: tp}, devices)


def mesh_from_slice_shape(shape: Tuple[int, ...], devices: Optional[Sequence] = None):
    """Mesh matching a scheduled ICI slice shape, e.g. (4,4,4) → 64 chips
    arranged dp×tp with tp within hosts."""
    n = 1
    for d in shape:
        n *= d
    return build_mesh(n, devices)


def build_named_mesh(axis_sizes: "dict[str, int]",
                     devices: Optional[Sequence] = None):
    """Arbitrary named mesh, e.g. {"dp": 2, "sp": 2, "tp": 2} or a
    multi-slice {"slice": 4, "dp": 4, "tp": 4} — `slice` rides DCN between
    ICI tori, everything else rides ICI."""
    import jax
    from jax.sharding import Mesh
    n = 1
    for s in axis_sizes.values():
        n *= s
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    arr = np.array(devs[:n]).reshape(tuple(axis_sizes.values()))
    return Mesh(arr, tuple(axis_sizes))


def slice_assignment(pods) -> "list[tuple[tuple[int, ...], str]]":
    """Decode the scheduler's slice placement from bound gang pods: a sorted
    list of (chip_coordinate, node_name) from the TopologyMatch coord
    annotations — the on-host runtime's source of truth for building the
    physical device mesh."""
    from ..api.topology import parse_coord
    from ..plugins.topologymatch import COORD_ANNOTATION
    out = []
    for p in pods:
        ann = p.meta.annotations.get(COORD_ANNOTATION)
        if ann and p.spec.node_name:
            out.append((parse_coord(ann), p.spec.node_name))
    return sorted(out)
