"""Slice assignment → jax.sharding.Mesh.

The scheduler reserves chip coordinates for a gang (topologymatch plugin
annotations); this module turns that assignment into the device mesh a JAX
job would build on those hosts. Off-cluster (tests, dry-runs) the same
factorization runs over virtual CPU devices.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def factor_mesh(n_devices: int, max_tp: int = 4) -> Tuple[int, int]:
    """(dp, tp) with tp the largest power-of-two divisor of n ≤ max_tp — tp
    rides ICI within a host (4 chips/host on v5e/v5p), dp spans hosts.
    Power-of-two keeps tp dividing the model dims (all sized in multiples
    of 4)."""
    tp = max_tp
    while tp > 1 and (n_devices % tp or tp & (tp - 1)):
        tp -= 1
    return n_devices // tp, tp


def build_mesh(n_devices: int, devices: Optional[Sequence] = None,
               axis_names: Tuple[str, str] = ("dp", "tp")):
    """A dp×tp Mesh over the first n devices (CPU-virtual or TPU)."""
    import jax
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n_devices:
        raise ValueError(f"need {n_devices} devices, have {len(devs)}")
    dp, tp = factor_mesh(n_devices)
    arr = np.array(devs[:n_devices]).reshape(dp, tp)
    from jax.sharding import Mesh
    return Mesh(arr, axis_names)


def mesh_from_slice_shape(shape: Tuple[int, ...], devices: Optional[Sequence] = None):
    """Mesh matching a scheduled ICI slice shape, e.g. (4,4,4) → 64 chips
    arranged dp×tp with tp within hosts."""
    n = 1
    for d in shape:
        n *= d
    return build_mesh(n, devices)
